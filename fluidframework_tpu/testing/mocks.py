"""Mock runtimes: multi-client DDS testing with no server.

The reference's key test mechanism (SURVEY.md §4: MockContainerRuntimeFactory
+ MockFluidDataStoreRuntime; upstream paths UNVERIFIED — empty reference
mount): the factory holds submitted ops un-sequenced; ``process_all_messages``
stamps them through the in-proc Sequencer and delivers to every client replica
in total order, so N replicas of a DDS converge deterministically and tests
can control interleavings (deliver some messages, edit concurrently, deliver
the rest).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Deque, Dict, List

from ..dds.shared_object import SharedObject
from ..protocol.messages import MessageType, RawOperation, SequencedMessage
from ..protocol.sequencer import Sequencer


class _MockDeltaConnection:
    """The per-(client, channel) submit handle given to a DDS."""

    def __init__(self, runtime: "MockClientRuntime", channel_id: str) -> None:
        self._runtime = runtime
        self._channel_id = channel_id

    def submit(self, contents, ref_seq=None) -> int:
        return self._runtime.submit_channel_op(self._channel_id, contents,
                                               ref_seq)

    @property
    def ref_seq(self):
        return self._runtime.ref_seq

    @property
    def min_seq(self):
        return getattr(self._runtime, "min_seq", 0)


class MockClientRuntime:
    """One simulated client: routes channel ops out to the factory and
    inbound sequenced messages to its attached channel replicas."""

    def __init__(self, factory: "MockContainerRuntimeFactory", client_id: str):
        self.factory = factory
        self.client_id = client_id
        self.ref_seq = factory.sequencer.seq  # last processed seq
        self._client_seq = 0
        self.channels: Dict[str, SharedObject] = {}

    def attach(self, dds: SharedObject) -> SharedObject:
        self.channels[dds.id] = dds
        dds.connect(_MockDeltaConnection(self, dds.id), self.client_id)
        return dds

    def submit_channel_op(self, channel_id: str, contents,
                          ref_seq=None) -> int:
        self._client_seq += 1
        self.factory.enqueue(
            RawOperation(
                client_id=self.client_id,
                client_seq=self._client_seq,
                ref_seq=self.ref_seq if ref_seq is None else ref_seq,
                type=MessageType.OP,
                contents={"address": channel_id, "contents": contents},
            )
        )
        return self._client_seq

    def _advance_channels(self, msg: SequencedMessage, skip_address=None) -> None:
        """Every container message advances every channel's window (seq /
        min_seq for zamboni), whether or not the op was addressed to it."""
        for address, dds in self.channels.items():
            if address == skip_address:
                continue
            advance = getattr(dds, "advance", None)
            if advance:
                advance(msg.seq, msg.min_seq)

    def deliver(self, msg: SequencedMessage) -> None:
        self.ref_seq = msg.seq
        if msg.type is not MessageType.OP:
            self._advance_channels(msg)
            return
        envelope = msg.contents
        dds = self.channels.get(envelope["address"])
        if dds is None:
            self._advance_channels(msg)
            return
        dds.process(
            dataclasses.replace(msg, contents=envelope["contents"]),
            local=(msg.client_id == self.client_id),
        )
        self._advance_channels(msg, skip_address=envelope["address"])


class MockContainerRuntimeFactory:
    """Holds pending raw ops; sequencing happens on demand."""

    def __init__(self) -> None:
        self.sequencer = Sequencer()
        self.clients: List[MockClientRuntime] = []
        self._pending_raw: Deque[RawOperation] = collections.deque()
        self._delivery_queue: Deque[SequencedMessage] = collections.deque()
        self.sequencer.subscribe(self._delivery_queue.append)

    def create_client(self, client_id: str) -> MockClientRuntime:
        self.sequencer.connect(client_id)
        runtime = MockClientRuntime(self, client_id)
        self.clients.append(runtime)
        self._drain_delivery()  # deliver the JOIN immediately
        return runtime

    def enqueue(self, op: RawOperation) -> None:
        self._pending_raw.append(op)

    @property
    def pending_count(self) -> int:
        return len(self._pending_raw)

    def process_some_messages(self, count: int) -> None:
        for _ in range(count):
            if not self._pending_raw:
                break
            op = self._pending_raw.popleft()
            self.sequencer.submit(op)
            self._drain_delivery()

    def process_all_messages(self) -> None:
        self.process_some_messages(len(self._pending_raw))

    def advance_min_seq(self) -> None:
        """Report every client as fully caught-up, advancing the MSN to the
        head — lets tests force zamboni/window eviction."""
        for client in self.clients:
            self.sequencer.update_ref_seq(client.client_id, self.sequencer.seq)
        self.sequencer.tick()  # propagate the new MSN
        self._drain_delivery()

    def _drain_delivery(self) -> None:
        while self._delivery_queue:
            msg = self._delivery_queue.popleft()
            for client in self.clients:
                client.deliver(msg)


def channel_log(factory: MockContainerRuntimeFactory, address: str,
                min_seq_exclusive: int = 0) -> list:
    """Extract one channel's sequenced ops from the durable log, unwrapped
    from their envelopes — the exact stream a catch-up replay (CPU oracle or
    device kernel) folds over."""
    out = []
    for msg in factory.sequencer.log:
        if msg.type is not MessageType.OP or msg.seq <= min_seq_exclusive:
            continue
        envelope = msg.contents
        if envelope.get("address") != address:
            continue
        out.append(dataclasses.replace(msg, contents=envelope["contents"]))
    return out
