"""faultline: deterministic fault injection across the serving stack.

The blueprint's core contract (PAPER.md §0) — ops are appended durably,
then broadcast; the log, not the live push, is the guarantee — is only as
real as the failure modes that have actually been exercised.  This module
is the substrate: a seeded, plan-driven injector whose hooks are threaded
through the REAL seams of the stack, so any failure scenario is a pure
function of ``(seed, plan)`` and replays bit-identically:

- ``OpLog.append``/``flush``        — fail, torn partial write, skipped
  fsync (``oplog.append`` / ``oplog.flush``);
- ``FileSummaryStorage`` store/read — fail, torn pre-rename tmp write,
  stale ``latest`` serve (``storage.store`` / ``storage.read``);
- ``_RpcClient`` send/recv          — fail, drop, delay (one-frame
  reorder), duplicate delivery, disconnect (``rpc.send`` / ``rpc.recv``);
- ``_ClientSession.write_frame``    — stall → broadcaster demotion
  (``session.write``);
- ``OrderingServer`` catchup fold   — fail, injected fold delay on the
  server's injected clock (``catchup.fail`` / ``catchup.slow``);
- ``ShardedOrderingService``        — shard kill at scheduled virtual
  ticks (``shard.kill``, driven by :meth:`FaultInjector.due`).

Matching is by **occurrence count** at a site (optionally scoped to one
document), never by wall clock: the Nth append is the Nth append on every
replay.  Every fire is counted in a thread-safe ``site:kind`` counter set
— the replay-identity surface the chaos oracle asserts on — and the plan
knows which of its points never fired (a scenario that claims coverage it
did not exercise fails loudly).

The injector raises :class:`FaultError` (an ``OSError``) for hard
failures, so every existing transient-transport path (the runtime
wire-drain's ConnectionError/OSError handling, ``RetryPolicy``'s default
retry set) treats injected faults exactly like real ones.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import Dict, List, Optional, Tuple

from ..utils.telemetry import LockedCounterSet


class FaultError(OSError):
    """An injected failure.  Subclasses OSError so the stack's existing
    transient-failure handling (wire-drain requeue, RetryPolicy's default
    ``retry_on``) takes it without special cases — the injected world must
    exercise the REAL recovery paths, not bespoke ones."""

    def __init__(self, site: str, kind: str, detail: str = "") -> None:
        super().__init__(
            f"injected fault at {site} ({kind})"
            + (f": {detail}" if detail else "")
        )
        self.site = site
        self.kind = kind


#: site -> kinds the seam at that site implements.  A plan naming an
#: unknown (site, kind) is a bug in the plan, not a silently-dead point.
SITES: Dict[str, Tuple[str, ...]] = {
    "oplog.append": ("fail", "torn"),
    "oplog.flush": ("fail", "skip_fsync"),
    "storage.store": ("fail", "torn"),
    "storage.read": ("fail", "stale"),
    "rpc.send": ("fail", "drop", "disconnect"),
    "rpc.recv": ("drop", "duplicate", "delay", "disconnect"),
    "session.write": ("stall",),
    "shard.kill": ("kill",),
    "client.stall": ("stall",),
    # fluidproc (out-of-process tier): the front door executes these
    # against REAL shard processes at scheduled virtual ticks —
    # ``proc.kill`` is SIGKILL (no drain, no seal; the per-shard log's
    # torn tail and the adoption path are the recovery under test),
    # ``proc.hang`` is SIGSTOP (the process is alive but silent; only
    # heartbeat-based death detection can notice, and the front door
    # SIGKILLs it before re-owning its documents — see SEMANTICS.md
    # "Deployment & migration").
    "proc.kill": ("kill",),
    "proc.hang": ("hang",),
    # ``replica.kill`` (round 18) SIGKILLs a front-door REPLICA — the
    # door itself, not a shard — at a scheduled tick: every client
    # socket it held drops with nothing flushed, shards keep running,
    # and the swarm's adapter must fail over to a surviving door.  The
    # swarm executes this (the replica fleet is harness topology the
    # primary's tick driver never sees).
    "replica.kill": ("kill",),
    # Catch-up fold tier (round 15, the storm subsystem): fired by the
    # server's fold lane AFTER admission — ``catchup.fail`` raises out
    # of the fold (the single-flight finally-abandon, the admission
    # release, and the caller's retry policy are the recovery under
    # test), ``catchup.slow`` injects a fold delay of ``arg`` seconds
    # on the server's injected clock (virtual under a VirtualClock), so
    # the measured fold cost — and the load-derived shed pacing it
    # feeds — slows deterministically.
    "catchup.fail": ("fail",),
    "catchup.slow": ("delay",),
    # Streaming fold tier (round 16): ``stream.stall`` makes the
    # streaming service skip a whole poll round (the dirty docs stay
    # pending and the NEXT catch-up takes the ordinary cold-fold path —
    # the degradation under test), ``stream.crash`` raises out of the
    # per-doc fold mid-round (the service must swallow it, count it,
    # and leave the doc foldable later).  Log truncation crash points
    # mirror PR 12's migration style: ``oplog.truncate.seal`` fires
    # BEFORE the truncation marker is durable (a crash here leaves the
    # log byte-identical), ``oplog.truncate.drop`` fires AFTER the
    # marker is durable but BEFORE physical compaction (a crash here
    # must reopen to the same floor with the old bytes still present).
    "stream.stall": ("stall",),
    "stream.crash": ("fail",),
    "oplog.truncate.seal": ("fail",),
    "oplog.truncate.drop": ("fail",),
}

#: sites matched by occurrence count (the seam calls ``fire``); the rest
#: are schedule-driven (the harness calls ``due`` with the virtual tick).
SCHEDULED_SITES = ("shard.kill", "client.stall", "proc.kill", "proc.hang",
                   "replica.kill")


@dataclasses.dataclass(frozen=True)
class FaultPoint:
    """One scheduled fault.

    ``at`` is the 1-based occurrence index at the site (scoped to ``doc``
    when set) for seam sites, or the virtual tick/step for scheduled
    sites (``shard.kill``, ``client.stall``).  ``count`` fires the fault
    for that many consecutive occurrences (seam sites only) — e.g. a
    3-append outage.  ``arg`` is kind-specific: the torn-write fraction,
    the stall length in steps."""

    site: str
    kind: str
    at: int = 1
    count: int = 1
    doc: Optional[str] = None
    shard: Optional[str] = None
    arg: float = 0.0

    def validate(self) -> None:
        kinds = SITES.get(self.site)
        if kinds is None:
            raise ValueError(f"unknown fault site {self.site!r}")
        if self.kind not in kinds:
            raise ValueError(
                f"site {self.site!r} does not implement kind "
                f"{self.kind!r} (has {kinds})")
        if self.at < 0 or self.count < 1:
            raise ValueError(f"bad at/count on {self}")

    def label(self) -> str:
        scope = f"@{self.doc}" if self.doc else ""
        return f"{self.site}:{self.kind}{scope}#{self.at}x{self.count}"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A replayable fault schedule: ``(seed, points)`` fully determines
    every injected fault.  ``seed`` seeds nothing inside the injector —
    it names the scenario (the chaos harness derives its traffic schedule
    from the same seed) and rides the bench/telemetry output."""

    seed: int = 0
    points: Tuple[FaultPoint, ...] = ()

    def __post_init__(self) -> None:
        for p in self.points:
            p.validate()

    @staticmethod
    def generate(seed: int, docs: List[str], steps: int,
                 intensity: int = 2) -> "FaultPlan":
        """Seeded scenario generator covering every required fault class
        (ROADMAP's bursty-herd/laggard/failover scenario axis): oplog
        append failures, torn appends, a mid-run shard kill, stalled
        (laggard) clients, and stale summary reads — ``intensity`` scales
        the per-class point count.  Deterministic: same (seed, docs,
        steps) → same plan."""
        rng = random.Random(seed * 9176 + len(docs))
        points: List[FaultPoint] = []
        for _ in range(intensity):
            # Transient durable-append outages on specific documents: the
            # Nth append to that doc fails for 1-2 consecutive attempts
            # (strictly fewer than RetryPolicy.max_attempts, so inline
            # retries absorb the outage without reshaping the schedule).
            points.append(FaultPoint(
                "oplog.append", "fail", doc=rng.choice(docs),
                at=rng.randint(2, 6), count=rng.randint(1, 2)))
            # Torn partial writes (crash-shaped: bytes hit the disk, the
            # record does not) on the shared log.
            points.append(FaultPoint(
                "oplog.append", "torn", at=rng.randint(8, 12 + steps // 8),
                arg=round(rng.uniform(0.2, 0.8), 3)))
            # A laggard: one client stops draining for `arg` steps, then
            # resumes through gap repair.
            points.append(FaultPoint(
                "client.stall", "stall", doc=rng.choice(docs),
                at=rng.randint(steps // 4, steps // 2),
                arg=float(rng.randint(4, 10))))
        # Stale summary serves across one document's cold loads.  The
        # window spans the harness's whole resolve sequence (setup
        # resolve, the pre-late-join summarizer resolve, the late join
        # itself) so the LATE JOIN — which loads after a newer summary
        # was uploaded mid-run — really gets served the parent and
        # replays the longer tail; a single at=1 point would fire
        # vacuously at setup when only the attach summary exists.
        points.append(FaultPoint(
            "storage.read", "stale", doc=rng.choice(docs), at=1,
            count=3))
        # THE failover: one shard dies mid-run — pinned to a document so
        # the victim (that doc's current owner under rendezvous routing)
        # always holds live orderers worth failing over.
        points.append(FaultPoint(
            "shard.kill", "kill", doc=rng.choice(docs),
            at=rng.randint(steps // 3, 2 * steps // 3)))
        return FaultPlan(seed=seed, points=tuple(points))


class FaultInjector:
    """Threads a :class:`FaultPlan` through the stack's seams.

    Thread-safe: seams fire from client threads, the TCP reader thread,
    and server executor threads concurrently.  All state is occurrence
    counters — no wall clock, no PRNG — so a replay of the same driving
    schedule consults the same counters in the same order.
    """

    def __init__(self, plan: Optional[FaultPlan] = None) -> None:
        self.plan = plan if plan is not None else FaultPlan()
        self._lock = threading.Lock()
        #: occurrences per site and per (site, doc) — the matching keys
        self._counts: Dict[Tuple[str, Optional[str]], int] = {}  # guarded-by: _lock
        #: per-point fire tally (index into plan.points)  # guarded-by: _lock
        self._fired: Dict[int, int] = {}
        #: ``site:kind`` observation counters — the replay-identity
        #: surface (asserted identical across replays of one (seed, plan))
        self.observed = LockedCounterSet()

    # -- seam API --------------------------------------------------------------

    def fire(self, site: str, doc: Optional[str] = None,
             shard: Optional[str] = None) -> Optional[FaultPoint]:
        """One occurrence at ``site``: returns the matching plan point
        (the seam then implements the fault) or None.  At most one point
        fires per occurrence; a point whose start occurrence was claimed
        by an earlier-listed point fires on the NEXT eligible occurrences
        instead — every plan point eventually fires (given enough
        traffic), which is what lets the oracle assert full coverage."""
        with self._lock:
            n_global = self._counts[(site, None)] = \
                self._counts.get((site, None), 0) + 1
            n_doc = None
            if doc is not None:
                n_doc = self._counts[(site, doc)] = \
                    self._counts.get((site, doc), 0) + 1
            for idx, p in enumerate(self.plan.points):
                if p.site != site or p.site in SCHEDULED_SITES:
                    continue
                if p.doc is not None and p.doc != doc:
                    continue
                if p.shard is not None and p.shard != shard:
                    continue
                n = n_global if p.doc is None else n_doc
                if n is None or n < p.at:
                    continue
                if self._fired.get(idx, 0) >= p.count:
                    continue
                self._fired[idx] = self._fired.get(idx, 0) + 1
                self.observed.bump(f"{site}:{p.kind}")
                return p
        return None

    def due(self, site: str, tick: int) -> List[FaultPoint]:
        """Scheduled sites (``shard.kill``, ``client.stall``): the points
        of ``site`` whose tick has arrived and that have not fired yet.
        The harness drives this once per step with its own step index."""
        out: List[FaultPoint] = []
        with self._lock:
            for idx, p in enumerate(self.plan.points):
                if p.site != site or p.at > tick:
                    continue
                if self._fired.get(idx):
                    continue
                self._fired[idx] = 1
                self.observed.bump(f"{site}:{p.kind}")
                out.append(p)
        return out

    def mark_unfired(self, point: FaultPoint) -> None:
        """A scheduled point ``due()`` handed out could NOT be executed
        (e.g. its kill victim is the last live shard): roll back its
        fired mark and observation count so ``unfired()`` reports it —
        the coverage oracle must never claim coverage for a fault that
        did not happen."""
        with self._lock:
            for idx, p in enumerate(self.plan.points):
                if p == point and self._fired.get(idx):
                    self._fired[idx] = 0
                    self.observed.bump(f"{p.site}:{p.kind}", -1)
                    return

    # -- oracle surface --------------------------------------------------------

    def unfired(self) -> List[FaultPoint]:
        """Plan points that never triggered — a chaos run claiming this
        plan's coverage must end with an empty list, or the scenario did
        not exercise what it says it did."""
        with self._lock:
            return [p for idx, p in enumerate(self.plan.points)
                    if not self._fired.get(idx)]

    def snapshot(self) -> Dict[str, int]:
        """``site:kind`` observation counts — byte-comparable across
        replays of the same (seed, plan)."""
        return self.observed.snapshot()
