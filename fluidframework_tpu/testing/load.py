"""Load/stress harness: many puppet clients with fault injection.

Capability-equivalent of the reference's ``test-service-load`` (SURVEY.md
§4: many puppet clients against a real service, configurable op rates,
random disconnects; upstream paths UNVERIFIED — empty reference mount).

Drives the REAL stack (Loader → driver → ordering service), not the mocks:
each puppet runs a seeded random schedule of edits, syncs, disconnects/
reconnects, stash/rehydrate cycles, and late joins; at the end everything
synchronizes and the harness asserts byte-identical summaries across every
surviving client — the convergence oracle under load."""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional

from ..drivers import LocalDocumentServiceFactory
from ..loader import Loader
from ..service import LocalOrderingService


@dataclasses.dataclass
class LoadSpec:
    seed: int = 0
    clients: int = 4
    #: fault injection: NACK every Nth submit service-side (0 = off); the
    #: nacked ops must still converge (the runtime requeues + resends)
    nack_every: int = 0
    steps: int = 200               # total scheduled actions
    edit_weight: float = 0.70
    sync_weight: float = 0.15
    disconnect_weight: float = 0.05
    stash_weight: float = 0.03     # crash + rehydrate as a new session
    late_join_weight: float = 0.02
    max_clients: int = 8


class VirtualClock:
    """Deterministic time source for the simulated stack.

    Each read advances by a fixed tick, so DeltaManager retryAfter holds
    (``clock() + retry_after`` vs later reads) resolve after the same
    number of scheduler decisions on every run, regardless of host speed
    or wall-clock start — the load run is fully replayable from its seed.
    """

    def __init__(self, tick: float = 0.001) -> None:
        self.now = 0.0
        self.tick = tick

    def __call__(self) -> float:
        self.now += self.tick
        return self.now

    def sleep(self, seconds: float) -> None:
        """Virtual sleep: advance time without blocking — the backoff
        actuator RetryPolicy uses, so a retry schedule is a pure function
        of the run's decision sequence (the DeltaManager picks this up
        automatically when its injected clock has a ``sleep``)."""
        self.now += max(0.0, seconds)


@dataclasses.dataclass
class LoadResult:
    steps: int
    edits: int
    disconnects: int
    rehydrates: int
    late_joins: int
    final_clients: int
    sequenced_ops: int
    summary_digest: str
    nacks_issued: int = 0


def run_load(spec: LoadSpec) -> LoadResult:
    rng = random.Random(spec.seed)
    throttle = None
    if spec.nack_every:
        counter = {"n": 0}

        def throttle(_client_id):
            counter["n"] += 1
            if counter["n"] % spec.nack_every == 0:
                return 0.0  # immediate-retry nack (fault injection)
            return None

    service = LocalOrderingService(throttle=throttle)
    # Wall-clock-free: every DeltaManager in the run shares one virtual
    # clock, so nack holds resolve identically on every replay of a seed.
    loader = Loader(LocalDocumentServiceFactory(service),
                    clock=VirtualClock())

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("sequence-tpu", "text")
        ds.create_channel("map-tpu", "kv")
        ds.create_channel("counter-tpu", "count")

    containers: Dict[str, object] = {}
    offline: Dict[str, bool] = {}
    next_id = 0

    def new_client(pending_state=None):
        nonlocal next_id
        next_id += 1
        cid = f"load-{spec.seed}-{next_id}"
        if not containers and pending_state is None:
            c = loader.create("load-doc", cid, build)
        else:
            c = loader.resolve("load-doc", cid, pending_state=pending_state)
        containers[cid] = c
        offline[cid] = False
        return cid

    for _ in range(spec.clients):
        new_client()

    edits = disconnects = rehydrates = late_joins = 0

    def do_edit(container):
        nonlocal edits
        ds = container.runtime.get_datastore("ds")
        choice = rng.random()
        if choice < 0.6:
            text = ds.get_channel("text")
            length = len(text.text)
            if length < 4 or rng.random() < 0.7:
                text.insert_text(rng.randint(0, length),
                                 rng.choice("abcdef") * rng.randint(1, 4))
            else:
                start = rng.randint(0, length - 2)
                text.remove_range(start, min(length, start + 3))
        elif choice < 0.9:
            ds.get_channel("kv").set(f"k{rng.randint(0, 20)}",
                                     rng.randint(0, 999))
        else:
            ds.get_channel("count").increment(rng.choice([1, -1, 5]))
        edits += 1

    for _step in range(spec.steps):
        cid = rng.choice(sorted(containers))
        container = containers[cid]
        r = rng.random()
        if r < spec.edit_weight:
            do_edit(container)
        elif r < spec.edit_weight + spec.sync_weight:
            for c in containers.values():
                c.drain()
        elif r < spec.edit_weight + spec.sync_weight \
                + spec.disconnect_weight:
            if offline[cid]:
                container.reconnect()
                offline[cid] = False
            else:
                container.disconnect()
                offline[cid] = True
            disconnects += 1
        elif r < spec.edit_weight + spec.sync_weight \
                + spec.disconnect_weight + spec.stash_weight:
            if len(containers) > 1:
                stash = container.close_and_get_pending_state()
                del containers[cid]
                del offline[cid]
                new_client(pending_state=stash)
                rehydrates += 1
        else:
            if len(containers) < spec.max_clients:
                new_client()
                late_joins += 1

    # Final convergence: reconnect everyone, drain to quiescence.
    for cid, container in containers.items():
        if offline[cid]:
            container.reconnect()
            offline[cid] = False
    # Pump until TRUE quiescence: reconnect resubmits and nack-requeued
    # wire messages (fault injection) may need several flush+drain rounds
    # before every replica has flushed everything and seen the head.
    for _round in range(64):
        for container in containers.values():
            container.runtime.flush()
            container.drain()
        head = service.oplog.head("load-doc")
        if all(
            c.runtime.ref_seq == head
            and not c.runtime._pending_wire
            and not c.runtime._outbox
            for c in containers.values()
        ):
            break
    else:
        raise AssertionError("load run never quiesced after 64 rounds")

    digests = {c.runtime.summarize().digest() for c in containers.values()}
    if len(digests) != 1:
        detail = []
        for cid, c in containers.items():
            text = c.runtime.get_datastore("ds").get_channel("text")
            dm = c.delta_manager
            detail.append(
                f"{cid}: seq={c.runtime.ref_seq} nacks={dm.nacks} "
                f"state={dm.state.value} "
                f"pending_wire={len(c.runtime._pending_wire)} "
                f"outbox={len(c.runtime._outbox)} text={text.text[:40]!r}"
            )
        raise AssertionError(
            "load run diverged: "
            + f"{len(digests)} distinct summaries\n" + "\n".join(detail)
        )
    return LoadResult(
        steps=spec.steps,
        edits=edits,
        disconnects=disconnects,
        rehydrates=rehydrates,
        late_joins=late_joins,
        final_clients=len(containers),
        sequenced_ops=service.oplog.head("load-doc"),
        summary_digest=next(iter(digests)),
        nacks_issued=sum(
            o.sequencer.nacks_issued for o in service._orderers.values()
        ),
    )


# --- sharded ordering tier: multi-shard traffic with mid-run failover --------


@dataclasses.dataclass
class ShardedLoadSpec:
    """A deterministic multi-document, multi-client schedule over the
    sharded ordering tier (ISSUE 7), with an optional mid-run shard kill.

    The same spec driven with ``shards=1`` (single ``LocalOrderingService``)
    and ``scripted_reconnect_at`` set to the killed run's fence step is the
    byte-identity ORACLE: a voluntary reconnect stamps the same LEAVE+JOIN
    the fence reconnect does, so the two runs sequence identical per-doc
    logs and must produce identical summaries."""

    seed: int = 0
    shards: int = 4
    docs: int = 8
    clients_per_doc: int = 2
    steps: int = 240
    #: step AFTER which one shard is killed (None = no kill).  The victim
    #: is the owner of the first doc unless ``kill_shard`` names one.
    kill_at: Optional[int] = None
    kill_shard: Optional[str] = None
    #: "eager" = fenced clients reconnect at the kill step (the fence
    #: event); "lazy" = clients keep editing until a submit raises the
    #: fence flag, then reconnect (exercises the in-flight fence path).
    fence_reaction: str = "eager"
    #: oracle-twin knob: at this step, voluntarily reconnect the clients
    #: of ``scripted_docs`` (no kill) — mirrors the killed run's fence
    #: reconnects so both runs stamp identical LEAVE+JOIN schedules.
    scripted_reconnect_at: Optional[int] = None
    scripted_docs: tuple = ()
    #: attach a serialize-once Broadcaster probe with this many recorder
    #: sinks per document (0 = off); latencies are in virtual-clock ticks.
    probe_sinks: int = 0


@dataclasses.dataclass
class ShardedLoadResult:
    per_doc_digest: Dict[str, str]
    per_doc_head: Dict[str, int]
    sequenced_ops: int
    edits: int
    reconnects: int
    fenced_docs: List[str]
    killed_shard: Optional[str]
    epoch_bumped: bool
    shard_docs: Dict[str, int]      # live docs per surviving shard
    shard_ops: Dict[str, int]       # sequenced ops per surviving shard
    broadcast_encodes: int = 0
    broadcast_latencies: Optional[List[float]] = None


class _ProbeSink:
    """Recorder sink for the Broadcaster probe: accepts every frame and
    records delivery latency in virtual-clock ticks against the
    scenario's current submit timestamp."""

    def __init__(self, clock: VirtualClock, submit_t0: dict,
                 latencies: List[float]) -> None:
        self._clock = clock
        self._submit_t0 = submit_t0
        self._latencies = latencies

    def write_frame(self, data: bytes) -> bool:
        self._latencies.append(self._clock() - self._submit_t0["t"])
        return True

    def write_signal(self, data: bytes, signal: dict) -> bool:
        return True

    def on_demoted(self, doc_id: str, head_seq: int) -> None:
        raise AssertionError("probe sink accepts everything")

    def on_fence(self, doc_id: str, epoch: str, head_seq: int) -> None:
        pass


def run_sharded_load(spec: ShardedLoadSpec) -> ShardedLoadResult:
    from ..protocol.messages import ShardFencedError
    from ..service.broadcaster import Broadcaster
    from ..service.sharding import ShardedOrderingService

    rng = random.Random(spec.seed)
    clock = VirtualClock()
    if spec.shards > 1:
        service = ShardedOrderingService(n_shards=spec.shards)
    else:
        service = LocalOrderingService()
    factory = LocalDocumentServiceFactory(service)
    loader = Loader(factory, clock=clock)

    doc_ids = [f"shard-doc-{i:02d}" for i in range(spec.docs)]

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("sequence-tpu", "text")
        ds.create_channel("map-tpu", "kv")

    containers: Dict[tuple, object] = {}
    for doc_id in doc_ids:
        for c in range(spec.clients_per_doc):
            cid = f"ld{spec.seed}-{doc_id}-c{c}"
            if c == 0:
                containers[(doc_id, c)] = loader.create(doc_id, cid, build)
            else:
                containers[(doc_id, c)] = loader.resolve(doc_id, cid)

    # Broadcaster probe: serialize-once fan-out over every doc, recorder
    # sinks timing delivery in virtual ticks.
    broadcaster = latencies = None
    submit_t0 = {"t": 0.0}
    if spec.probe_sinks > 0:
        broadcaster = Broadcaster()
        latencies = []
        for doc_id in doc_ids:
            for _ in range(spec.probe_sinks):
                broadcaster.attach(doc_id, service.endpoint(doc_id),
                                   _ProbeSink(clock, submit_t0, latencies))
        if hasattr(service, "add_fence_listener"):
            service.add_fence_listener(
                lambda _sid, docs, epoch: [
                    broadcaster.refence(d, service.endpoint(d), epoch)
                    for d in docs
                ]
            )

    edits = reconnects = 0
    fenced_docs: List[str] = []
    killed: Optional[str] = None
    epoch0 = service.storage.epoch

    def do_edit(container):
        nonlocal edits
        ds = container.runtime.get_datastore("ds")
        if rng.random() < 0.7:
            text = ds.get_channel("text")
            n = len(text.text)
            if n < 4 or rng.random() < 0.7:
                text.insert_text(rng.randint(0, n),
                                 rng.choice("abcdef") * rng.randint(1, 3))
            else:
                start = rng.randint(0, n - 2)
                text.remove_range(start, min(n, start + 2))
        else:
            ds.get_channel("kv").set(f"k{rng.randint(0, 15)}",
                                     rng.randint(0, 999))
        edits += 1

    def reconnect(key):
        nonlocal reconnects
        doc_id, _ = key
        containers[key].reconnect(
            document_service=factory.resolve(doc_id))
        reconnects += 1

    def reconnect_doc_clients(docs):
        for key in sorted(containers):
            if key[0] in docs:
                reconnect(key)

    for step in range(spec.steps):
        key = (rng.choice(doc_ids), rng.randrange(spec.clients_per_doc))
        container = containers[key]
        submit_t0["t"] = clock.now  # probe anchor: do not advance
        try:
            do_edit(container)
        except ShardFencedError:
            # Lazy reaction: the edit's flush hit the fence before the
            # wire-drain could swallow it (connect paths raise through).
            reconnect(key)
            container.drain()
        if container.delta_manager.fence_required:
            # The wire-drain swallowed the fence (ConnectionError
            # contract) and flagged it: re-resolve through the router
            # and reconnect — the queued ops ride out on the new owner.
            reconnect(key)
        if step % 4 == 3:
            for c in containers.values():
                c.drain()
        if spec.kill_at is not None and step == spec.kill_at:
            victim = spec.kill_shard or service.shard_of(doc_ids[0])
            fenced_docs = service.kill_shard(victim)
            killed = victim
            if spec.fence_reaction == "eager":
                reconnect_doc_clients(set(fenced_docs))
        if spec.scripted_reconnect_at is not None \
                and step == spec.scripted_reconnect_at:
            reconnect_doc_clients(set(spec.scripted_docs))

    # Quiescence: same discipline as run_load, per document.
    for _round in range(64):
        for c in containers.values():
            if c.delta_manager.fence_required:
                reconnect_doc_clients({c.doc_id})
            c.runtime.flush()
            c.drain()
        if all(
            c.runtime.ref_seq == service.oplog.head(c.doc_id)
            and not c.runtime._pending_wire
            and not c.runtime._outbox
            for c in containers.values()
        ):
            break
    else:
        raise AssertionError("sharded load never quiesced after 64 rounds")

    per_doc_digest: Dict[str, str] = {}
    per_doc_head: Dict[str, int] = {}
    for doc_id in doc_ids:
        digests = {
            c.runtime.summarize().digest()
            for key, c in containers.items() if key[0] == doc_id
        }
        if len(digests) != 1:
            raise AssertionError(
                f"{doc_id} diverged: {len(digests)} distinct summaries")
        per_doc_digest[doc_id] = next(iter(digests))
        head = service.oplog.head(doc_id)
        per_doc_head[doc_id] = head
        seqs = [m.seq for m in service.oplog.get(doc_id)]
        if seqs != list(range(1, head + 1)):
            raise AssertionError(
                f"{doc_id} seq numbers not contiguous: {seqs[:10]}...")

    shard_docs: Dict[str, int] = {}
    shard_ops: Dict[str, int] = {}
    if isinstance(service, ShardedOrderingService):
        for sid, (n_docs, n_ops) in service.shard_load().items():
            shard_docs[sid] = n_docs
            shard_ops[sid] = n_ops
    return ShardedLoadResult(
        per_doc_digest=per_doc_digest,
        per_doc_head=per_doc_head,
        sequenced_ops=sum(per_doc_head.values()),
        edits=edits,
        reconnects=reconnects,
        fenced_docs=list(fenced_docs),
        killed_shard=killed,
        epoch_bumped=service.storage.epoch != epoch0,
        shard_docs=shard_docs,
        shard_ops=shard_ops,
        broadcast_encodes=(broadcaster.stats()["encodes"]
                           if broadcaster is not None else 0),
        broadcast_latencies=latencies,
    )


# --- chaos load: mixed traffic under a generated fault schedule --------------


@dataclasses.dataclass
class ChaosLoadSpec:
    """A deterministic multi-shard schedule driven UNDER a fault plan
    (testing/faults.py): every seam failure — durable-append outages,
    torn writes, stale summary serves, laggard clients, a shard kill —
    is injected by occurrence/tick, so the whole run is a pure function
    of ``(seed, plan)``.

    The acceptance oracle (:func:`run_chaos_with_oracle`) re-drives the
    SAME scenario fault-free on a single shard, with the kill's
    fence-forced reconnects mirrored as scripted voluntary reconnects
    and the laggard (client-behavior) stalls kept — final per-document
    summaries must be byte-identical: faults may cost retries, never
    state."""

    seed: int = 0
    shards: int = 4
    docs: int = 6
    clients_per_doc: int = 2
    steps: int = 240
    #: None → ``FaultPlan.generate(seed, docs, steps)``
    plan: Optional[object] = None
    #: directory for the durable tier (file-backed oplog + summary
    #: store); required when the plan carries file-level fault points
    #: (torn appends, storage store/read faults)
    dir: Optional[str] = None
    #: None → a deterministic small-backoff RetryPolicy
    retry: Optional[object] = None
    #: one scripted late-join per document (exercises the cold-load /
    #: stale-summary-serve path mid-run); identical in the oracle twin
    late_joins: bool = True
    #: oracle-twin knob: ((step, (doc, ...)), ...) voluntary reconnects
    #: mirroring the chaos run's fence reconnects
    scripted_reconnects: tuple = ()


@dataclasses.dataclass
class ChaosLoadResult:
    per_doc_digest: Dict[str, str]
    per_doc_head: Dict[str, int]
    sequenced_ops: int
    edits: int
    reconnects: int
    #: (step, killed shard id, (affected doc, ...)) per executed kill
    kills: List[tuple]
    #: injector ``site:kind`` observation counts (replay-identity surface)
    fault_counts: Dict[str, int]
    #: summed DeltaManager ``retry.*`` counters across every client
    retry_counts: Dict[str, int]
    #: labels of plan points that never fired (must be [] for a run that
    #: claims its plan's coverage)
    unfired: List[str]
    #: virtual ticks from each kill to every affected doc re-converging
    recovery_ticks: List[float]
    stalled_steps: int


def chaos_doc_ids(docs: int) -> List[str]:
    """The chaos harness's document naming scheme — public so plan
    builders (tools/chaos.py, plan files) target real ids; a doc-scoped
    point naming a nonexistent id would silently never fire."""
    return [f"chaos-doc-{i:02d}" for i in range(docs)]


def _chaos_doc_ids(spec: ChaosLoadSpec) -> List[str]:
    return chaos_doc_ids(spec.docs)


def percentile(sorted_values, q: float) -> float:
    """Index-clamped percentile over an already-sorted sample — the one
    shared implementation for every bench reporter (service_e2e, chaos)."""
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1,
              max(0, int(len(sorted_values) * q) - 1))
    return sorted_values[idx]


def run_chaos_load(spec: ChaosLoadSpec) -> ChaosLoadResult:
    import os as _os

    from ..drivers.file_driver import FileSummaryStorage
    from ..protocol.messages import ShardFencedError
    from ..service.oplog import OpLog
    from ..service.retry import RetryPolicy
    from ..service.sharding import ShardedOrderingService
    from .faults import FaultInjector, FaultPlan

    doc_ids = _chaos_doc_ids(spec)
    plan = spec.plan if spec.plan is not None \
        else FaultPlan.generate(spec.seed, doc_ids, spec.steps)
    wire_sites = [p.site for p in plan.points
                  if p.site.startswith("rpc.") or p.site == "session.write"
                  or p.site.startswith("catchup.")]
    if wire_sites:
        raise ValueError(
            f"plan points at {sorted(set(wire_sites))} need the TCP "
            "stack or the server catchup fold lane, which this "
            "in-process harness does not drive — they would silently "
            "never fire and fail the coverage oracle; exercise wire "
            "sites via tools/chaos.py's tcp_smoke or the directed wire "
            "tests (tests/test_faultline.py), and catchup.* via the "
            "catchup-storm swarm scenario (testing/scenarios.py)")
    file_sites = ("storage.store", "storage.read", "oplog.flush")
    needs_dir = any(
        p.site in file_sites or (p.site == "oplog.append"
                                 and p.kind == "torn")
        for p in plan.points)
    if needs_dir and spec.dir is None:
        raise ValueError(
            "this plan injects file-level faults (torn appends, flush, "
            "summary store/read); pass ChaosLoadSpec.dir for the "
            "durable tier")
    injector = FaultInjector(plan)
    rng = random.Random(spec.seed)
    clock = VirtualClock()
    retry = spec.retry if spec.retry is not None else RetryPolicy(
        max_attempts=5, base_delay=0.01, max_delay=0.5, budget=5.0)

    if spec.dir is not None:
        _os.makedirs(spec.dir, exist_ok=True)
        # autoflush = the deployed durable-before-broadcast shape (the
        # standalone server's): every append fsyncs before the
        # broadcast, so flush faults fire on the real cadence.
        oplog = OpLog(_os.path.join(spec.dir, "chaos-ops.jsonl"),
                      autoflush=True, faults=injector)
        storage = FileSummaryStorage(
            _os.path.join(spec.dir, "chaos-summaries"), faults=injector)
    else:
        oplog, storage = OpLog(faults=injector), None
    if spec.shards > 1:
        service = ShardedOrderingService(
            n_shards=spec.shards, oplog=oplog, storage=storage,
            faults=injector)
    else:
        service = LocalOrderingService(oplog=oplog, storage=storage)
    factory = LocalDocumentServiceFactory(service)
    loader = Loader(factory, clock=clock, retry=retry)

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("sequence-tpu", "text")
        ds.create_channel("map-tpu", "kv")

    containers: Dict[tuple, object] = {}
    for doc_id in doc_ids:
        for c in range(spec.clients_per_doc):
            cid = f"ch{spec.seed}-{doc_id}-c{c}"
            if c == 0:
                containers[(doc_id, c)] = loader.create(doc_id, cid, build)
            else:
                containers[(doc_id, c)] = loader.resolve(doc_id, cid)

    # Scripted late-joins: doc i gains a fresh client at a deterministic
    # step — identical in the oracle twin (scenario, not fault); the cold
    # resolve is where a stale-summary serve lands mid-run.
    late_join_step = {}
    if spec.late_joins:
        for i, doc_id in enumerate(doc_ids):
            late_join_step[spec.steps // 3 + 2 * i] = doc_id

    edits = reconnects = stalled_steps = 0
    kills: List[tuple] = []
    #: (doc, client index) -> stalled until step (exclusive)
    stalled: Dict[tuple, int] = {}
    #: (kill step, t0 virtual, remaining affected docs) under recovery
    recovering: List[list] = []
    recovery_ticks: List[float] = []

    def do_edit(container):
        nonlocal edits
        ds = container.runtime.get_datastore("ds")
        if rng.random() < 0.7:
            text = ds.get_channel("text")
            n = len(text.text)
            if n < 4 or rng.random() < 0.7:
                text.insert_text(rng.randint(0, n),
                                 rng.choice("abcdef") * rng.randint(1, 3))
            else:
                start = rng.randint(0, n - 2)
                text.remove_range(start, min(n, start + 2))
        else:
            ds.get_channel("kv").set(f"k{rng.randint(0, 15)}",
                                     rng.randint(0, 999))
        edits += 1

    def reconnect_docs(docs) -> None:
        nonlocal reconnects
        for key in sorted(containers):
            if key[0] in docs:
                # No explicit service: a fence reconnect re-resolves the
                # recovered owner through the DeltaManager's retry
                # (ShardFencedError → on_fence → router); a voluntary
                # (oracle-twin) reconnect just re-attaches.  Both stamp
                # the same LEAVE+JOIN.
                containers[key].reconnect()
                reconnects += 1

    for step in range(spec.steps):
        key = (rng.choice(doc_ids), rng.randrange(spec.clients_per_doc))
        container = containers[key]
        try:
            do_edit(container)
        except ShardFencedError:
            container.drain()  # self-heal: re-resolve + replay held ops
        doc_id = late_join_step.get(step)
        if doc_id is not None:
            # Mid-run service-side summary at the current durable head
            # (scenario behavior, identical in the oracle twin: no ops
            # are stamped).  This is what makes a stale-read fault on
            # the late join REAL — a lagging replica then serves the
            # PARENT summary and the joiner replays the longer tail.
            ro = loader.resolve(doc_id)
            service.storage.upload(doc_id, ro.runtime.summarize(),
                                   ro.runtime.ref_seq)
            ro.close()
            idx = spec.clients_per_doc
            containers[(doc_id, idx)] = loader.resolve(
                doc_id, f"ch{spec.seed}-{doc_id}-c{idx}")
        for p in injector.due("client.stall", step):
            victim = (p.doc, 1 % spec.clients_per_doc)
            stalled[victim] = step + int(p.arg)
            stalled_steps += int(p.arg)
        if step % 4 == 3:
            for ckey in sorted(containers):
                if stalled.get(ckey, 0) > step:
                    continue  # laggard: inbound queue grows, no drain
                containers[ckey].drain()
        if isinstance(service, ShardedOrderingService):
            before_dead = set(service.router.dead())
            affected = service.tick(step)
            newly_dead = [s for s in service.router.dead()
                          if s not in before_dead]
            if newly_dead:
                kills.append((step, newly_dead[0], tuple(affected)))
                recovering.append([step, clock.now, set(affected)])
                reconnect_docs(set(affected))
        for when, docs in spec.scripted_reconnects:
            if when == step:
                reconnect_docs(set(docs))
        # Recovery metric: a doc counts recovered once every one of its
        # clients is back at the durable head; the sample is the virtual
        # ticks elapsed since its shard was killed.
        for entry in recovering:
            done = {
                d for d in entry[2]
                if all(c.runtime.ref_seq >= service.oplog.head(d)
                       for k, c in containers.items() if k[0] == d)
            }
            for _d in sorted(done):
                recovery_ticks.append(clock.now - entry[1])
            entry[2] -= done
        recovering = [e for e in recovering if e[2]]

    # Quiescence: flush+drain rounds; Container.drain self-heals fences.
    for _round in range(64):
        for c in containers.values():
            c.runtime.flush()
            c.drain()
        if all(
            c.runtime.ref_seq == service.oplog.head(c.doc_id)
            and not c.runtime._pending_wire
            and not c.runtime._outbox
            for c in containers.values()
        ):
            break
    else:
        raise AssertionError("chaos load never quiesced after 64 rounds")

    # Docs still marked recovering when the step loop ended converged in
    # the quiescence rounds: sample them at the post-quiescence clock.
    for entry in recovering:
        for _d in sorted(entry[2]):
            recovery_ticks.append(clock.now - entry[1])

    per_doc_digest: Dict[str, str] = {}
    per_doc_head: Dict[str, int] = {}
    for doc_id in doc_ids:
        digests = {
            c.runtime.summarize().digest()
            for key, c in containers.items() if key[0] == doc_id
        }
        if len(digests) != 1:
            raise AssertionError(
                f"{doc_id} diverged: {len(digests)} distinct summaries")
        per_doc_digest[doc_id] = next(iter(digests))
        head = service.oplog.head(doc_id)
        per_doc_head[doc_id] = head
        seqs = [m.seq for m in service.oplog.get(doc_id)]
        if seqs != list(range(1, head + 1)):
            raise AssertionError(
                f"{doc_id} seq numbers not contiguous under faults: "
                f"{seqs[:10]}...")

    retry_counts: Dict[str, int] = {}
    for ckey in sorted(containers):
        counters = containers[ckey].delta_manager.retry_counters
        for name, value in sorted(counters.snapshot().items()):
            retry_counts[name] = retry_counts.get(name, 0) + value
    return ChaosLoadResult(
        per_doc_digest=per_doc_digest,
        per_doc_head=per_doc_head,
        sequenced_ops=sum(per_doc_head.values()),
        edits=edits,
        reconnects=reconnects,
        kills=kills,
        fault_counts=injector.snapshot(),
        retry_counts=retry_counts,
        unfired=[p.label() for p in injector.unfired()],
        recovery_ticks=recovery_ticks,
        stalled_steps=stalled_steps,
    )


def run_chaos_with_oracle(spec: ChaosLoadSpec):
    """THE acceptance harness: drive ``spec`` under its fault plan, then
    re-drive the identical scenario FAULT-FREE on a single shard — the
    kill's fence reconnects mirrored as scripted voluntary reconnects at
    the same steps (a reconnect stamps the same LEAVE+JOIN either way),
    laggard stalls kept (client behavior, not a service fault) — and
    return ``(chaos, oracle)``.  Callers assert per-doc digests/heads
    byte-identical: the entire fault schedule may cost retries and
    recoveries, but never state."""
    from .faults import FaultPlan

    chaos = run_chaos_load(spec)
    doc_ids = _chaos_doc_ids(spec)
    plan = spec.plan if spec.plan is not None \
        else FaultPlan.generate(spec.seed, doc_ids, spec.steps)
    stall_points = tuple(p for p in plan.points
                         if p.site == "client.stall")
    oracle_spec = dataclasses.replace(
        spec,
        shards=1,
        dir=None,  # fault-free: the in-memory durable tier suffices
        plan=FaultPlan(seed=spec.seed, points=stall_points),
        scripted_reconnects=tuple(
            (step, docs) for step, _shard, docs in chaos.kills),
    )
    return chaos, run_chaos_load(oracle_spec)


# --- wire soak: many docs through the standalone server's catchup RPC --------


def _soak_doc_name(i: int) -> str:
    return f"soak{i:05d}"


#: channel mix per doc index — all four kernel types cross the device path
_SOAK_KINDS = ("string", "map", "matrix", "tree", "string+map")


def _soak_build(kind: str):
    def build(rt):
        ds = rt.create_datastore("ds")
        if kind in ("string", "string+map"):
            ds.create_channel("sequence-tpu", "text")
        if kind in ("map", "string+map"):
            ds.create_channel("map-tpu", "kv")
        if kind == "matrix":
            ds.create_channel("matrix-tpu", "mx")
        if kind == "tree":
            ds.create_channel("tree-tpu", "tr")

    return build


def _soak_edit(container, kind: str, rng: random.Random,
               edits: int) -> None:
    ds = container.runtime.get_datastore("ds")
    for _ in range(edits):
        if kind in ("string", "string+map"):
            text = ds.get_channel("text")
            n = len(text.text)
            r = rng.random()
            if n < 4 or r < 0.6:
                text.insert_text(rng.randint(0, n),
                                 rng.choice("abcdef") * rng.randint(1, 4))
            elif r < 0.85 or kind == "string":
                start = rng.randint(0, n - 2)
                text.remove_range(start, min(n, start + 2))
            else:
                ds.get_channel("kv").set(f"k{rng.randint(0, 9)}",
                                         rng.randint(0, 99))
        elif kind == "map":
            ds.get_channel("kv").set(f"k{rng.randint(0, 9)}",
                                     rng.randint(0, 99))
        elif kind == "matrix":
            mx = ds.get_channel("mx")
            if mx.row_count == 0 or mx.col_count == 0:
                mx.insert_rows(0, 2)
                mx.insert_cols(0, 2)
            else:
                mx.set_cell(rng.randrange(mx.row_count),
                            rng.randrange(mx.col_count),
                            rng.randint(0, 99))
        else:  # tree
            tr = ds.get_channel("tr")
            kids = tr.children("", "a")
            if not kids or rng.random() < 0.6:
                tr.insert("", "a", rng.randint(0, len(kids)),
                          [tr.build("n", value=rng.randint(0, 99))])
            else:
                tr.set_value(rng.choice(kids), rng.randint(0, 99))


def wire_soak_worker(host: str, port: int, lo: int, hi: int,
                     edits_per_doc: int, seed: int) -> Dict[str, str]:
    """Seed docs [lo, hi) against a running standalone server over TCP;
    returns {doc_id: expected summary digest} (the seeder's drained-to-head
    summarize — what a post-catchup fresh load must reproduce)."""
    import time

    from ..drivers.network_driver import NetworkDocumentServiceFactory

    factory = NetworkDocumentServiceFactory(host=host, port=port)
    out: Dict[str, str] = {}
    try:
        loader = Loader(factory)
        for i in range(lo, hi):
            doc_id = _soak_doc_name(i)
            kind = _SOAK_KINDS[i % len(_SOAK_KINDS)]
            rng = random.Random(seed * 7919 + i)
            c = loader.create(doc_id, f"seeder{i}", _soak_build(kind))
            _soak_edit(c, kind, rng, edits_per_doc)
            c.runtime.flush()
            head = factory.resolve(doc_id).delta_storage.head()
            deadline = time.time() + 30
            while time.time() < deadline and c.runtime.ref_seq < head:
                c.drain()
                time.sleep(0.005)
            c.drain()
            c.close()  # LEAVE advances the head past the seeder's view...
            # ...so the expected digest comes from a READ-ONLY load (no
            # JOIN) at the quiesced head — exactly what a post-catchup
            # fresh read-only load must reproduce byte-identically.
            ro = loader.resolve(doc_id)
            out[doc_id] = ro.runtime.summarize().digest()
            ro.close()
        return out
    finally:
        factory.close()


def main() -> None:
    """Subprocess entry: ``python -m fluidframework_tpu.testing.load
    --wire-worker HOST PORT LO HI EDITS SEED`` prints one JSON object of
    {doc_id: digest}."""
    import argparse
    import json
    import sys

    p = argparse.ArgumentParser()
    p.add_argument("--wire-worker", nargs=6, metavar=(
        "HOST", "PORT", "LO", "HI", "EDITS", "SEED"))
    args = p.parse_args()
    if args.wire_worker:
        host, port, lo, hi, edits, seed = args.wire_worker
        digests = wire_soak_worker(host, int(port), int(lo), int(hi),
                                   int(edits), int(seed))
        json.dump(digests, sys.stdout)
        sys.stdout.write("\n")


if __name__ == "__main__":
    main()
