"""Load/stress harness: many puppet clients with fault injection.

Capability-equivalent of the reference's ``test-service-load`` (SURVEY.md
§4: many puppet clients against a real service, configurable op rates,
random disconnects; upstream paths UNVERIFIED — empty reference mount).

Drives the REAL stack (Loader → driver → ordering service), not the mocks:
each puppet runs a seeded random schedule of edits, syncs, disconnects/
reconnects, stash/rehydrate cycles, and late joins; at the end everything
synchronizes and the harness asserts byte-identical summaries across every
surviving client — the convergence oracle under load."""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional

from ..drivers import LocalDocumentServiceFactory
from ..loader import Loader
from ..service import LocalOrderingService


@dataclasses.dataclass
class LoadSpec:
    seed: int = 0
    clients: int = 4
    #: fault injection: NACK every Nth submit service-side (0 = off); the
    #: nacked ops must still converge (the runtime requeues + resends)
    nack_every: int = 0
    steps: int = 200               # total scheduled actions
    edit_weight: float = 0.70
    sync_weight: float = 0.15
    disconnect_weight: float = 0.05
    stash_weight: float = 0.03     # crash + rehydrate as a new session
    late_join_weight: float = 0.02
    max_clients: int = 8


@dataclasses.dataclass
class LoadResult:
    steps: int
    edits: int
    disconnects: int
    rehydrates: int
    late_joins: int
    final_clients: int
    sequenced_ops: int
    summary_digest: str
    nacks_issued: int = 0


def run_load(spec: LoadSpec) -> LoadResult:
    rng = random.Random(spec.seed)
    throttle = None
    if spec.nack_every:
        counter = {"n": 0}

        def throttle(_client_id):
            counter["n"] += 1
            if counter["n"] % spec.nack_every == 0:
                return 0.0  # immediate-retry nack (fault injection)
            return None

    service = LocalOrderingService(throttle=throttle)
    loader = Loader(LocalDocumentServiceFactory(service))

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("sequence-tpu", "text")
        ds.create_channel("map-tpu", "kv")
        ds.create_channel("counter-tpu", "count")

    containers: Dict[str, object] = {}
    offline: Dict[str, bool] = {}
    next_id = 0

    def new_client(pending_state=None):
        nonlocal next_id
        next_id += 1
        cid = f"load-{spec.seed}-{next_id}"
        if not containers and pending_state is None:
            c = loader.create("load-doc", cid, build)
        else:
            c = loader.resolve("load-doc", cid, pending_state=pending_state)
        containers[cid] = c
        offline[cid] = False
        return cid

    for _ in range(spec.clients):
        new_client()

    edits = disconnects = rehydrates = late_joins = 0

    def do_edit(container):
        nonlocal edits
        ds = container.runtime.get_datastore("ds")
        choice = rng.random()
        if choice < 0.6:
            text = ds.get_channel("text")
            length = len(text.text)
            if length < 4 or rng.random() < 0.7:
                text.insert_text(rng.randint(0, length),
                                 rng.choice("abcdef") * rng.randint(1, 4))
            else:
                start = rng.randint(0, length - 2)
                text.remove_range(start, min(length, start + 3))
        elif choice < 0.9:
            ds.get_channel("kv").set(f"k{rng.randint(0, 20)}",
                                     rng.randint(0, 999))
        else:
            ds.get_channel("count").increment(rng.choice([1, -1, 5]))
        edits += 1

    for _step in range(spec.steps):
        cid = rng.choice(sorted(containers))
        container = containers[cid]
        r = rng.random()
        if r < spec.edit_weight:
            do_edit(container)
        elif r < spec.edit_weight + spec.sync_weight:
            for c in containers.values():
                c.drain()
        elif r < spec.edit_weight + spec.sync_weight \
                + spec.disconnect_weight:
            if offline[cid]:
                container.reconnect()
                offline[cid] = False
            else:
                container.disconnect()
                offline[cid] = True
            disconnects += 1
        elif r < spec.edit_weight + spec.sync_weight \
                + spec.disconnect_weight + spec.stash_weight:
            if len(containers) > 1:
                stash = container.close_and_get_pending_state()
                del containers[cid]
                del offline[cid]
                new_client(pending_state=stash)
                rehydrates += 1
        else:
            if len(containers) < spec.max_clients:
                new_client()
                late_joins += 1

    # Final convergence: reconnect everyone, drain to quiescence.
    for cid, container in containers.items():
        if offline[cid]:
            container.reconnect()
            offline[cid] = False
    # Pump until TRUE quiescence: reconnect resubmits and nack-requeued
    # wire messages (fault injection) may need several flush+drain rounds
    # before every replica has flushed everything and seen the head.
    for _round in range(64):
        for container in containers.values():
            container.runtime.flush()
            container.drain()
        head = service.oplog.head("load-doc")
        if all(
            c.runtime.ref_seq == head
            and not c.runtime._pending_wire
            and not c.runtime._outbox
            for c in containers.values()
        ):
            break
    else:
        raise AssertionError("load run never quiesced after 64 rounds")

    digests = {c.runtime.summarize().digest() for c in containers.values()}
    if len(digests) != 1:
        detail = []
        for cid, c in containers.items():
            text = c.runtime.get_datastore("ds").get_channel("text")
            dm = c.delta_manager
            detail.append(
                f"{cid}: seq={c.runtime.ref_seq} nacks={dm.nacks} "
                f"state={dm.state.value} "
                f"pending_wire={len(c.runtime._pending_wire)} "
                f"outbox={len(c.runtime._outbox)} text={text.text[:40]!r}"
            )
        raise AssertionError(
            "load run diverged: "
            + f"{len(digests)} distinct summaries\n" + "\n".join(detail)
        )
    return LoadResult(
        steps=spec.steps,
        edits=edits,
        disconnects=disconnects,
        rehydrates=rehydrates,
        late_joins=late_joins,
        final_clients=len(containers),
        sequenced_ops=service.oplog.head("load-doc"),
        summary_digest=next(iter(digests)),
        nacks_issued=sum(
            o.sequencer.nacks_issued for o in service._orderers.values()
        ),
    )
