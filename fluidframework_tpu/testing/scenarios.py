"""fluidscale: a vectorized 10⁵–10⁶-client scenario engine over the REAL
serving stack (ISSUE 10).

``testing/load.py`` drives dozens of puppet clients, each a full
Container + DeltaManager — which makes the north star's "millions of
users" claim unfalsifiable: nothing could ever drive enough clients to
measure it.  This module simulates swarm populations **columnar**: all
per-client state (document assignment, op cadence, next-fire tick,
connect / laggard / catch-up state, consumption cursor) lives in numpy
arrays stepped O(population) per virtual tick, while every generated op
is submitted through the *real* path — by default (ISSUE 11) the
columnar wire path: each tick's ops planned as ONE struct-packed
:class:`~fluidframework_tpu.protocol.wire.ColumnBatch`, shipped through
the real ``column_batch_to_bytes``/``from_bytes`` framing, and stamped
vectorized via ``submit_columns`` (one durable-log flush per tick);
with ``spec.columnar=False``, the r10 boxed per-op path — the
byte-identical parity oracle.  Broadcast sinks ride the serialize-once
:class:`~fluidframework_tpu.service.broadcaster.Broadcaster` on the
SAMPLED documents (identical topology in both modes), and everything
lands in the durable
:class:`~fluidframework_tpu.service.oplog.OpLog`.  Nothing in
the serving path is mocked; only the CLIENTS are virtual.

Determinism (see SEMANTICS.md "Swarm determinism"): a run is a pure
function of ``(seed, spec)`` — op content and cadence come from counter-
based hash mixing, consumption is modeled in virtual ticks, faults are
``FaultPlan``-scheduled, and the single-threaded step loop gives the
batched ingress a deterministic submission order.  Replaying the same
spec reproduces every metric, fault observation, and telemetry counter
bit-identically.

The acceptance oracle (:func:`run_swarm_with_oracle`) re-drives the SAME
scenario fault-free on a single shard, mirroring any batch deferrals the
faulted run recorded (``scripted_defers``) so both runs stamp
byte-identical per-document logs — final summaries of sampled documents,
loaded through the real Loader, must match byte-for-byte.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..drivers import LocalDocumentServiceFactory
from ..loader import Loader
from ..service import LocalOrderingService
from ..service.broadcaster import Broadcaster
from ..service.oplog import OpLog
from ..service.sharding import ShardedOrderingService
from ..protocol.messages import MessageType, NackError, RawOperation
from ..protocol.wire import (COL_KIND_INCREMENT, COL_KIND_INSERT,
                             COL_KIND_SET, CHAR_STRINGS, ColumnBatch,
                             column_batch_from_bytes, column_batch_to_bytes,
                             key_string)
from ..runtime.op_pipeline import BATCH_WIRE_VERSION
from ..utils.telemetry import CounterSet, IngressMeter
from .faults import FaultInjector, FaultPlan, FaultPoint
from .load import VirtualClock, percentile

# -- client states (int8 column) ----------------------------------------------

_UNBORN = 0     # not yet connected (pre-ramp)
_STEADY = 1     # connected, typing and draining on its fire cadence
_DARK = 2       # herd cohort: neither submits nor drains (gone dark)
_LAGGARD = 3    # keeps typing against a FROZEN view; never drains
_CATCHUP = 4    # draining a backlog at catchup_rate ops/tick


def _u64(x) -> np.uint64:
    return np.uint64(x & 0xFFFFFFFFFFFFFFFF)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over a uint64 array: the counter-based hash
    every swarm decision draws from — no PRNG object state, so any
    (client, op index) decision is recomputable from the seed alone."""
    x = (x ^ (x >> np.uint64(30))) * _u64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * _u64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _hash_clients(seed: int, salt: int, idx: np.ndarray,
                  extra: Optional[np.ndarray] = None) -> np.ndarray:
    h = (idx.astype(np.uint64) * _u64(0x9E3779B97F4A7C15)
         + _u64(seed * 0x100000001B3 + salt * 0xD1B54A32D192ED03 + 1))
    if extra is not None:
        h = h + extra.astype(np.uint64) * _u64(0xA0761D6478BD642F)
    return _mix64(h)


# -- scenario DSL -------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Phase:
    """One scenario phase.  ``kind``:

    - ``ramp``     — the population connects, spread over the phase
      (batched JOINs through ``connect_many``);
    - ``steady``   — steady typing traffic on per-client cadences;
    - ``herd``     — ``frac`` of the steady population goes DARK for the
      phase, then re-enters together as a catch-up herd at its end;
    - ``laggards`` — ``frac`` get individual stop-draining windows inside
      the phase (they keep typing against frozen views — the MSN-pinning
      shape), each recovering through a catch-up burst;
    - ``election`` — instant event (``ticks`` may be 0): a service-side
      summarizer loads each sampled document at the durable head and
      uploads a summary (the summary-election capability at scale).
    """

    kind: str
    ticks: int = 0
    frac: float = 0.0

    def __post_init__(self) -> None:
        if self.kind not in ("ramp", "steady", "herd", "laggards",
                             "election"):
            raise ValueError(f"unknown phase kind {self.kind!r}")
        if self.ticks < 0 or not (0.0 <= self.frac <= 1.0):
            raise ValueError(f"bad phase {self}")


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """A fully deterministic swarm scenario: the run is a pure function
    of this value (``seed`` included)."""

    name: str
    phases: Tuple[Phase, ...]
    seed: int = 0
    clients: int = 1000
    docs: int = 16
    shards: int = 4
    #: mean client ops over the whole run; op cadence is derived from it
    #: (total ops ≈ clients × ops_per_client, independent of population)
    ops_per_client: float = 3.0
    #: ops a catching-up client consumes per tick
    catchup_rate: int = 256
    #: every Nth document is sampled for elections + the digest oracle
    sample_every: int = 8
    #: scheduled faults (shard kills, durable-append outages) driven at
    #: virtual ticks through testing/faults.py
    plan: Optional[FaultPlan] = None
    #: oracle-twin knob: ``((tick, doc_index, consumed), ...)`` — split
    #: that document's tick batch at ``consumed`` and defer the whole
    #: batch to the next tick, mirroring a faulted run's recorded
    #: deferrals so both runs stamp identical logs
    scripted_defers: Tuple[tuple, ...] = ()
    #: same mirror for batched JOINs: ``((tick, doc_index, joined), ...)``
    #: — connect only the first ``joined`` clients of that document's
    #: tick cohort, the rest re-try next tick
    scripted_join_defers: Tuple[tuple, ...] = ()
    #: directory for a durable file-backed op log (None = in-memory);
    #: group commit makes the fsync cost one flush per tick batch
    dir: Optional[str] = None
    #: columnar wire path (ISSUE 11): plan each tick's ops as ONE
    #: struct-packed ColumnBatch, ship it through the real wire
    #: encode/decode, and stamp it through the services' vectorized
    #: ``submit_columns``; the per-op boxed loop survives as the
    #: fallback for pending/scripted/subscriber-bearing documents.
    #: ``False`` = the r10 boxed path — the byte-identical parity oracle.
    columnar: bool = True
    #: out-of-process tier (ISSUE 12): drive the scenario against REAL
    #: shard-host processes behind an in-process front door — every op
    #: crosses the wire twice (swarm → front door → owning shard), logs
    #: are per-shard files on disk, and scheduled ``proc.kill`` /
    #: ``proc.hang`` points SIGKILL/SIGSTOP real processes.  Only
    #: SCHEDULED fault sites are allowed in the plan (a seam site like
    #: ``oplog.append`` lives inside a shard process this harness cannot
    #: reach — such a plan fails loudly instead of reporting hollow
    #: coverage).
    out_of_proc: bool = False
    #: run a REAL CatchupService cold+warm fold pass over the sampled
    #: documents after the run (ISSUE 13): the swarm's op logs hit the
    #: device fold twice with tier 1 off, so the warm pass exercises the
    #: pack / delta / device-resident tiers and their counters land in
    #: ``SwarmResult.fold_tier`` (outside replay identity, like
    #: ``ingress``).  In-proc runs only.
    fold_probe: bool = False
    #: SharedTree collaboration (ISSUE 14): every document carries a
    #: tree channel and the swarm's generated ops are id-addressed tree
    #: changesets (leaf insert / value set / remove under root fields)
    #: instead of map/counter/string traffic.  Tree changesets are
    #: outside the closed columnar wire vocabulary, so ingress takes the
    #: boxed envelope path by design (the per-doc fallback route the
    #: columnar contract documents); with ``fold_probe`` the sampled
    #: documents then catch up through the REAL CatchupService TREE
    #: route — the second-kernel-family serving shape.
    tree_ops: bool = False
    #: catch-up STORM (ISSUE 15): every herd/laggard re-entry cohort
    #: elects real catch-up callers per document whose joins are
    #: converted into REAL ``CatchupService.catch_up`` calls through an
    #: adaptive-admission fold lane (``service/server.py``) — warm
    #: bypass, load-derived shed pacing, degraded serving, and the
    #: ``catchup.slow``/``catchup.fail`` seams all fire; the rest of the
    #: cohort models consumption columnar so cost stays bounded.
    #: In-proc runs are replay bit-identical (admission runs off a
    #: VirtualClock); out-of-proc runs issue the real ``catchup`` RPC
    #: through the front door against WIRE-CLOCK shard admission
    #: (ISSUE 18), so remote verdicts are bit-identical too — only
    #: transport noise stays outside replay identity.
    storm: bool = False
    #: real catch-up callers elected per document per storm wave — the
    #: "sampled real folds" bound; the cohort remainder stays columnar
    storm_clients_per_doc: int = 4
    #: admission slots of the storm fold lane (Catchup.MaxInflight)
    storm_max_inflight: int = 4
    #: consecutive overflow verdicts before degraded-mode serving takes
    #: over from shedding (Catchup.DegradeAfter) — high enough that the
    #: herd really cycles through shed → paced retry before the tier
    #: falls back to stale serves
    storm_degrade_after: int = 4
    #: reconnect jitter: a cohort's first attempts hash-spread over this
    #: many ticks (an instantaneous 10⁴ spike would lock the tier into
    #: pure degraded mode on tick one — real herds arrive over seconds,
    #: and the spread is what lets folds, sheds, paced retries, and warm
    #: hits all interplay)
    storm_spread_ticks: int = 8
    #: modeled fold duration: virtual ticks an admission lease stays
    #: occupied after its (synchronous) fold returns — what makes a
    #: single-threaded deterministic driver produce real overlapping-
    #: fold admission pressure
    storm_fold_ticks: int = 2
    #: seconds one virtual tick maps to on the storm's injected clock
    #: (converts the server's load-derived retry_after into ticks)
    storm_tick_seconds: float = 0.05
    #: oracle-twin knob (set by :func:`oracle_spec`): unlimited
    #: admission and zero modeled hold — the never-shed twin every
    #: shed/degraded client must converge byte-identically to
    storm_never_shed: bool = False
    #: streaming fold (ISSUE 16): attach a
    #: :class:`~..service.streamfold.StreamFoldService` to the storm's
    #: in-proc server — committed micro-batches fold once per tick at
    #: ``stream_cadence``, summaries publish to the streaming-head
    #: index, and the oplog truncates behind the newest durable summary
    #: (``stream_retention`` hot-tail floor).  Herd re-entries then
    #: serve from the ``stream`` lane instead of cold folds.
    stream: bool = False
    stream_cadence: int = 8
    stream_retention: int = 64
    #: fail-loud floor on the real-caller election (ISSUE 16 satellite):
    #: a gate that needs at least this many REAL catch-up callers per
    #: document must declare it here — asking for more than
    #: ``storm_clients_per_doc`` admits is a spec error, not a silently
    #: clipped sample.
    storm_min_cohort: int = 0
    #: front-door replicas (ISSUE 18): out-of-proc runs stand up this
    #: many front doors over ONE shard fleet — the primary spawns and
    #: supervises the shards, every additional door ATTACHES to the same
    #: addresses (shared-nothing: replicas agree on placement only
    #: through the deterministic rendezvous router).  The swarm's data
    #: path pins to the NEWEST replica, so a scheduled ``replica.kill``
    #: SIGKILLs the door the traffic actually rides and the adapter must
    #: fail over to a survivor.
    replicas: int = 1
    #: shard backend for out-of-proc runs: ``"proc"`` (real processes,
    #: the default) or ``"thread"`` (in-process ShardHostServers behind
    #: the same real TCP wire — no fork cost, which is what lets a
    #: replica-death drill run in tier-1 time).  ``replica.kill`` works
    #: under either; ``proc.kill``/``proc.hang`` need real processes.
    proc_spawn: str = "proc"

    def __post_init__(self) -> None:
        if self.clients < self.docs:
            raise ValueError("need at least one client per document")
        if self.storm_min_cohort > self.storm_clients_per_doc:
            raise ValueError(
                f"{self.name!r} asks for storm_min_cohort="
                f"{self.storm_min_cohort} real catch-up callers per doc "
                f"but storm_clients_per_doc={self.storm_clients_per_doc} "
                f"silently bounds the election — raise the bound or "
                f"lower the gate")
        if self.stream and not self.storm:
            raise ValueError(
                f"{self.name!r}: stream=True rides the storm's in-proc "
                f"server — set storm=True")
        if self.stream and self.out_of_proc:
            raise ValueError(
                f"{self.name!r}: streaming scenarios run in-proc (shard "
                f"host processes take --stream directly)")
        if self.docs < 1 or self.shards < 1:
            raise ValueError(f"bad docs/shards on {self.name!r}")
        if self.replicas < 1:
            raise ValueError(f"bad replicas on {self.name!r}")
        if self.replicas > 1 and not self.out_of_proc:
            raise ValueError(
                f"{self.name!r}: front-door replicas are an out-of-proc "
                f"topology — set out_of_proc=True")
        if self.proc_spawn not in ("proc", "thread"):
            raise ValueError(
                f"{self.name!r}: proc_spawn must be 'proc' or 'thread'")
        if self.out_of_proc and self.plan is not None:
            allowed = {"proc.kill", "proc.hang", "shard.kill",
                       "replica.kill"}
            bad = [p.label() for p in self.plan.points
                   if p.site not in allowed]
            if bad:
                raise ValueError(
                    f"out-of-proc scenarios only execute scheduled "
                    f"process faults {sorted(allowed)}; plan has {bad}")
            if self.replicas < 2 and any(p.site == "replica.kill"
                                         for p in self.plan.points):
                raise ValueError(
                    f"{self.name!r}: replica.kill needs a survivor — "
                    f"set replicas >= 2")
            if self.proc_spawn == "thread" and any(
                    p.site in ("proc.kill", "proc.hang")
                    for p in self.plan.points):
                raise ValueError(
                    f"{self.name!r}: proc.kill/proc.hang SIGKILL/SIGSTOP "
                    f"real processes — use proc_spawn='proc' (thread "
                    f"shards take shard.kill)")

    @property
    def ticks(self) -> int:
        return sum(p.ticks for p in self.phases)

    def doc_id(self, d: int) -> str:
        return f"sw-{d:04d}"


@dataclasses.dataclass
class SwarmResult:
    """Everything a run measures — all of it deterministic, so the whole
    value doubles as the replay-identity surface."""

    name: str
    seed: int
    clients: int
    docs: int
    shards: int
    ticks: int
    #: sequenced messages across all documents (JOIN/LEAVE included)
    sequenced_ops: int
    #: client OP messages stamped / submitted / dedup'd on resubmit
    ops_stamped: int
    ops_submitted: int
    ops_deduped: int
    joins: int
    #: virtual-tick latency until the SLOWEST steady client consumed a
    #: message (per sequenced message)
    delivery_p50_ticks: float
    delivery_p99_ticks: float
    delivery_samples: int
    #: virtual ticks from catch-up start to reaching the head
    catchup_p50_ticks: float
    catchup_p99_ticks: float
    catchup_samples: int
    #: deepest head-minus-cursor backlog any client reached
    max_pending_depth: int
    #: (tick, doc_index, ops consumed) per deferred batch
    defers: Tuple[tuple, ...]
    #: (tick, doc_index, clients joined) per deferred JOIN cohort
    join_defers: Tuple[tuple, ...]
    #: (tick, killed shard, docs re-owned) per executed failover
    kills: Tuple[tuple, ...]
    #: (tick, door index) per executed front-door replica kill
    replica_kills: Tuple[tuple, ...]
    per_doc_head: Dict[str, int]
    #: sampled doc -> final summary digest (real Loader load at the end)
    sampled_digests: Dict[str, str]
    #: injector ``site:kind`` observations (empty when no plan)
    fault_counts: Dict[str, int]
    #: swarm + broadcaster counters
    counters: Dict[str, int]
    #: per-phase counter attribution (CounterSet.delta over each phase)
    phase_counters: Dict[str, Dict[str, int]]
    #: ingress-stage accounting (IngressMeter.snapshot()): wall-derived,
    #: NOT part of the replay-identity surface
    ingress: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: out-of-proc runs: per-shard stats pulled over the ``stats`` RPC
    #: plus live-tap delivery accounting — carries pids and async frame
    #: counts, so (like ``ingress``) excluded from replay identity
    shard_stats: Dict[str, object] = dataclasses.field(default_factory=dict)
    #: ``spec.fold_probe`` runs: catch-up fold-tier counters over the
    #: sampled docs (device-resident / delta / pack cache stats + the
    #: h2d/d2h byte split) — busy seconds are wall-derived, so (like
    #: ``ingress``) excluded from replay identity
    fold_tier: Dict[str, object] = dataclasses.field(default_factory=dict)
    #: ``spec.storm`` runs: the catch-up storm report (per-lane counts,
    #: p50/p99 storm latency in virtual ticks, admission + tier-cache
    #: snapshots, per-phase tier stats).  The DETERMINISTIC essentials
    #: (requests/warm/folds/shed/degraded/retries) are mirrored into
    #: ``counters`` as ``swarm.storm_*`` for in-proc runs — those ARE
    #: replay identity; this dict additionally carries wall-derived
    #: stage seconds and (out of proc) remote verdicts, so the dict
    #: itself is excluded like ``ingress``.
    storm: Dict[str, object] = dataclasses.field(default_factory=dict)

    def identity(self) -> dict:
        """The bit-identity surface: every field, canonically shaped —
        except ``ingress``/``shard_stats``/``fold_tier``/``storm``,
        which carry wall-clock / process derived detail and are
        excluded (the storm's deterministic counters ride ``counters``
        instead)."""
        out = dataclasses.asdict(self)
        out.pop("ingress", None)
        out.pop("shard_stats", None)
        out.pop("fold_tier", None)
        out.pop("storm", None)
        return out


# -- named scenarios ----------------------------------------------------------


def _steady_typing(seed, clients, docs, shards) -> ScenarioSpec:
    """Ramp to full population, then steady typing traffic end to end."""
    return ScenarioSpec(
        name="steady-typing", seed=seed, clients=clients, docs=docs,
        shards=shards,
        phases=(Phase("ramp", 24), Phase("steady", 120)),
    )


def _catchup_herd(seed, clients, docs, shards) -> ScenarioSpec:
    """A cohort goes dark mid-run and returns as one catch-up herd.

    The bursty reconnect-storm shape: 30% of the steady population stops
    submitting and draining for a window, then re-enters together and
    drains its backlog at the catch-up rate."""
    return ScenarioSpec(
        name="catchup-herd", seed=seed, clients=clients, docs=docs,
        shards=shards,
        phases=(Phase("ramp", 16), Phase("steady", 48),
                Phase("herd", 40, frac=0.3), Phase("steady", 48)),
    )


def _laggard_window(seed, clients, docs, shards) -> ScenarioSpec:
    """Staggered laggards keep typing against frozen views (MSN pin).

    20% of the swarm stops draining in individually-staggered windows
    while still submitting — their frozen views pin the MSN — and each
    recovers through a catch-up burst."""
    return ScenarioSpec(
        name="laggard-window", seed=seed, clients=clients, docs=docs,
        shards=shards,
        phases=(Phase("ramp", 16), Phase("steady", 32),
                Phase("laggards", 80, frac=0.2), Phase("steady", 32)),
    )


def _tree_collab(seed, clients, docs, shards) -> ScenarioSpec:
    """SharedTree collab swarm: boxed tree changesets + a catch-up herd.

    Every client edits a shared tree channel (leaf inserts / LWW value
    sets / removes under root fields, all id-addressed); a cohort goes
    dark mid-run and returns as one herd.  The sampled documents then
    catch up cold+warm through the REAL CatchupService tree route
    (``fold_probe``), so the report carries the second kernel family's
    resident / delta / pack tier counters (ISSUE 14)."""
    return ScenarioSpec(
        name="tree-collab", seed=seed, clients=clients, docs=docs,
        shards=shards, tree_ops=True,
        phases=(Phase("ramp", 16), Phase("steady", 48),
                Phase("herd", 32, frac=0.25), Phase("steady", 32)),
    )


def _catchup_storm(seed, clients, docs, shards) -> ScenarioSpec:
    """A dark cohort returns as a catch-up STORM through the real fold tier.

    30% of the steady population goes dark, then re-enters together —
    and the re-entry herd is converted into real
    ``CatchupService.catch_up`` calls (``storm_clients_per_doc`` real
    callers elected per document; the cohort remainder models
    consumption columnar) against the server's adaptive-admission fold
    lane: warm-cache bypass, load-derived shed pacing honored under
    VirtualClock, degraded serving under sustained overload, and the
    ``catchup.slow``/``catchup.fail`` fault seams, all deterministic
    and replay bit-identical (ISSUE 15).  A mid-run election freshens
    the stored summaries degraded serving answers from."""
    phases = (Phase("ramp", 16), Phase("steady", 40), Phase("election"),
              Phase("herd", 32, frac=0.3), Phase("steady", 40))
    plan = FaultPlan(seed=seed, points=(
        # The 2nd admitted fold is slow (0.2 s on the injected clock =
        # 4 ticks): the measured-cost EMA, and with it the shed pacing,
        # must adapt.  The 5th admitted fold dies: single-flight
        # finally-abandon + admission release + caller retry.
        FaultPoint("catchup.slow", "delay", at=2, arg=0.2),
        FaultPoint("catchup.fail", "fail", at=5),
    ))
    return ScenarioSpec(
        name="catchup-storm", seed=seed, clients=clients, docs=docs,
        shards=shards, storm=True, plan=plan, phases=phases)


def _failover_drill(seed, clients, docs, shards) -> ScenarioSpec:
    """Mid-run shard kill between summary elections, under live traffic.

    A FaultPlan-scheduled kill fences one shard's orderers, bumps the
    storage epoch, and lazily re-owns its documents while the swarm keeps
    typing; summary elections bracket the failover."""
    phases = (Phase("ramp", 16), Phase("steady", 40), Phase("election"),
              Phase("steady", 40), Phase("election"), Phase("steady", 40))
    total = sum(p.ticks for p in phases)
    plan = FaultPlan(seed=seed, points=(
        FaultPoint("shard.kill", "kill", doc="sw-0000", at=total // 2),
    ))
    return ScenarioSpec(
        name="failover-drill", seed=seed, clients=clients, docs=docs,
        shards=shards, phases=phases, plan=plan,
    )


#: name -> builder(seed, clients, docs, shards); the builder docstring's
#: first line is the one-line doc ``tools/loadgen.py --list`` prints.
SCENARIOS: Dict[str, Callable[..., ScenarioSpec]] = {
    "steady-typing": _steady_typing,
    "catchup-herd": _catchup_herd,
    "laggard-window": _laggard_window,
    "tree-collab": _tree_collab,
    "catchup-storm": _catchup_storm,
    "failover-drill": _failover_drill,
}


def build_scenario(name: str, seed: int = 0, clients: int = 1000,
                   docs: int = 16, shards: int = 4) -> ScenarioSpec:
    builder = SCENARIOS.get(name)
    if builder is None:
        raise KeyError(
            f"unknown scenario {name!r} (have: {', '.join(SCENARIOS)})")
    return builder(seed, clients, docs, shards)


def scenario_docs() -> Dict[str, str]:
    """{name: one-line description} for CLI listings."""
    return {
        name: (builder.__doc__ or "").strip().splitlines()[0]
        for name, builder in SCENARIOS.items()
    }


# -- the engine ---------------------------------------------------------------


class _SwarmSink:
    """Broadcaster sink for the swarm: accepts every frame (counting
    them — the serialize-once pin) and rides fences quietly; per-client
    delivery is modeled columnar, not per sink."""

    def __init__(self, counters: CounterSet) -> None:
        self._counters = counters

    def write_frame(self, data: bytes) -> bool:
        self._counters.bump("swarm.frames")
        return True

    def write_signal(self, data: bytes, signal: dict) -> bool:
        return True

    def on_demoted(self, doc_id: str, head_seq: int) -> None:
        raise AssertionError("swarm sink accepts everything")

    def on_fence(self, doc_id: str, epoch: str, head_seq: int) -> None:
        self._counters.bump("swarm.sink_fences")


class _StormSession:
    """Session shim for driving ``OrderingServer._dispatch`` in-proc
    (the storm server is never started — no sockets, no tenants)."""

    tenant = None


class _CatchupStorm:
    """Deterministic catch-up storm driver (ISSUE 15): the loop-closer
    between the swarm engine and the fold tier.

    **In-proc**: builds a REAL :class:`~..service.server.OrderingServer`
    (never started — no sockets) over the swarm's sharded service and
    drives its catchup entry per storming client, sequentially, off a
    dedicated VirtualClock.  Fold-slot occupancy is modeled in virtual
    time (``catchup_hold_seconds`` = ``storm_fold_ticks`` ×
    ``storm_tick_seconds``), so sequentially-driven folds OVERLAP on
    the admission controller's clock and every shed / degrade / warm /
    retry decision is a pure function of ``(seed, spec)`` — the whole
    storm replays bit-identically, counters included.  Shed clients
    honor the server's load-derived ``retry_after`` (converted to
    ticks) before retrying.

    **Out-of-proc**: issues the real ``catchup`` RPC through the front
    door to the owning shard process.  The shard runs WIRE-CLOCK
    admission (ISSUE 18): its controller advances only on the ``vnow``
    each request carries, requests go out sequentially on one
    connection, and the remote verdict sequence becomes the same pure
    function of ``(seed, spec)`` as in-proc — verdict counters rejoin
    the replay-identity surface, and only transport noise (timeouts,
    dead sockets, their retries) stays in the identity-excluded
    ``SwarmResult.storm`` report.
    """

    #: defensive bound — the acceptance criterion is ZERO unbounded
    #: queueing, so a client that cannot get served in this many
    #: attempts is a bug, not pacing.
    MAX_ATTEMPTS = 64

    def __init__(self, swarm: "ClientSwarm") -> None:
        self.swarm = swarm
        spec = swarm.spec
        #: tick -> storm client indices due (first attempt or retry)
        self.due: Dict[int, List[int]] = {}
        self.start_tick: Dict[int, int] = {}
        self.attempts: Dict[int, int] = {}
        self.latencies: List[int] = []
        self.remote: Dict[str, int] = {}
        self.phase_tiers: Dict[str, object] = {}
        self._session = _StormSession()
        self.clock = None
        self.server = None
        self.streamfold = None
        #: cohort members the storm_clients_per_doc bound clipped out of
        #: the real-caller election (they stay columnar-modeled) — the
        #: PR 15 silent bound, surfaced (ISSUE 16 satellite)
        self.elected = 0
        self.clipped = 0
        #: fold-cost EMA twin (ISSUE 19 satellite): the harness re-runs
        #: the admission controller's cost arithmetic from ITS OWN
        #: observations — in-proc the virtual-clock delta across the
        #: dispatch plus the modeled hold, out-of-proc the modeled hold
        #: alone (wire-clock admission admits and releases at the same
        #: observed vnow) — and every shed nack's snapshot ``cost_ema``
        #: must reproduce it, or the storm fails loudly: a server whose
        #: pacing derives from costs the harness never observed is
        #: lying or buggy.  Keyed per admission domain (the one in-proc
        #: server, or the owning shard id out-of-proc — each shard
        #: paces from its own controller).
        from ..service.server import ADMISSION_COST_INIT
        self.ema_twin: Dict[str, float] = {}
        self.ema_checks = 0
        self.ema_skips = 0
        #: set when transport noise makes the twin unverifiable (a lost
        #: request may still have folded server-side; a respawned shard
        #: comes back with a RESET controller) — checks are then
        #: SKIPPED and counted, never silently passed.
        self.ema_taint: Optional[str] = None
        self._cost_init = ADMISSION_COST_INIT
        #: modeled fold duration (seconds of lease occupancy after the
        #: synchronous fold returns) — the same value the in-proc server
        #: gets on ``catchup_hold_seconds`` and the out-of-proc shards
        #: get via ``--catchup-hold``.
        self.fold_hold = (0.0 if spec.storm_never_shed
                          else spec.storm_fold_ticks
                          * spec.storm_tick_seconds)
        #: out-of-proc storms run WIRE-CLOCK admission (ISSUE 18): the
        #: shard's controller advances only on the vnow each catchup
        #: request carries, the harness issues requests sequentially on
        #: one connection, and the verdict sequence becomes a pure
        #: function of request order — so verdict counters rejoin the
        #: replay-identity surface.  Transport noise (timeouts, socket
        #: errors and the retries they cause) stays identity-excluded.
        self.wire_clock = spec.out_of_proc
        if not spec.out_of_proc:
            from ..service.server import OrderingServer
            from ..utils.telemetry import ConfigProvider, MonitoringContext

            self.clock = VirtualClock(tick=0.0001)
            max_inflight = (1 << 30 if spec.storm_never_shed
                            else spec.storm_max_inflight)
            self.server = OrderingServer(
                swarm.service, catchup_max_inflight=max_inflight,
                faults=swarm.injector, clock=self.clock,
                mc=MonitoringContext(config=ConfigProvider({
                    "Catchup.DegradeAfter": spec.storm_degrade_after,
                })))
            if not spec.storm_never_shed:
                self.server.catchup_hold_seconds = (
                    spec.storm_fold_ticks * spec.storm_tick_seconds)
            if spec.stream:
                # Streaming fold rides the SAME server the storm drives:
                # the commit hook attaches to the swarm's real service,
                # and step() polls once per virtual tick.
                self.streamfold = self.server.enable_streaming(
                    cadence_ops=spec.stream_cadence,
                    retention_floor=spec.stream_retention)

    # -- scheduling ------------------------------------------------------------

    def enlist(self, t: int, cohort: np.ndarray) -> None:
        """A re-entry cohort formed at tick ``t-1``: elect the first
        ``storm_clients_per_doc`` members of each document's cohort
        (client-index order — deterministic) as REAL catch-up callers
        due at ``t``; the rest stay columnar-modeled."""
        if cohort.size == 0:
            return
        k = max(0, int(self.swarm.spec.storm_clients_per_doc))
        if k == 0:
            return
        docs = self.swarm.doc_of[cohort]
        order = np.argsort(docs, kind="stable")
        members = cohort[order]
        docs = docs[order]
        cuts = np.flatnonzero(np.diff(docs)) + 1
        starts = np.concatenate([[0], cuts])
        ends = np.concatenate([cuts, [members.size]])
        chosen: List[int] = []
        for s, e in zip(starts.tolist(), ends.tolist()):
            take = min(e, s + k)
            chosen.extend(int(i) for i in members[s:take])
            self.clipped += e - take
        self.elected += len(chosen)
        spread = max(1, int(self.swarm.spec.storm_spread_ticks))
        jitter = _hash_clients(self.swarm.spec.seed, 41,
                               np.asarray(chosen, dtype=np.int64))
        for i, j in zip(chosen, (jitter % np.uint64(spread)).tolist()):
            due_t = t + int(j)
            self.due.setdefault(due_t, []).append(i)
            self.start_tick[i] = due_t
            self.attempts[i] = 0
        self.swarm.counters.bump("swarm.storm_requests", len(chosen))

    def pending(self) -> bool:
        return bool(self.due)

    # -- the per-tick step -----------------------------------------------------

    def step(self, t: int) -> None:
        if self.clock is not None:
            # One swarm tick of storm time: previously-held fold leases
            # age toward expiry on the admission controller's clock.
            self.clock.sleep(self.swarm.spec.storm_tick_seconds)
        if self.streamfold is not None:
            # One streaming round per virtual tick.  step() runs after
            # the tick's ingress group commit closed, so the truncation
            # marker's flush commit point is real — poll() must never
            # run inside an open oplog.batch().
            self.streamfold.poll()
        # Everything due AT OR BEFORE t: the run loop skips storm steps
        # across the phase→quiescence boundary (those ticks advance ``t``
        # without a step), and an entry stranded at a skipped tick would
        # otherwise never pop — ``pending()`` stays true and the drain
        # loop spins forever.
        wave: List[int] = []
        for tick in sorted(k for k in self.due if k <= t):
            wave.extend(self.due.pop(tick))
        if not wave:
            return
        for i in wave:
            self.attempts[i] += 1
            if self.attempts[i] > self.MAX_ATTEMPTS:
                raise AssertionError(
                    f"storm client {i} not served after "
                    f"{self.MAX_ATTEMPTS} attempts — unbounded queueing")
            if self.server is not None:
                self._issue_inproc(i, t)
            else:
                self._issue_proc(i, t)

    def _bump(self, name: str, by: int = 1) -> None:
        """Verdict accounting: in-proc verdicts are deterministic and
        land in the swarm counters (the replay-identity surface).
        Out-of-proc verdicts USED to be wall-clock shaped and rode only
        the identity-excluded ``storm`` report; under wire-clock
        admission (ISSUE 18) they are deterministic too and rejoin the
        identity counters — only transport NOISE (fold errors and the
        retries they cause, bumped explicitly into ``remote``) stays
        excluded, because a request timeout under load must never flip
        ``replay_identical``."""
        if self.server is not None or self.wire_clock:
            self.swarm.counters.bump(name, by)
        else:
            self.remote[name] = self.remote.get(name, 0) + by

    def _count(self, name: str) -> int:
        if self.server is not None or self.wire_clock:
            return self.swarm.counters.get(name)
        return self.remote.get(name, 0)

    def _noise(self, name: str, by: int = 1) -> None:
        """Non-deterministic accounting (transport errors, their
        retries): identity-excluded by construction."""
        self.remote[name] = self.remote.get(name, 0) + by

    def _ema_observe(self, key: str, cost: float) -> None:
        """One fold lease released: fold its observed cost into the
        twin with the controller's own arithmetic (release(), EMA 1/2 —
        including the cost>0 guard, so zero-cost releases leave the
        twin untouched exactly as they leave the controller's EMA)."""
        if cost > 0.0:
            self.ema_twin[key] = (0.5 * self.ema_twin.get(
                key, self._cost_init) + 0.5 * cost)

    def _ema_check(self, key: str, snap) -> None:
        """The storm-verdict tolerance gate (ISSUE 19 satellite): the
        shed nack's snapshot ``cost_ema`` must reproduce the harness's
        own observed fold costs.  In-proc the tolerance covers the
        server's OWN clock reads between admit and release (each
        VirtualClock read advances one tick the harness cannot see);
        out-of-proc wire-clock admission is exact up to the snapshot's
        1e-6 rounding.  Tainted runs (transport noise, respawns) skip
        the check and COUNT the skip — never a silent pass."""
        if not snap or "cost_ema" not in snap:
            return
        if self.server is None and self.ema_taint is None:
            if self.swarm.counters.get("swarm.kills"):
                self.ema_taint = "shard-kill (controller reset on respawn)"
            elif getattr(self.swarm.service, "door_failovers", 0):
                self.ema_taint = "door-failover (resend may have folded)"
        if self.ema_taint is not None:
            self.ema_skips += 1
            return
        twin = self.ema_twin.get(key, self._cost_init)
        tol = 50 * self.clock.tick if self.clock is not None else 1e-5
        self.ema_checks += 1
        if abs(float(snap["cost_ema"]) - twin) > tol:
            raise AssertionError(
                f"admission snapshot cost_ema {snap['cost_ema']!r} does "
                f"not reproduce the harness-observed fold-cost EMA "
                f"{twin!r} for {key!r} (tolerance {tol!r}): the shed "
                f"pacing derives from costs the harness never saw")

    def _retry(self, i: int, t: int, after_ticks: int,
               noise: bool = False) -> None:
        self.due.setdefault(t + max(1, after_ticks), []).append(i)
        if noise:
            self._noise("swarm.storm_retries")
        else:
            self._bump("swarm.storm_retries")

    def _serve(self, i: int, t: int, out: dict) -> None:
        """Record one successful catchup answer and verify it.  The
        served ``(handle, seq)`` is integrity-checked (a readable
        summary at a seq the durable log actually holds), but the
        client's consumption CURSOR is deliberately untouched: admission
        verdicts differ between a shedding run and its never-shed
        oracle twin, and any cursor influence would shift the client's
        later fire schedule and ref_seqs — forking the logs the oracle
        methodology pins byte-identical.  Sheds and degrades cost
        LATENCY (recorded here in virtual ticks), never state; the
        cohort drains columnar at ``catchup_rate`` either way — the
        "sampled real folds + columnar-modeled remainder" split.  (The
        100k matrix CAUGHT the cursor-jump variant of this harness:
        divergent served seqs leaked into op ref_seqs and the sampled
        digests split from the oracle.)"""
        swarm = self.swarm
        doc_id = swarm.doc_ids[int(swarm.doc_of[i])]
        served = out["docs"].get(doc_id)
        if served is not None:
            handle, seq = served
            if int(seq) > int(swarm.head_arr[swarm.doc_of[i]]):
                raise AssertionError(
                    f"catchup served {doc_id} at seq {seq} beyond the "
                    f"durable head {int(swarm.head_arr[swarm.doc_of[i]])}")
            if self.server is not None:
                # In-proc: the handle must resolve in the shared store —
                # a degraded serve hands out a REAL stored summary, not
                # a fabrication.
                swarm.service.storage.read(handle)
        lane = out.get("lane", "fold")
        self._bump({
            "warm": "swarm.storm_warm",
            "fold": "swarm.storm_folds",
            "degraded": "swarm.storm_degraded",
            "stream": "swarm.storm_stream",
        }.get(lane, "swarm.storm_folds"))
        self._bump("swarm.storm_served")
        self.latencies.append(t - self.start_tick[i])

    def _issue_inproc(self, i: int, t: int) -> None:
        swarm = self.swarm
        doc_id = swarm.doc_ids[int(swarm.doc_of[i])]
        # ``.now`` is the non-advancing read: the before/after pair must
        # not itself tick the clock the server's admission reads from.
        before = self.clock.now
        try:
            out = self.server._dispatch(self._session, "catchup",
                                        {"docs": [doc_id]})
        except NackError as exc:
            # Load-derived pacing honored in virtual ticks — the shed
            # client waits the server's own hold, never less.  The nack
            # carries the controller snapshot; its cost_ema must match
            # the harness's own fold-cost observations.
            self._bump("swarm.storm_shed")
            self._ema_check("inproc", getattr(exc, "admission", None))
            ticks = int(round(float(exc.retry_after)
                              / swarm.spec.storm_tick_seconds))
            self._retry(i, t, ticks)
            return
        except OSError:
            # Injected catchup.fail (FaultError ⊂ OSError): the fold
            # died after admission — slot released, single-flight
            # waiters woken by the finally-abandon; the caller retries.
            # The finally released WITH the hold, so the failed fold's
            # cost still landed in the pacing EMA — mirror it.
            self._ema_observe("inproc",
                              (self.clock.now - before) + self.fold_hold)
            self._bump("swarm.storm_fold_errors")
            self._retry(i, t, 1)
            return
        if out.get("lane", "fold") == "fold":
            # A real fold held a lease: its released cost (the virtual
            # time the dispatch consumed — catchup.slow sleeps land
            # here — plus the modeled hold) is what the controller's
            # EMA folded in.  Warm/stream/degraded serves never took a
            # lease and never touch the EMA.
            self._ema_observe("inproc",
                              (self.clock.now - before) + self.fold_hold)
        self._serve(i, t, out)

    def _issue_proc(self, i: int, t: int) -> None:
        """One REAL catchup RPC through the front door (with door
        failover — a replica SIGKILL mid-storm rotates to a survivor and
        resends).  The request carries the wire clock: ``vnow`` is the
        storm's own virtual time, and the shard's virtual admission
        controller advances on nothing else — same pacing model as the
        in-proc storm, across a real process boundary."""
        from ..drivers.network_driver import RpcError

        swarm = self.swarm
        doc_id = swarm.doc_ids[int(swarm.doc_of[i])]
        shard = str(swarm.service.router.owner(doc_id))
        try:
            out = swarm.service.request("catchup", {
                "docs": [doc_id],
                "vnow": t * swarm.spec.storm_tick_seconds})
        except NackError as exc:
            self._bump("swarm.storm_shed")
            retry = float(exc.retry_after)
            snap = getattr(exc, "admission", None)
            if self.wire_clock and snap:
                # ISSUE 18 satellite: the nack carries the shard's
                # admission snapshot, and the pacing must RE-DERIVE from
                # the reported fold-cost EMA — drift between the
                # snapshot and the verdict's retry_after is a bug, not
                # rounding (cost_ema ships rounded to 1e-6).
                backlog = int(snap["inflight"]) + int(snap["shed_streak"])
                derived = min(float(snap["retry_cap"]), max(
                    float(snap["retry_floor"]),
                    float(snap["cost_ema"]) * backlog
                    / max(1, int(snap["max_inflight"]))))
                if abs(derived - retry) > 1e-4:
                    raise AssertionError(
                        f"admission snapshot does not reproduce the "
                        f"shed pacing: derived {derived!r} vs wire "
                        f"retry_after {retry!r} ({snap!r})")
                retry = derived
                # ISSUE 19 satellite: the reported cost_ema itself must
                # reproduce the harness's own fold-cost observations
                # for this shard's admission domain.
                self._ema_check(shard, snap)
            ticks = int(round(retry / swarm.spec.storm_tick_seconds))
            self._retry(i, t, ticks)
            return
        except (RpcError, OSError) as exc:
            # Transport noise: wall-clock shaped, identity-excluded —
            # and it taints the EMA twin (the lost request may still
            # have folded, and released, server-side).
            if self.ema_taint is None:
                self.ema_taint = f"transport:{type(exc).__name__}"
            self._noise("swarm.storm_fold_errors")
            self._noise(f"error:{type(exc).__name__}")
            self._retry(i, t, 1, noise=True)
            return
        if out.get("lane", "fold") == "fold":
            # Wire-clock admission admits and releases a sequential
            # request at the SAME observed vnow: the lease's released
            # cost is exactly the modeled hold.
            self._ema_observe(shard, self.fold_hold)
        self._serve(i, t, out)

    # -- reporting -------------------------------------------------------------

    def _tier_stats(self):
        if self.server is None:
            return None
        catchup = self.server._catchup
        if catchup is None:
            return None
        return {
            "cache": (catchup.cache.stats()
                      if catchup.cache is not None else None),
            "delta_cache": (catchup.delta_cache.stats()
                            if catchup.delta_cache is not None else None),
            "pack_cache": (catchup._pack_cache.stats()
                           if catchup._pack_cache is not None else None),
            "device_cache": (catchup.device_cache.stats()
                             if catchup.device_cache is not None
                             else None),
        }

    def phase_mark(self, key: str) -> None:
        """Cumulative tier-cache snapshot at one phase boundary — the
        per-phase hit-rate record the storm bench reads (diff adjacent
        snapshots for a phase's own traffic)."""
        self.phase_tiers[key] = self._tier_stats()

    def summary(self) -> Dict[str, object]:
        lat = sorted(self.latencies)
        folds = self._count("swarm.storm_folds")
        shed = self._count("swarm.storm_shed")
        degraded = self._count("swarm.storm_degraded")
        lane_total = folds + shed + degraded
        out: Dict[str, object] = {
            "mode": "proc" if self.server is None else "inproc",
            # Wire-clock storms (every out-of-proc storm now): verdict
            # counters are deterministic and live in the swarm counters;
            # ``remote`` below carries only transport noise.
            "wire_clock": self.wire_clock,
            "requests": self.swarm.counters.get("swarm.storm_requests"),
            # The real-caller election bound, surfaced: gates sampling
            # "real folds" must read the bound they sampled under, and
            # how many cohort members it clipped to columnar modeling.
            "clients_per_doc_bound":
                self.swarm.spec.storm_clients_per_doc,
            "elected": self.elected,
            "cohort_clipped": self.clipped,
            "served": self._count("swarm.storm_served"),
            "warm": self._count("swarm.storm_warm"),
            "stream": self._count("swarm.storm_stream"),
            "folds": folds,
            "shed": shed,
            "degraded": degraded,
            "retries": (self._count("swarm.storm_retries")
                        + self.remote.get("swarm.storm_retries", 0)),
            "fold_errors": (self._count("swarm.storm_fold_errors")
                            + self.remote.get("swarm.storm_fold_errors",
                                              0)),
            "shed_rate": (round(shed / lane_total, 4)
                          if lane_total else None),
            "latency_p50_ticks": float(percentile(lat, 0.50)),
            "latency_p99_ticks": float(percentile(lat, 0.99)),
            "latency_samples": len(lat),
            "tiers": self._tier_stats(),
            "phase_tiers": self.phase_tiers,
        }
        # ISSUE 19 satellite — the cost_ema cross-check is part of the
        # storm VERDICT: a storm that shed must have audited (or
        # explicitly skipped, taint recorded) at least one snapshot; a
        # server that stops shipping auditable snapshots fails loudly
        # instead of sailing through unchecked.
        if shed and not (self.ema_checks + self.ema_skips):
            raise AssertionError(
                f"{shed} shed verdict(s) carried no auditable admission "
                f"snapshot — the cost_ema cross-check never ran")
        out["ema_crosscheck"] = {
            "checks": self.ema_checks,
            "skipped": self.ema_skips,
            "tainted": self.ema_taint,
            "twin": {k: round(v, 6)
                     for k, v in sorted(self.ema_twin.items())},
        }
        if self.server is not None:
            out["admission"] = self.server.admission.snapshot()
            out["admission_control"] = \
                self.server.admission_control.snapshot()
        else:
            out["remote"] = dict(sorted(self.remote.items()))
        if self.streamfold is not None:
            out["streamfold"] = self.streamfold.stats()
        return out


class ClientSwarm:
    """The columnar client population plus the real service it drives.

    One instance = one run; :func:`run_swarm` is the entry point.
    """

    def __init__(self, spec: ScenarioSpec) -> None:
        self.spec = spec
        n, docs = spec.clients, spec.docs
        self.counters = CounterSet(
            "swarm.ops_submitted", "swarm.ops_stamped", "swarm.ops_deduped",
            "swarm.joins", "swarm.defers", "swarm.join_defers",
            "swarm.elections",
            "swarm.catchup_completions", "swarm.delivery_samples",
            "swarm.frames", "swarm.sink_fences", "swarm.kills",
            "swarm.replica_kills",
            # catch-up storm (ISSUE 15): deterministic for in-proc runs,
            # hence part of the replay-identity surface
            "swarm.storm_requests", "swarm.storm_served",
            "swarm.storm_warm", "swarm.storm_folds", "swarm.storm_shed",
            "swarm.storm_degraded", "swarm.storm_retries",
            "swarm.storm_fold_errors",
            # streaming-head serves (ISSUE 16): a catch-up answered from
            # the continuously-published summary index — no fold, no
            # admission
            "swarm.storm_stream",
        )
        # -- columnar per-client state (the whole point) ----------------
        idx = np.arange(n, dtype=np.int64)
        #: contiguous doc blocks: doc d owns clients [starts[d], starts[d+1])
        self.doc_of = (idx * docs // n).astype(np.int32)
        self.doc_starts = np.searchsorted(self.doc_of, np.arange(docs))
        self.state = np.zeros(n, dtype=np.int8)   # _UNBORN
        self.cursor = np.zeros(n, dtype=np.int64)
        self.client_seq = np.zeros(n, dtype=np.int64)
        self.op_count = np.zeros(n, dtype=np.int64)
        #: tree-collab: nodes each client has inserted so far — target
        #: ids for its sets/removes derive from this count, so every
        #: referenced id was inserted by the same client earlier in its
        #: own (sequencer-ordered) stream.
        self.tree_created = np.zeros(n, dtype=np.int64)
        self.next_fire = np.full(n, np.iinfo(np.int64).max, dtype=np.int64)
        self.catchup_start = np.zeros(n, dtype=np.int64)
        self.lag_start = np.full(n, -1, dtype=np.int64)
        self.lag_end = np.full(n, -1, dtype=np.int64)
        # cadence: period ≈ active ticks / ops_per_client, jittered per
        # client so fires de-synchronize (independent of population)
        active = max(1, sum(p.ticks for p in self.spec.phases
                            if p.kind in ("steady", "herd", "laggards")))
        base = max(3, int(round(active / max(0.25, spec.ops_per_client))))
        jitter = _hash_clients(spec.seed, 11, idx) % np.uint64(base)
        self.period = (base + jitter.astype(np.int64)).astype(np.int64)
        # ramp schedule: spread connects over the FIRST ramp phase (or
        # connect everyone at tick 0 when the scenario has none)
        ramp_at, ramp_ticks = 0, 0
        at = 0
        for p in spec.phases:
            if p.kind == "ramp":
                ramp_at, ramp_ticks = at, p.ticks
                break
            at += p.ticks
        if ramp_ticks > 0:
            spread = _hash_clients(spec.seed, 13, idx) % np.uint64(ramp_ticks)
            self.connect_at = ramp_at + spread.astype(np.int64)
        else:
            self.connect_at = np.zeros(n, dtype=np.int64)
        #: precomputed wire client ids (also the JOIN batch payload)
        within = (idx - self.doc_starts[self.doc_of]).astype(np.int64)
        self.client_ids = [
            f"sw{spec.seed}-d{int(d):04d}-c{int(c)}"
            for d, c in zip(self.doc_of, within)
        ]
        # -- the real service -------------------------------------------
        self.injector = (FaultInjector(spec.plan)
                         if spec.plan is not None else None)
        self._cluster = None
        self._tmpdir = None
        #: attach-mode front doors over the primary's shard fleet
        #: (``spec.replicas`` > 1); the data path pins to the last one.
        self._replicas: list = []
        #: scheduled replica kills executed: ``(tick, door_index)``
        self.replica_kills: List[Tuple[int, int]] = []
        self._proc_taps: Dict[str, object] = {}
        self._proc_frames: Dict[str, set] = {}
        if spec.out_of_proc:
            # The REAL process tier: shard-host processes with per-shard
            # durable logs behind an in-process front door (the harness
            # drives its fault-plan tick and reads its stats directly).
            import os as _os
            import tempfile as _tempfile

            from ..drivers.network_driver import \
                NetworkDocumentServiceFactory
            from ..service.frontdoor import FrontDoor
            from ..service.procclient import ProcServiceClient

            base = spec.dir
            if base is None:
                self._tmpdir = _tempfile.mkdtemp(prefix="fluidproc-swarm-")
                base = self._tmpdir
            _os.makedirs(base, exist_ok=True)
            # Wire-clock admission (ISSUE 18): a storm's shards take the
            # virtual controller so every catchup verdict is a pure
            # function of request order + the vnow each request carries
            # — out-of-proc verdicts rejoin the replay-identity surface.
            shard_args: List[str] = []
            if spec.storm:
                max_inflight = (1 << 30 if spec.storm_never_shed
                                else spec.storm_max_inflight)
                shard_args += [
                    "--virtual-admission",
                    "--catchup-max-inflight", str(max_inflight),
                    "--catchup-degrade-after",
                    str(spec.storm_degrade_after)]
                if not spec.storm_never_shed:
                    shard_args += ["--catchup-hold",
                                   str(spec.storm_fold_ticks
                                       * spec.storm_tick_seconds)]
            self._cluster = FrontDoor(
                _os.path.join(base, "proc"), n_shards=spec.shards,
                spawn=spec.proc_spawn, faults=self.injector,
                shard_args=shard_args,
                request_timeout=5.0).start()
            try:
                # Additional front doors ATTACH to the primary's shard
                # fleet: shared-nothing replicas that agree on placement
                # only through the rendezvous router.  The primary stays
                # the supervisor (fault ticks, respawns); replicas never
                # terminate shards that are not theirs to stop.
                for _r in range(1, spec.replicas):
                    self._replicas.append(FrontDoor(
                        _os.path.join(base, "proc"), spawn="attach",
                        attach_addrs=self._cluster.shard_addrs(),
                        request_timeout=5.0).start())
                self.service = ProcServiceClient(
                    self._cluster, replicas=self._replicas)
                self.factory = NetworkDocumentServiceFactory(
                    port=self._cluster.port)
            except BaseException:
                # Construction failed AFTER the processes spawned: reap
                # them, or every failed setup leaks a live shard fleet.
                for door in self._replicas:
                    door.close()
                self._cluster.close()
                raise
        else:
            if spec.dir is not None:
                import os as _os

                _os.makedirs(spec.dir, exist_ok=True)
                oplog = OpLog(_os.path.join(spec.dir, "swarm-ops.jsonl"),
                              autoflush=True, faults=self.injector)
            else:
                oplog = OpLog(faults=self.injector)
            if spec.shards > 1:
                self.service = ShardedOrderingService(
                    n_shards=spec.shards, oplog=oplog,
                    faults=self.injector)
            else:
                self.service = LocalOrderingService(oplog=oplog)
            self.factory = LocalDocumentServiceFactory(self.service)
        self.loader = Loader(self.factory, clock=VirtualClock())
        self.broadcaster = Broadcaster()
        self._sink = _SwarmSink(self.counters)
        # -- per-doc bookkeeping ----------------------------------------
        self.doc_ids = [spec.doc_id(d) for d in range(docs)]
        self.head_arr = np.zeros(docs, dtype=np.int64)
        #: per doc: tick each seq was stamped at (index seq-1)
        self.stamp_ticks: List[List[int]] = [[] for _ in range(docs)]
        #: per doc: seqs (exclusive floor) already sampled for delivery
        self.delivered_floor = np.zeros(docs, dtype=np.int64)
        self.delivery_lat: List[int] = []
        self.catchup_lat: List[int] = []
        self.max_pending_depth = 0
        self.defers: List[tuple] = []
        self.join_defers: List[tuple] = []
        self.kills: List[tuple] = []
        self.pending: Dict[int, List[RawOperation]] = {}
        self._scripted = {(t, d): k for t, d, k in spec.scripted_defers}
        self._scripted_joins = {(t, d): k
                                for t, d, k in spec.scripted_join_defers}
        self.sampled = [d for d in range(docs)
                        if d % max(1, spec.sample_every) == 0]
        self._doc_index = {doc_id: d
                           for d, doc_id in enumerate(self.doc_ids)}
        #: ingress-stage wall/byte accounting (outside replay identity)
        self.ingress = IngressMeter()
        #: catch-up storm driver (ISSUE 15; None unless spec.storm)
        self._storm = _CatchupStorm(self) if spec.storm else None

    # -- setup -----------------------------------------------------------------

    def _build(self, rt) -> None:
        ds = rt.create_datastore("ds")
        ds.create_channel("sequence-tpu", "text")
        ds.create_channel("map-tpu", "kv")
        ds.create_channel("counter-tpu", "count")
        if self.spec.tree_ops:
            ds.create_channel("tree-tpu", "tree")

    def setup(self) -> None:
        """Create every document through the real Loader (attach summary
        with the three channels), then close the boot client — swarm
        clients JOIN the quorum directly, they never materialize
        containers.  Broadcast sinks attach to the SAMPLED documents
        only (the per-message fan-out consumers the oracle verifies);
        the rest of the population models consumption columnar with no
        live subscribers — exactly the shape that lets the columnar
        ingress skip per-message materialization.  The topology is
        mode-independent, so columnar-on and columnar-off runs count
        identical frames."""
        sampled = set(self.sampled)
        for d, doc_id in enumerate(self.doc_ids):
            c = self.loader.create(doc_id, f"boot-{doc_id}", self._build)
            c.drain()
            c.close()
            if d in sampled:
                if self.spec.out_of_proc:
                    self._tap_proc_doc(doc_id)
                else:
                    self.broadcaster.attach(doc_id,
                                            self.service.endpoint(doc_id),
                                            self._sink)
        if isinstance(self.service, ShardedOrderingService):
            self.service.add_fence_listener(
                lambda _sid, docs, epoch: [
                    self.broadcaster.refence(
                        doc, self.service.endpoint(doc), epoch)
                    for doc in docs
                ]
            )
        self._sync_heads(range(self.spec.docs), tick=0)

    def _tap_proc_doc(self, doc_id: str) -> None:
        """Out-of-proc sampled doc: a LIVE broadcast tap through the
        front-door relay (the real per-message fan-out consumer — the
        shard serves these docs boxed, exactly the in-proc topology).
        Delivery is async wall-time, so the unique-seq accounting lands
        in ``shard_stats`` (outside replay identity)."""
        conn = self.factory.resolve(doc_id).connection()
        seen = self._proc_frames.setdefault(doc_id, set())
        conn.subscribe(lambda msg, s=seen: s.add(msg.seq))
        self._proc_taps[doc_id] = conn

    def _sync_heads(self, doc_indices, tick: int) -> None:
        """Record stamp ticks for every new seq and refresh head_arr.
        Out-of-proc services read heads in ONE bulk RPC (grouped by
        owning shard) instead of one round-trip per document."""
        doc_indices = list(doc_indices)
        ids = [self.doc_ids[d] for d in doc_indices]
        bulk = getattr(self.service, "heads", None)
        heads = (bulk(ids) if bulk is not None
                 else {i: self.service.oplog.head(i) for i in ids})
        for d, doc_id in zip(doc_indices, ids):
            head = heads[doc_id]
            ticks = self.stamp_ticks[d]
            if head > len(ticks):
                ticks.extend([tick] * (head - len(ticks)))
            self.head_arr[d] = head

    # -- per-tick steps --------------------------------------------------------

    def _defer_joins(self, t: int, d: int, members: np.ndarray,
                     joined: int) -> None:
        self.connect_at[members[joined:]] = t + 1
        self.join_defers.append((t, d, joined))
        self.counters.bump("swarm.join_defers")

    def _connect_due(self, t: int) -> None:
        """Batched JOINs for every client whose ramp slot is this tick,
        one ``connect_many`` per document.  A mid-batch failure (injected
        durable fault) defers the unjoined suffix to the next tick — the
        JOIN count is read back from the durable head (one message per
        JOIN), the same whole-truth the oracle twin's scripted mirror
        replays."""
        due = np.flatnonzero((self.state == _UNBORN)
                             & (self.connect_at == t))
        if due.size == 0:
            return
        touched = []
        joined_chunks = []
        session = f"sw{self.spec.seed}"
        # due is ascending and doc blocks are contiguous in client index,
        # so per-doc cohorts are contiguous runs — boundary scan instead
        # of a per-doc mask over the whole due set.
        docs_due = self.doc_of[due]
        cuts = np.flatnonzero(np.diff(docs_due)) + 1
        starts = np.concatenate([[0], cuts])
        ends = np.concatenate([cuts, [due.size]])
        with self.service.oplog.batch():  # JOINs group-commit like ops
            for s, e in zip(starts.tolist(), ends.tolist()):
                d = int(docs_due[s])
                members = due[s:e]
                ids = [self.client_ids[i] for i in members.tolist()]
                doc_id = self.doc_ids[d]
                endpoint = self.service.endpoint(doc_id)
                connect = (endpoint.connect_columns if self.spec.columnar
                           else endpoint.connect_many)
                k = self._scripted_joins.get((t, d))
                with self.ingress.timed():
                    if k is not None:
                        connect(ids[:k], session)
                        self._defer_joins(t, d, members, k)
                        joined = members[:k]
                    else:
                        before = self.service.oplog.head(doc_id)
                        try:
                            connect(ids, session)
                            joined = members
                        except (ConnectionError, OSError):
                            landed = (self.service.oplog.head(doc_id)
                                      - before)
                            self._defer_joins(t, d, members, landed)
                            joined = members[:landed]
                touched.append(d)
                if joined.size:
                    joined_chunks.append(joined)
                    self.counters.bump("swarm.joins", int(joined.size))
                    if self.spec.columnar:
                        self.ingress.columnar_ops += int(joined.size)
                    else:
                        self.ingress.boxed_ops += int(joined.size)
        self._sync_heads(touched, t)
        if not joined_chunks:
            return
        now = np.concatenate(joined_chunks)
        self.state[now] = _STEADY
        self.cursor[now] = self.head_arr[self.doc_of[now]]
        h = _hash_clients(self.spec.seed, 17, now)
        self.next_fire[now] = (
            t + 1 + (h % self.period[now].astype(np.uint64)).astype(np.int64)
        )

    def _fire(self, t: int):
        """Columnar decision core shared by both ingress modes: who fires
        this tick and what each op is — every column derived from the
        counter-based hash, population state stepped vectorized.  Returns
        ``None`` or ``(firing, kind_code, key_i, value, ch_i)`` where
        ``kind_code`` is the closed wire vocabulary and ``value`` carries
        the set value / increment delta."""
        firing = np.flatnonzero(
            ((self.state == _STEADY) | (self.state == _LAGGARD))
            & (self.next_fire <= t))
        if firing.size == 0:
            return None
        self.next_fire[firing] = t + self.period[firing]
        h = _hash_clients(self.spec.seed, 19, firing,
                          extra=self.op_count[firing])
        kind = (h % np.uint64(100)).astype(np.int64)
        key_i = ((h >> np.uint64(8)) % np.uint64(32)).astype(np.int64)
        val = ((h >> np.uint64(16)) % np.uint64(1000)).astype(np.int64)
        ch_i = ((h >> np.uint64(24)) % np.uint64(26)).astype(np.int64)
        self.op_count[firing] += 1
        self.client_seq[firing] += 1
        self.counters.bump("swarm.ops_submitted", int(firing.size))
        kind_code = np.where(
            kind < 60, COL_KIND_SET,
            np.where(kind < 85, COL_KIND_INCREMENT, COL_KIND_INSERT)
        ).astype(np.int8)
        delta = val % 7 - 3
        delta[delta == 0] = 1
        value = np.where(kind_code == COL_KIND_INCREMENT, delta, val)
        return firing, kind_code, key_i, value, ch_i

    def _tree_edit(self, i: int, k: int, key_i: int, value: int,
                   ch_i: int) -> dict:
        """One client's tree changeset for this fire: the columnar plan's
        (kind, key, value, char) columns mapped onto id-addressed edits.
        Inserts mint ``{client}-n{count}`` leaf ids (globally unique by
        construction); sets/removes target the client's OWN earlier
        inserts — removing an already-removed id is the first-remover-
        wins no-op, setting a purged id the oracle's silent drop, both
        byte-exact on the device fold."""
        created = int(self.tree_created[i])
        cid = self.client_ids[i]
        if k == COL_KIND_INSERT or created == 0:
            nid = f"{cid}-n{created}"
            self.tree_created[i] = created + 1
            return {"kind": "insert", "parent": "",
                    "field": f"f{ch_i % 2}", "anchor": None,
                    "content": [{"id": nid, "type": "n", "value": value}]}
        target = f"{cid}-n{key_i % created}"
        if k == COL_KIND_SET:
            return {"kind": "set", "id": target, "value": value}
        return {"kind": "remove", "ids": [target]}

    def _generate_ops(self, t: int) -> Dict[int, List[RawOperation]]:
        """Boxed ingress (``columnar=False`` — the parity oracle — and
        ALL ``tree_ops`` traffic, whose changesets live outside the
        closed columnar vocabulary): the same columnar plan,
        materialized per op into dict + RawOperation envelopes before
        submission."""
        out: Dict[int, List[RawOperation]] = {}
        fired = self._fire(t)
        if fired is None:
            return out
        firing, kind_code, key_i, value, ch_i = fired
        docs = self.doc_of[firing]
        seqs = self.client_seq[firing]
        refs = self.cursor[firing]
        tree_mode = self.spec.tree_ops
        for j, i in enumerate(firing.tolist()):
            k = int(kind_code[j])
            if tree_mode:
                contents = {"edits": [self._tree_edit(
                    i, k, int(key_i[j]), int(value[j]), int(ch_i[j]))]}
                channel = "tree"
            elif k == COL_KIND_SET:
                contents = {"kind": "set", "key": key_string(int(key_i[j])),
                            "value": int(value[j])}
                channel = "kv"
            elif k == COL_KIND_INCREMENT:
                contents = {"kind": "increment", "delta": int(value[j])}
                channel = "count"
            else:
                contents = {"kind": "insert", "pos": 0,
                            "text": CHAR_STRINGS[int(ch_i[j])]}
                channel = "text"
            sub = {"clientSeq": int(seqs[j]), "refSeq": int(refs[j]),
                   "ds": "ds", "channel": channel, "contents": contents}
            op = RawOperation(
                client_id=self.client_ids[i],
                client_seq=int(seqs[j]),
                ref_seq=int(refs[j]),
                type=MessageType.OP,
                contents={"type": "groupedBatch", "v": BATCH_WIRE_VERSION,
                          "ops": [sub]},
            )
            out.setdefault(int(docs[j]), []).append(op)
        return out

    def _plan_columns(self, t: int) -> Optional[ColumnBatch]:
        """Columnar ingress plan: this tick's ops as ONE
        :class:`ColumnBatch` over the swarm's shared doc/client tables —
        zero per-op Python objects.  Rows are client-index ascending, so
        ``doc_index`` is non-decreasing (contiguous per-doc runs)."""
        fired = self._fire(t)
        if fired is None:
            return None
        firing, kind_code, key_i, value, ch_i = fired
        return ColumnBatch(
            doc_index=self.doc_of[firing].astype(np.int32, copy=False),
            client_index=firing.astype(np.int32),
            client_seq=self.client_seq[firing],
            ref_seq=self.cursor[firing],
            kind=kind_code,
            key_index=key_i.astype(np.int16),
            value=value.astype(np.int64, copy=False),
            char_index=ch_i.astype(np.int16),
            doc_ids=self.doc_ids,
            client_ids=self.client_ids,
            v=BATCH_WIRE_VERSION,
        )

    def _tick_ingress(self, t: int) -> List[int]:
        """One tick's ingress through the mode-selected wire path.  The
        ingress meter covers the WHOLE swarm→sequencer leg — op
        planning/boxing, wire encode/decode, and batch stamping — which
        is the r10 per-op cost the columnar path exists to kill."""
        if not self.spec.columnar or self.spec.tree_ops:
            # tree-collab always boxes: changesets are outside the
            # closed columnar vocabulary — the documented fallback.
            with self.ingress.timed():
                ops = self._generate_ops(t)
            return self._submit(t, ops)
        with self.ingress.timed():
            batch = self._plan_columns(t)
        if batch is None:
            return self._submit(t, {})
        # Ship through the REAL wire: struct-pack to framed bytes and
        # decode back (tables compacted to the referenced entries) — the
        # gated runs measure the full encode→bytes→decode→stamp path,
        # not an in-process shortcut.
        with self.ingress.timed():
            data = column_batch_to_bytes(batch)
            wire_batch = column_batch_from_bytes(data)
        self.ingress.encode_bytes += len(data)
        self.ingress.decode_bytes += len(data)
        self.ingress.batches += 1
        # Contiguous per-doc row runs (rows are client-index ascending).
        di = wire_batch.doc_index
        cuts = np.flatnonzero(np.diff(di)) + 1
        starts = np.concatenate([[0], cuts])
        ends = np.concatenate([cuts, [di.shape[0]]])
        doc_rows = {
            wire_batch.doc_ids[int(di[s])]: np.arange(s, e, dtype=np.int64)
            for s, e in zip(starts.tolist(), ends.tolist())
        }
        return self._submit(t, {}, batch=wire_batch, doc_rows=doc_rows)

    def _submit(self, t: int, new_ops: Dict[int, List[RawOperation]],
                batch: Optional[ColumnBatch] = None,
                doc_rows: Optional[Dict[str, np.ndarray]] = None
                ) -> List[int]:
        """Submit this tick's batches (deferred batches first) through the
        service's batched ingress; record deferrals — from real mid-batch
        failures or from the oracle twin's scripted mirror — for the next
        tick's whole-batch resubmit.

        Columnar mode hands this tick's ops as ``(batch, doc_rows)``:
        documents with no complications ride ``submit_columns`` as raw
        row slices; a document that carries a deferred batch or a
        scripted split this tick MATERIALIZES its rows (boxed fallback)
        so the pending-first op order and the split bookkeeping stay
        byte-identical to the boxed mode.  Both submit calls share one
        group commit."""
        full: Dict[int, List[RawOperation]] = {}
        for d, ops in self.pending.items():
            full[d] = list(ops)
        for d, ops in new_ops.items():
            full.setdefault(d, []).extend(ops)
        col_rows: Dict[str, np.ndarray] = {}
        if doc_rows:
            for doc_id, rows in doc_rows.items():
                d = self._doc_index[doc_id]
                if d in full or (t, d) in self._scripted:
                    full.setdefault(d, []).extend(
                        batch.materialize(int(i)) for i in rows.tolist())
                else:
                    col_rows[doc_id] = rows
        if not full and not col_rows:
            self.pending = {}
            return []
        submit: Dict[str, List[RawOperation]] = {}
        defer_now: Dict[int, List[RawOperation]] = {}
        for d in sorted(full):
            k = self._scripted.get((t, d))
            if k is None:
                submit[self.doc_ids[d]] = full[d]
            else:
                # Oracle-twin mirror of a recorded deferral: stamp the
                # same prefix this tick, re-run the whole batch next tick
                # (dedup absorbs the prefix — identical to the faulted
                # run's recovery), so both logs split identically.
                submit[self.doc_ids[d]] = full[d][:k]
                defer_now[d] = full[d]
                self.defers.append((t, d, k))
                self.counters.bump("swarm.defers")
        with self.ingress.timed():
            # ONE service call, ONE group commit, ONE globally sorted
            # per-doc order across both shapes: occurrence-indexed fault
            # schedules must fire on the same op in either mode.
            outcomes = self.service.submit_mixed(submit, batch, col_rows)
        self.ingress.boxed_ops += sum(len(ops) for ops in submit.values())
        self.ingress.columnar_ops += sum(
            int(r.shape[0]) for r in col_rows.values())
        touched = sorted(set(full)
                         | {self._doc_index[x] for x in col_rows})
        for d in touched:
            doc_id = self.doc_ids[d]
            outcome = outcomes[doc_id]
            self.counters.bump("swarm.ops_stamped", outcome.n_stamped())
            self.counters.bump(
                "swarm.ops_deduped",
                outcome.consumed - outcome.n_stamped()
                if outcome.error is None else 0)
            if outcome.error is not None:
                if d in full:
                    defer_now[d] = full[d]
                else:
                    # Deferral recovery round-trips through the boxed
                    # fallback: the rows materialize ONCE here and
                    # resubmit as a plain pending batch next tick.
                    defer_now[d] = [batch.materialize(int(i))
                                    for i in col_rows[doc_id].tolist()]
                consumed = outcome.consumed
                if consumed < 0:
                    # Out-of-proc "shard died mid-batch": the exact
                    # consumed count died with the process — the durable
                    # head (read from the adopted owner) is the whole
                    # truth, same as the JOIN-deferral readback.
                    consumed = max(0, self.service.oplog.head(doc_id)
                                   - int(self.head_arr[d]))
                self.defers.append((t, d, consumed))
                self.counters.bump("swarm.defers")
        self.pending = defer_now
        self._sync_heads(touched, t)
        return touched

    def _drive_faults(self, t: int) -> None:
        """Scheduled fault execution: in-proc shard kills and (out of
        proc) real process kills/hangs both ride the service's ``tick``
        driver — the router diff is the mode-independent kill record."""
        if self.injector is None:
            return
        # Replica kills are the SWARM's to execute: the front-door fleet
        # is harness topology the primary's tick driver knows nothing
        # about.  SIGKILL semantics for an in-process door: kill() tears
        # the pump down with nothing flushed — wire-indistinguishable
        # from the process dying — and the newest LIVE replica is always
        # the victim, because that is the door the data path pins to.
        for point in self.injector.due("replica.kill", t):
            victim = next(
                (i for i in range(len(self._replicas) - 1, -1, -1)
                 if not self._replicas[i].killed), None)
            if victim is None:
                self.injector.mark_unfired(point)
                continue
            self._replicas[victim].kill()
            self.replica_kills.append((t, victim))
            self.counters.bump("swarm.replica_kills")
        router = getattr(self.service, "router", None)
        tick = getattr(self.service, "tick", None)
        if router is None or tick is None:
            return
        before = set(router.dead())
        affected = tick(t)
        newly = [s for s in router.dead() if s not in before]
        if newly:
            self.kills.append((t, newly[0], len(affected)))
            self.counters.bump("swarm.kills")

    def _election(self, t: int) -> None:
        """Service-side summarizer pass over the sampled documents: load
        read-only at the durable head, upload the summary — mid-run late
        joiners (and the final verification) then load summary + tail
        through the real catch-up path."""
        for d in self.sampled:
            doc_id = self.doc_ids[d]
            ro = self.loader.resolve(doc_id)
            self.service.storage.upload(doc_id, ro.runtime.summarize(),
                                        ro.runtime.ref_seq)
            ro.close()
            self.counters.bump("swarm.elections")

    def _consume(self, t: int, final: bool = False) -> None:
        """Columnar consumption: steady clients that fired this tick
        drain to the head; catch-up clients advance ``catchup_rate`` per
        tick and complete when they reach it.  ``final`` drains everyone
        (the end-of-run quiescence)."""
        heads = self.head_arr[self.doc_of]
        if final:
            drain = np.flatnonzero(self.state == _STEADY)
        else:
            drain = np.flatnonzero((self.state == _STEADY)
                                   & (self.next_fire == t + self.period))
        self.cursor[drain] = heads[drain]
        catching = np.flatnonzero(self.state == _CATCHUP)
        if catching.size:
            self.cursor[catching] = np.minimum(
                heads[catching],
                self.cursor[catching] + self.spec.catchup_rate)
            done = catching[self.cursor[catching] >= heads[catching]]
            if done.size:
                self.catchup_lat.extend(
                    (t - self.catchup_start[done]).tolist())
                self.state[done] = _STEADY
                h = _hash_clients(self.spec.seed, 23, done)
                self.next_fire[done] = (
                    t + 1
                    + (h % self.period[done].astype(np.uint64)).astype(
                        np.int64))
                self.counters.bump("swarm.catchup_completions",
                                   int(done.size))
        connected = self.state != _UNBORN
        if connected.any():
            depth = int((heads - self.cursor)[connected].max())
            self.max_pending_depth = max(self.max_pending_depth, depth)

    def _sample_delivery(self, t: int, final: bool = False) -> None:
        """Advance each document's delivered floor to the slowest steady
        client's cursor and sample one latency per newly-covered seq."""
        docs = self.spec.docs
        masked = np.where(self.state == _STEADY, self.cursor,
                          np.iinfo(np.int64).max)
        mins = np.minimum.reduceat(masked, self.doc_starts)
        counts = np.add.reduceat((self.state == _STEADY).astype(np.int64),
                                 self.doc_starts)
        floors = np.where(counts > 0, np.minimum(mins, self.head_arr),
                          self.delivered_floor)
        if final:
            floors = self.head_arr.copy()
        for d in range(docs):
            lo, hi = int(self.delivered_floor[d]), int(floors[d])
            if hi > lo:
                ticks = self.stamp_ticks[d]
                self.delivery_lat.extend(t - s for s in ticks[lo:hi])
                self.delivered_floor[d] = hi
                self.counters.bump("swarm.delivery_samples", hi - lo)

    def _phase_transitions(self, t: int, phase: Phase,
                           phase_start: int) -> None:
        n = self.spec.clients
        idx = np.arange(n, dtype=np.int64)
        if phase.kind == "herd":
            if t == phase_start and phase.frac > 0:
                h = _hash_clients(self.spec.seed, 29 + phase_start, idx)
                cohort = np.flatnonzero(
                    (self.state == _STEADY)
                    & ((h % np.uint64(1000)).astype(np.int64)
                       < int(phase.frac * 1000)))
                self.state[cohort] = _DARK
            if t == phase_start + phase.ticks - 1:
                dark = np.flatnonzero(self.state == _DARK)
                self.state[dark] = _CATCHUP
                self.catchup_start[dark] = t
                if self._storm is not None:
                    # THE storm: the whole re-entry herd forms at once —
                    # its elected real callers all fire next tick.
                    self._storm.enlist(t + 1, dark)
        elif phase.kind == "laggards":
            if t == phase_start and phase.frac > 0:
                h = _hash_clients(self.spec.seed, 31 + phase_start, idx)
                cohort = np.flatnonzero(
                    (self.state == _STEADY)
                    & ((h % np.uint64(1000)).astype(np.int64)
                       < int(phase.frac * 1000)))
                h2 = _hash_clients(self.spec.seed, 37, cohort)
                span = max(2, phase.ticks // 2)
                start = t + (h2 % np.uint64(span)).astype(np.int64)
                length = 1 + (
                    (h2 >> np.uint64(17)) % np.uint64(span)).astype(np.int64)
                self.lag_start[cohort] = start
                self.lag_end[cohort] = np.minimum(
                    start + length, t + phase.ticks - 1)
            starting = np.flatnonzero((self.state == _STEADY)
                                      & (self.lag_start == t))
            self.state[starting] = _LAGGARD
            ending = np.flatnonzero((self.state == _LAGGARD)
                                    & (self.lag_end == t))
            self.state[ending] = _CATCHUP
            self.catchup_start[ending] = t
            if self._storm is not None and ending.size:
                # Staggered re-entries storm too — smaller waves that
                # keep the fold lane warm between herd spikes.
                self._storm.enlist(t + 1, ending)

    # -- the run ---------------------------------------------------------------

    def run(self) -> SwarmResult:
        self.setup()
        t = 0
        phase_counters: Dict[str, Dict[str, int]] = {}
        for p_i, phase in enumerate(self.spec.phases):
            phase_start = t
            since = self.counters.snapshot()
            if phase.kind == "election":
                self._election(t)
            for _ in range(phase.ticks):
                self._phase_transitions(t, phase, phase_start)
                self._connect_due(t)
                self._tick_ingress(t)
                self._drive_faults(t)
                if self._storm is not None:
                    self._storm.step(t)
                self._consume(t)
                self._sample_delivery(t)
                t += 1
            phase_counters[f"{p_i}:{phase.kind}"] = \
                self.counters.delta(since)
            if self._storm is not None:
                self._storm.phase_mark(f"{p_i}:{phase.kind}")
        # Quiescence: land any deferred JOIN cohorts and batches
        # (fault-free tail), then drain every client to the head.
        for _round in range(8):
            if not self.pending and not np.any(self.state == _UNBORN):
                break
            t += 1
            self._connect_due(t)
            self._submit(t, {})
        if self.pending or np.any(self.state == _UNBORN):
            raise AssertionError(
                f"swarm never drained its deferred work: "
                f"pending={sorted(self.pending)} "
                f"unborn={int(np.count_nonzero(self.state == _UNBORN))}")
        catching = np.flatnonzero((self.state == _CATCHUP)
                                  | (self.state == _DARK)
                                  | (self.state == _LAGGARD))
        if catching.size:
            self.catchup_start[catching] = np.where(
                self.state[catching] == _CATCHUP,
                self.catchup_start[catching], t)
            self.state[catching] = _CATCHUP
        while int(np.count_nonzero(self.state == _CATCHUP)) \
                or (self._storm is not None and self._storm.pending()):
            t += 1
            if self._storm is not None:
                # Paced retries land beyond the scripted phases: keep
                # serving until the whole storm drained (bounded by the
                # driver's MAX_ATTEMPTS guard — zero unbounded queueing).
                self._storm.step(t)
            self._consume(t)
            self._sample_delivery(t)
        self._consume(t, final=True)
        self._sample_delivery(t, final=True)
        return self._result(t, phase_counters)

    def _result(self, t: int,
                phase_counters: Dict[str, Dict[str, int]]) -> SwarmResult:
        bulk = getattr(self.service, "heads", None)
        per_doc_head = (bulk(self.doc_ids) if bulk is not None
                        else {doc: self.service.oplog.head(doc)
                              for doc in self.doc_ids})
        # O(log entries), not O(messages): columnar segments verify by
        # boundary (their seqs are an arange by construction).  Out-of-
        # proc services answer in bulk RPCs grouped by owning shard.
        bulk_contig = getattr(self.service, "contiguous", None)
        if bulk_contig is not None:
            broken = sorted(doc for doc, ok in
                            bulk_contig(self.doc_ids).items() if not ok)
            if broken:
                raise AssertionError(
                    f"seq numbers not contiguous: {broken}")
        else:
            for doc in self.doc_ids:
                if not self.service.oplog.is_contiguous(doc):
                    raise AssertionError(
                        f"{doc} seq numbers not contiguous")
        digests = {}
        for d in self.sampled:
            ro = self.loader.resolve(self.doc_ids[d])
            digests[self.doc_ids[d]] = ro.runtime.summarize().digest()
            ro.close()
        counters = self.counters.snapshot()
        for k, v in sorted(self.broadcaster.stats().items()):
            counters[f"broadcast.{k}"] = v
        delivery = sorted(self.delivery_lat)
        catchup = sorted(self.catchup_lat)
        return SwarmResult(
            name=self.spec.name,
            seed=self.spec.seed,
            clients=self.spec.clients,
            docs=self.spec.docs,
            shards=self.spec.shards,
            ticks=t,
            sequenced_ops=sum(per_doc_head.values()),
            ops_stamped=counters["swarm.ops_stamped"],
            ops_submitted=counters["swarm.ops_submitted"],
            ops_deduped=counters["swarm.ops_deduped"],
            joins=counters["swarm.joins"],
            delivery_p50_ticks=float(percentile(delivery, 0.50)),
            delivery_p99_ticks=float(percentile(delivery, 0.99)),
            delivery_samples=len(delivery),
            catchup_p50_ticks=float(percentile(catchup, 0.50)),
            catchup_p99_ticks=float(percentile(catchup, 0.99)),
            catchup_samples=len(catchup),
            max_pending_depth=self.max_pending_depth,
            defers=tuple(self.defers),
            join_defers=tuple(self.join_defers),
            kills=tuple(self.kills),
            replica_kills=tuple(self.replica_kills),
            per_doc_head=per_doc_head,
            sampled_digests=digests,
            fault_counts=(self.injector.snapshot()
                          if self.injector is not None else {}),
            counters=counters,
            phase_counters=phase_counters,
            ingress=self.ingress.snapshot(),
            shard_stats=self._shard_stats(per_doc_head),
            fold_tier=(self._fold_probe()
                       if self.spec.fold_probe else {}),
            storm=(self._storm.summary()
                   if self._storm is not None else {}),
        )

    def _fold_probe(self) -> Dict[str, object]:
        """ISSUE 13: close the loop between the swarm engine and the
        device fold — catch the SAMPLED documents up twice through a
        real CatchupService (tier 1 off, so the warm pass re-folds
        through the pack / device-resident / delta tiers instead of
        serving a memoized tree) and report the fold-tier counters.  The
        cold pass fills the tiers from the swarm's real op logs; the
        warm pass must serve resident (``device_cache["served"]``) and
        delta-download (``delta_cache["served"]``) hits with the h2d
        upload collapsed to zero pack bytes.  Wall-derived, hence
        outside replay identity."""
        if self._cluster is not None:
            return {"skipped": "out-of-proc"}
        from ..service.catchup import CatchupService

        svc = CatchupService(self.service, mesh=None, cache=None)
        ids = [self.doc_ids[d] for d in self.sampled]
        svc.catch_up(ids, upload=False)  # cold: the tiers fill
        stats: dict = {}
        svc.catch_up(ids, upload=False, stats=stats)  # warm: tiers serve
        stage = svc.pipeline_stage
        return {
            "docs": len(ids),
            "device_cache": (svc.device_cache.stats()
                             if svc.device_cache is not None else None),
            "delta_cache": (svc.delta_cache.stats()
                            if svc.delta_cache is not None else None),
            "pack_cache": (svc._pack_cache.stats()
                           if svc._pack_cache is not None else None),
            # The second kernel family's tiers (ISSUE 14) — live on
            # tree-collab runs, zero-traffic otherwise.
            "tree_device_cache": (
                svc.tree_device_cache.stats()
                if svc.tree_device_cache is not None else None),
            "tree_pack_cache": (
                svc.tree_pack_cache.stats()
                if svc.tree_pack_cache is not None else None),
            "host_channels": stats.get("hostChannels", 0),
            "fallback_channels": stats.get("fallbackChannels", 0),
            "h2d_bytes": int(stage.get("h2d_bytes", 0)),
            "d2h_bytes": int(stage.get("d2h_bytes", 0)),
        }

    def _shard_stats(self, per_doc_head: Dict[str, int]) -> Dict[str, object]:
        """Out-of-proc only: per-shard ``stats`` RPC pulls + the live-tap
        delivery audit (unique seqs relayed to the swarm's sampled-doc
        subscriptions — async wall-time, hence outside identity)."""
        if self._cluster is None:
            return {}
        return {
            "cluster": self.service.stats(),
            "doors": 1 + len(self._replicas),
            "door_failovers": self.service.door_failovers,
            "replica_pumps": [door.stats().get("pump")
                              for door in self._replicas],
            "tap_unique_frames": {doc: len(seen) for doc, seen
                                  in sorted(self._proc_frames.items())},
            "tap_heads": {doc: per_doc_head[doc]
                          for doc in sorted(self._proc_frames)},
        }

    def close(self) -> None:
        """Tear the run down: out-of-proc clusters terminate their shard
        processes (SIGTERM → drain-and-seal) and temp deployments are
        removed; in-proc runs have nothing to release."""
        if self._cluster is None:
            return
        try:
            self.factory.close()
        except OSError:
            pass
        self.service.close()
        for door in self._replicas:
            if not door.killed:
                door.close()
        self._replicas = []
        self._cluster.close()
        self._cluster = None
        if self._tmpdir is not None:
            import shutil

            shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = None


def run_swarm(spec: ScenarioSpec) -> SwarmResult:
    """Drive one scenario end to end; pure function of ``spec`` (modulo
    the wall-derived ``ingress``/``shard_stats`` fields).  Out-of-proc
    runs always release their shard processes, success or not."""
    swarm = ClientSwarm(spec)
    try:
        return swarm.run()
    finally:
        swarm.close()


def oracle_spec(spec: ScenarioSpec, result: SwarmResult) -> ScenarioSpec:
    """The fault-free single-shard IN-PROCESS twin of a completed run:
    same seed and phases, no faults, no processes, with the run's
    recorded op/JOIN deferrals replayed as scripted splits so both runs
    stamp byte-identical logs.  For an out-of-proc run this is the
    strongest cross-validation in the repo: a process tier under real
    SIGKILLs must land byte-identical per-document state to a single
    in-memory orderer."""
    return dataclasses.replace(
        spec,
        shards=1,
        plan=None,
        dir=None,
        out_of_proc=False,
        replicas=1,
        # The storm twin is the NEVER-SHED oracle (ISSUE 15): unlimited
        # admission, no modeled fold hold — every shed/degraded client
        # of the real run must converge byte-identically to it.
        storm_never_shed=True,
        scripted_defers=tuple(result.defers),
        scripted_join_defers=tuple(result.join_defers),
    )


def run_swarm_with_oracle(spec: ScenarioSpec
                          ) -> Tuple[SwarmResult, SwarmResult]:
    """THE acceptance harness: run ``spec`` (shards, faults and all),
    then re-drive the identical scenario FAULT-FREE on a single shard —
    see :func:`oracle_spec` — and return ``(result, oracle)``.  Callers
    assert ``sampled_digests`` and ``per_doc_head`` equal: failovers and
    injected faults may cost deferrals and recoveries, never state."""
    result = run_swarm(spec)
    return result, run_swarm(oracle_spec(spec, result))
