"""Test infrastructure: mock runtimes and the fuzz harness.

Capability-equivalent of the reference's test-runtime-utils +
test-dds-utils/stochastic-test-utils (SURVEY.md §4; upstream paths UNVERIFIED
— empty reference mount).
"""

from .faults import FaultError, FaultInjector, FaultPlan, FaultPoint
from .mocks import MockContainerRuntimeFactory, MockClientRuntime

__all__ = [
    "FaultError", "FaultInjector", "FaultPlan", "FaultPoint",
    "MockContainerRuntimeFactory", "MockClientRuntime",
]
