"""The DDS fuzz harness — seeded eventual-consistency testing.

Capability-equivalent of the reference's DDS fuzz harness
(SURVEY.md §4: test-dds-utils + stochastic-test-utils; upstream paths
UNVERIFIED — empty reference mount): seeded op generators drive N client
replicas through random edits with random partial delivery (interleaving
exploration — the framework's real race detector), periodically synchronizing
and asserting all replicas equivalent by state AND by canonical summary
digest.  The same harness drives CPU-oracle vs TPU-kernel equivalence: replay
the generated op log through the device path and compare digests.
"""

from __future__ import annotations

import random
from typing import Callable, List, Optional

from ..dds.shared_object import SharedObject
from .mocks import MockContainerRuntimeFactory

ALPHABET = "abcdefghijklmnopqrstuvwxyz"


class FuzzSpec:
    """Per-DDS-type fuzz behavior: how to build an instance, generate one
    random local edit, and snapshot comparable state."""

    #: weight of generating an op vs doing nothing in a step
    op_probability: float = 0.8

    def create(self, object_id: str) -> SharedObject:
        raise NotImplementedError

    def random_op(self, rng: random.Random, dds: SharedObject) -> None:
        raise NotImplementedError

    def observable(self, dds: SharedObject):
        """Human-readable converged-state projection (for failure messages)."""
        return None


class StringFuzzSpec(FuzzSpec):
    def __init__(self, annotate: bool = True, intervals: bool = False,
                 obliterate: bool = False) -> None:
        self.annotate = annotate
        self.intervals = intervals
        self.obliterate = obliterate

    def create(self, object_id: str) -> SharedObject:
        from ..dds.sequence import SharedString

        return SharedString(object_id)

    def random_op(self, rng: random.Random, dds) -> None:
        n = len(dds)
        r = rng.random()
        if self.intervals and r > 0.82 and n > 0:
            self._interval_op(rng, dds, n)
            return
        if r < 0.55 or n == 0:
            pos = rng.randint(0, n)
            text = "".join(rng.choice(ALPHABET) for _ in range(rng.randint(1, 6)))
            dds.insert_text(pos, text)
        elif self.obliterate and r < 0.68:
            start = rng.randint(0, n - 1)
            dds.obliterate_range(start, min(n, start + rng.randint(1, 8)))
        elif r < 0.8 or not self.annotate:
            start = rng.randint(0, n - 1)
            dds.remove_range(start, min(n, start + rng.randint(1, 8)))
        else:
            start = rng.randint(0, n - 1)
            end = min(n, start + rng.randint(1, 8))
            dds.annotate_range(start, end, {rng.choice("xyz"): rng.randint(0, 3)})

    def _interval_op(self, rng: random.Random, dds, n: int) -> None:
        # Small shared id pool so concurrent add/change/delete conflict.
        interval_id = f"iv{rng.randint(0, 3)}"
        coll = dds.get_interval_collection()
        r = rng.random()
        start = rng.randint(0, n - 1)
        end = min(n - 1, start + rng.randint(0, 6))
        if r < 0.5 or coll.get(interval_id) is None:
            dds.add_interval(start, end, interval_id=interval_id,
                             props={"tag": rng.randint(0, 3)})
        elif r < 0.85:
            dds.change_interval(interval_id, start=start, end=end,
                                props={"tag": rng.randint(0, 3)})
        else:
            dds.delete_interval(interval_id)

    def observable(self, dds):
        ivs = {
            label: coll.summary_obj()
            for label, coll in dds._interval_collections.items()
        }
        return (dds.text, ivs)


class MapFuzzSpec(FuzzSpec):
    KEYS = [f"k{i}" for i in range(8)]

    def create(self, object_id: str) -> SharedObject:
        from ..dds.map import SharedMap

        return SharedMap(object_id)

    def random_op(self, rng: random.Random, dds) -> None:
        r = rng.random()
        key = rng.choice(self.KEYS)
        if r < 0.7:
            dds.set(key, rng.randint(0, 99))
        elif r < 0.95:
            dds.delete(key)
        else:
            dds.clear()

    def observable(self, dds):
        return dict(sorted(dds._kernel.data.items()))


class DirectoryFuzzSpec(FuzzSpec):
    PATHS = ["/", "a", "a/b", "c"]
    KEYS = [f"k{i}" for i in range(4)]

    def create(self, object_id: str) -> SharedObject:
        from ..dds.map import SharedDirectory

        return SharedDirectory(object_id)

    def random_op(self, rng: random.Random, dds) -> None:
        r = rng.random()
        path = rng.choice(self.PATHS)
        if r < 0.6:
            dds.set(rng.choice(self.KEYS), rng.randint(0, 99), path=path)
        elif r < 0.8:
            dds.delete(rng.choice(self.KEYS), path=path)
        elif r < 0.9:
            dds.create_subdirectory(rng.choice(["a", "a/b", "c", "d/e"]))
        else:
            dds.delete_subdirectory(rng.choice(["a/b", "c", "d/e"]))

    def observable(self, dds):
        return dds._root.summary_obj()


class RegisterFuzzSpec(FuzzSpec):
    """Consensus register collection: concurrent writes to a small key
    pool — version lists and winners must converge."""

    KEYS = [f"r{i}" for i in range(4)]

    def create(self, object_id: str) -> SharedObject:
        from ..dds.consensus import ConsensusRegisterCollection

        return ConsensusRegisterCollection(object_id)

    def random_op(self, rng: random.Random, dds) -> None:
        dds.write(rng.choice(self.KEYS), rng.randint(0, 99))

    def observable(self, dds):
        return {k: dds.read_versions(k) for k in sorted(dds.keys())}


class QueueFuzzSpec(FuzzSpec):
    """Consensus queue: adds racing acquire/complete/release — held items
    and remaining queue contents must converge (the acquire order is the
    total order, so every replica agrees who holds what)."""

    def create(self, object_id: str) -> SharedObject:
        from ..dds.consensus import ConsensusQueue

        return ConsensusQueue(object_id)

    def random_op(self, rng: random.Random, dds) -> None:
        r = rng.random()
        held = sorted(dds.held_by_me)
        if r < 0.45 or (len(dds) == 0 and not held):
            dds.add(rng.randint(0, 999))
        elif r < 0.75 and len(dds):
            dds.acquire()
        elif held and r < 0.9:
            dds.complete(rng.choice(held))
        elif held:
            dds.release(rng.choice(held))

    def observable(self, dds):
        return (dds.items, sorted(dds.held_by_me))


class MatrixFuzzSpec(FuzzSpec):
    """Random row/col structure edits + cell writes; optional FWW switch."""

    def __init__(self, fww: bool = False) -> None:
        self.fww = fww

    def create(self, object_id: str) -> SharedObject:
        from ..dds.matrix import SharedMatrix

        return SharedMatrix(object_id)

    def random_op(self, rng: random.Random, dds) -> None:
        rows, cols = dds.row_count, dds.col_count
        r = rng.random()
        if self.fww and dds.policy == "lww" and r > 0.97:
            dds.switch_policy("fww")
        elif r < 0.18 or rows == 0:
            dds.insert_rows(rng.randint(0, rows), rng.randint(1, 3))
        elif r < 0.3 or cols == 0:
            dds.insert_cols(rng.randint(0, cols), rng.randint(1, 3))
        elif r < 0.4 and rows > 1:
            start = rng.randint(0, rows - 1)
            dds.remove_rows(start, min(rows - start, rng.randint(1, 2)))
        elif r < 0.5 and cols > 1:
            start = rng.randint(0, cols - 1)
            dds.remove_cols(start, min(cols - start, rng.randint(1, 2)))
        else:
            dds.set_cell(
                rng.randint(0, rows - 1), rng.randint(0, cols - 1),
                rng.randint(0, 99),
            )

    def observable(self, dds):
        return dds.to_list()


def run_fuzz(
    spec: FuzzSpec,
    seed: int,
    n_clients: int = 3,
    rounds: int = 40,
    ops_per_client_round: int = 3,
    sync_every: int = 8,
    on_sync: Optional[Callable[[MockContainerRuntimeFactory, List[SharedObject]], None]] = None,
):
    """Drive N replicas through seeded random edits with random partial
    delivery; synchronize periodically and at the end, asserting convergence
    by canonical summary digest.  Returns ``(replicas, factory)`` so callers
    can replay ``factory.sequencer.log`` through a device kernel and compare
    digests."""
    rng = random.Random(seed)
    factory = MockContainerRuntimeFactory()
    replicas: List[SharedObject] = []
    for i in range(n_clients):
        client = factory.create_client(f"client{i}")
        replicas.append(client.attach(spec.create("fuzz")))

    def check_converged() -> None:
        digests = {r.summarize().digest() for r in replicas}
        if len(digests) != 1:
            states = [spec.observable(r) for r in replicas]
            raise AssertionError(
                f"divergence (seed={seed}): "
                + " | ".join(repr(s) for s in states)
            )

    for round_no in range(rounds):
        for replica in replicas:
            for _ in range(ops_per_client_round):
                if rng.random() < spec.op_probability:
                    spec.random_op(rng, replica)
        # Random partial delivery explores interleavings.
        factory.process_some_messages(rng.randint(0, factory.pending_count))
        if (round_no + 1) % sync_every == 0:
            factory.process_all_messages()
            check_converged()
            if rng.random() < 0.5:
                factory.advance_min_seq()  # exercise zamboni mid-run
            if on_sync is not None:
                on_sync(factory, replicas)
    factory.process_all_messages()
    check_converged()
    return replicas, factory
