"""The ordering-service slice: capability-equivalent of the reference's
Routerlicious release group (SURVEY.md §2.3; upstream paths UNVERIFIED —
empty reference mount), re-shaped for an in-process / single-host TPU
deployment:

- :mod:`oplog`    — Scriptorium capability: durable per-document op log.
- :mod:`scribe`   — Scribe capability: summary validation + ack/nack.
- :mod:`orderer`  — Deli + LocalOrderer + Alfred capability: per-document
  sequencing with checkpoints, multi-document front door, signal fan-out.
- :mod:`catchup`  — the scriptorium-fed bulk catch-up service that routes
  replay through the TPU backend (the north-star service path).
"""

from .oplog import OpLog
from .orderer import DocumentOrderer, LocalOrderingService
from .scribe import Scribe

__all__ = ["OpLog", "DocumentOrderer", "LocalOrderingService", "Scribe"]
