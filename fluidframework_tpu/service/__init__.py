"""The ordering-service slice: capability-equivalent of the reference's
Routerlicious release group (SURVEY.md §2.3; upstream paths UNVERIFIED —
empty reference mount), re-shaped for an in-process / single-host TPU
deployment:

- :mod:`oplog`    — Scriptorium capability: durable per-document op log.
- :mod:`scribe`   — Scribe capability: summary validation + ack/nack.
- :mod:`orderer`  — Deli + LocalOrderer + Alfred capability: per-document
  sequencing with checkpoints, multi-document front door, signal fan-out.
- :mod:`catchup`  — the scriptorium-fed bulk catch-up service that routes
  replay through the TPU backend (the north-star service path).
- :mod:`sharding` — document-partitioned orderer shards (rendezvous
  routing, epoch-fenced failover) behind the same service surface.
- :mod:`broadcaster` — serialize-once broadcast fan-out with laggard
  demotion (the per-doc delta/signal distribution tier).
- :mod:`shardhost` — fluidproc: one shard as a standalone server
  PROCESS (own durable log, shared summary store, migration/adoption
  control plane, SIGTERM drain-and-seal).
- :mod:`frontdoor` — fluidproc: the routing front door (shard-process
  supervision, heartbeat death detection, SIGKILL-fenced failover,
  live document migration).  Imported lazily — not re-exported here —
  so the in-proc service surface keeps its import graph.
- :mod:`procclient` — fluidproc: the swarm-facing service adapter over
  the front door.
"""

from .broadcaster import Broadcaster
from .oplog import OpLog
from .orderer import DocumentOrderer, LocalOrderingService
from .retry import RetryPolicy
from .scribe import Scribe
from .sharding import ShardedOrderingService, ShardRouter

__all__ = [
    "Broadcaster", "OpLog", "DocumentOrderer", "LocalOrderingService",
    "RetryPolicy", "Scribe", "ShardRouter", "ShardedOrderingService",
]
