"""framepump: the single-threaded selector event loop behind the async
front door (ISSUE 18).

The out-of-proc tier's recorded scaling wall was the connection layer:
thread-per-connection pinned the front door at ~2x10^3 real sockets (two
threads per client once PR 15's lazily-started relay writer joined the
serve thread), while columnar shard ingress handles 10^6 simulated
clients.  This module replaces both threads with ONE event loop that
owns accept, reads, and budget-aware writes for every connection:

- :class:`FrameParser` — incremental length-prefixed frame reassembly
  (the ``[4-byte BE length][json]`` wire shape) over whatever byte
  chunks ``recv`` happens to return;
- :class:`PumpConnection` — per-socket state: the read parser plus a
  non-blocking write side holding the PR 15 relay contract (bounded
  ``relay`` under a per-client byte budget, budget-exempt queue-jumping
  ``relay_priority`` for control frames) in per-socket buffers instead
  of a writer thread + Condition;
- :class:`FramePump` — the loop: a ``selectors`` selector, the
  listener, a socketpair wakeup so any thread can hand the loop bytes
  to write, and a dirty-set handshake that keeps cross-thread senders
  O(append + maybe one wakeup byte).

Threading contract (this is what FL-RACE-BLOCKING's on-loop extension
enforces): methods marked on-loop run ONLY on the pump thread and must
never block — no RPC, no fold, no ``sendall`` — because one blocking
callback stalls every connection on the loop.  Frame dispatch therefore
happens via a callback that must hand real work to a worker pool and
write the response back cross-thread through :meth:`PumpConnection.
send_obj`.

Priority frames stay frame-aligned by construction: a partially-sent
frame lives in ``_inflight`` (never re-queued), so ``appendleft`` on the
pending deque can never interleave bytes into the middle of a frame.
"""

from __future__ import annotations

import json
import selectors
import socket
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Set

from ..protocol.wire import LEN as _LEN, MAX_FRAME, frame_bytes

#: read chunk per ready socket per loop pass: big enough to drain a
#: bursty client in few syscalls, small enough that one firehose cannot
#: monopolize the pass.
_READ_CHUNK = 256 << 10

#: response-path high water (mirrors the ordering server's
#: WRITE_HIGH_WATER): a client that stops reading while we owe it
#: RESPONSES (not relays — those have their own budget) is broken or
#: hostile; past this we close rather than buffer without bound.
RESPONSE_HIGH_WATER = 32 << 20


class FrameParser:
    """Incremental ``[4-byte BE length][payload]`` reassembly.

    Single-threaded by design (owned by the loop); feed() returns every
    COMPLETE payload the new chunk finished, keeping any tail bytes for
    the next chunk.  Raises ``ValueError`` on an oversized frame — the
    caller drops the connection (the stream is unrecoverable: we cannot
    know where the next frame starts)."""

    __slots__ = ("_buf",)

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf += data
        out: List[bytes] = []
        at = 0
        buf = self._buf
        while True:
            if len(buf) - at < _LEN.size:
                break
            (length,) = _LEN.unpack_from(buf, at)
            if length > MAX_FRAME:
                raise ValueError(f"frame length {length} exceeds "
                                 f"MAX_FRAME {MAX_FRAME}")
            if len(buf) - at < _LEN.size + length:
                break
            start = at + _LEN.size
            out.append(bytes(buf[start:start + length]))
            at = start + length
        if at:
            del buf[:at]
        return out


class PumpConnection:
    """One client socket on the pump: read parser + non-blocking write
    buffers carrying the PR 15 relay-budget contract.

    Write-side layout (all guarded by ``_wlock``): ``_inflight`` holds
    the partially-sent head frame (a memoryview advanced by each
    ``send``), ``_pending`` the queued whole frames.  ``relay_priority``
    jumps the queue with ``appendleft`` — frame-aligned because the
    in-flight frame is never in the deque.  Only the pump thread ever
    touches the socket; other threads append and ring the pump's
    wakeup."""

    __slots__ = (
        "sock", "parser", "subscribed", "relay_budget", "_pump",
        "_wlock", "_pending", "_inflight", "_inflight_len",
        "_relay_bytes", "_pending_bytes", "closed", "_peer",
    )

    def __init__(self, sock: socket.socket, pump: "FramePump",
                 relay_budget: int = 4 << 20) -> None:
        self.sock = sock
        self.parser = FrameParser()
        #: docs this client subscribed to (front-door bookkeeping; the
        #: door's route lock guards cross-thread mutation, same contract
        #: as the old per-session serve thread)
        self.subscribed: Set[str] = set()
        self.relay_budget = int(relay_budget)
        self._pump = pump
        self._wlock = threading.Lock()
        self._pending: "deque[bytes]" = deque()  # guarded-by: _wlock
        self._inflight: Optional[memoryview] = None  # guarded-by: _wlock
        self._inflight_len = 0  # guarded-by: _wlock
        self._relay_bytes = 0  # guarded-by: _wlock
        self._pending_bytes = 0  # guarded-by: _wlock
        self.closed = False
        try:
            self._peer = sock.getpeername()
        except OSError:
            self._peer = ("?", 0)

    # -- cross-thread write API ------------------------------------------------

    def send_obj(self, obj: dict) -> None:
        self.send_bytes(frame_bytes(obj))

    def send_bytes(self, data: bytes) -> None:
        """Response-path enqueue (unbudgeted but high-watered): worker
        threads answer requests here; the pump flushes."""
        overflow = False
        with self._wlock:
            if self.closed:
                return
            if self._pending_bytes - self._relay_bytes \
                    > RESPONSE_HIGH_WATER:
                overflow = True
            else:
                self._pending.append(data)
                self._pending_bytes += len(data)
        if overflow:
            # A client that stopped reading its own responses: close
            # instead of buffering without bound (relay frames have
            # their own budget + demotion; this is the response path).
            self._pump.drop(self)
            return
        self._pump.mark_dirty(self)

    # -- the PR 15 relay contract ----------------------------------------------

    def relay(self, data: bytes) -> bool:
        """Bounded enqueue of one broadcast frame: False = the budget is
        exhausted (a stalled or slow reader) and the caller demotes this
        session — the broadcaster's sink contract at this hop.  A frame
        larger than the whole budget is still accepted into an EMPTY
        relay queue (charged in flight): otherwise one oversized event
        would demote every subscriber — idle fast readers included — on
        every occurrence, forever.  Memory stays bounded by
        ``max(relay_budget, one frame)``."""
        with self._wlock:
            if self.closed:
                return True  # tearing down: drop silently, like the sink
            if self._relay_bytes > 0 \
                    and self._relay_bytes + len(data) > self.relay_budget:
                return False
            self._pending.append(data)
            self._relay_bytes += len(data)
            self._pending_bytes += len(data)
        self._pump.mark_dirty(self)
        return True

    def relay_priority(self, data: bytes) -> None:
        """Budget-exempt, queue-jumping enqueue for CONTROL frames
        (demoted / fence): bounded by construction — at most one per
        (doc, event) — and they must reach a saturated client PROMPTLY,
        not behind its whole data backlog (the demotion notice IS the
        recovery trigger the driver's re-subscribe rides; receivers
        dedup any stale data frames that drain after it by seq
        watermark).  ``appendleft`` is frame-aligned: the partially-sent
        frame lives in ``_inflight``, never in this deque."""
        with self._wlock:
            if self.closed:
                return
            self._pending.appendleft(data)
            self._pending_bytes += len(data)
        self._pump.mark_dirty(self)

    def relay_pending(self) -> int:
        with self._wlock:
            return self._relay_bytes

    def pending_bytes(self) -> int:
        with self._wlock:
            return self._pending_bytes

    # -- pump-side flush (loop thread only) ------------------------------------

    def flush(self) -> bool:  # on-loop
        """Send as much buffered data as the kernel accepts right now.
        Returns True when fully drained (the pump drops write
        interest).  Budget accounting: a relay frame stays charged
        until the kernel accepted its LAST byte — in-flight bytes count
        against the budget, exactly the writer-thread semantics."""
        while True:
            with self._wlock:
                if self._inflight is None:
                    if not self._pending:
                        return True
                    frame = self._pending.popleft()
                    self._inflight = memoryview(frame)
                    self._inflight_len = len(frame)
                view = self._inflight
                # The send stays inside the critical section: the socket
                # is non-blocking so the hold is one bounded syscall, and
                # it closes the window against close() clearing the
                # buffers between our read of _inflight and the
                # accounting below.
                try:
                    sent = self.sock.send(view)
                except (BlockingIOError, InterruptedError):
                    return False
                self._pending_bytes -= sent
                # _relay_bytes accounts whole frames; release on frame
                # completion below (per-byte split would need tagging —
                # whole-frame release keeps the stall bound identical).
                if sent == len(view):
                    self._relay_bytes = max(
                        0, self._relay_bytes
                        - self._uncharge(self._inflight_len))
                    self._inflight = None
                    self._inflight_len = 0
                else:
                    self._inflight = view[sent:]
                    return False

    def _uncharge(self, n: int) -> int:
        # holds-lock: _wlock
        # Relay frames and response frames share one FIFO (ordering is
        # the contract); budget release approximates by draining the
        # relay charge frame-by-frame — never below zero, never above
        # what was charged.  Exact per-frame tagging would double the
        # queue's memory for no observable difference in the demotion
        # bound.
        return n if self._relay_bytes >= n else self._relay_bytes

    def close(self) -> None:
        """Idempotent teardown; safe from any thread (the socket close
        races are absorbed by OSError guards — the pump unregisters on
        its next pass via the closed flag)."""
        with self._wlock:
            if self.closed:
                return
            self.closed = True
            self._pending.clear()
            self._inflight = None
            self._inflight_len = 0
            self._relay_bytes = 0
            self._pending_bytes = 0
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class FramePump:
    """The selector loop: one thread owning accept, reads, and writes
    for every client connection of a front-door replica.

    ``on_frame(conn, obj)`` fires ON the loop thread for every decoded
    frame — it must not block (hand work to a pool; see the module
    doc).  ``on_close(conn)`` fires when a connection leaves (EOF,
    error, response overflow) so the owner can drop bookkeeping."""

    def __init__(self, host: str, port: int,
                 on_frame: Callable[[PumpConnection, dict], None],
                 on_close: Optional[Callable[[PumpConnection], None]]
                 = None,
                 relay_budget: int = 4 << 20, backlog: int = 1024,
                 mc=None) -> None:
        self.host = host
        self.relay_budget = int(relay_budget)
        self._on_frame = on_frame
        self._on_close = on_close
        self._mc = mc
        self._selector = selectors.DefaultSelector()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(backlog)
        self._lsock.setblocking(False)
        self.port = self._lsock.getsockname()[1]
        # self-pipe: cross-thread senders ring this to wake select()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._dirty_lock = threading.Lock()
        self._dirty: Set[PumpConnection] = set()  # guarded-by: _dirty_lock
        self._conns: Dict[socket.socket, PumpConnection] = {}
        self._want_write: Set[PumpConnection] = set()
        self._stopping = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.accepted = 0
        self.dropped = 0

    # -- lifecycle (off-loop) --------------------------------------------------

    def start(self) -> "FramePump":  # off-loop
        self._selector.register(self._lsock, selectors.EVENT_READ,
                                self._accept_ready)
        self._selector.register(self._wake_r, selectors.EVENT_READ,
                                self._wake_ready)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="framepump")
        self._thread.start()
        return self

    def close(self) -> None:  # off-loop
        """Stop the loop and close every socket.  Abrupt by design —
        buffered frames are NOT flushed (a replica SIGKILL and a
        graceful close are indistinguishable to clients, which is
        exactly the failover contract the drivers recover through)."""
        if self._stopping.is_set():
            return
        self._stopping.set()
        self._ring()
        if self._thread is not None \
                and self._thread is not threading.current_thread():
            self._thread.join(timeout=10)
        for conn in list(self._conns.values()):
            conn.close()
        self._conns.clear()
        try:
            self._lsock.close()
        except OSError:
            pass
        for sock in (self._wake_r, self._wake_w):
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._selector.close()
        except (OSError, RuntimeError):
            pass

    def connections(self) -> List[PumpConnection]:  # off-loop
        """Snapshot for stats; best-effort under concurrency (the dict
        is only mutated on the loop thread)."""
        return list(self._conns.values())

    # -- cross-thread write handshake (off-loop) -------------------------------

    def mark_dirty(self, conn: PumpConnection) -> None:  # off-loop
        """A writer queued bytes on ``conn``: hand it to the loop.  One
        wakeup byte per idle->busy transition, not per frame."""
        with self._dirty_lock:
            ring = not self._dirty
            self._dirty.add(conn)
        if ring:
            self._ring()

    def drop(self, conn: PumpConnection) -> None:  # off-loop
        """Close ``conn`` and have the loop forget it (response
        overflow, owner-side demote-to-dead)."""
        conn.close()
        self.mark_dirty(conn)  # the loop observes .closed and purges

    def _ring(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass  # pipe full = a wakeup is already pending; or closing

    # -- the loop (every method below runs on the pump thread) -----------------

    def _run(self) -> None:  # on-loop
        while not self._stopping.is_set():
            events = self._selector.select(timeout=0.5)
            for key, mask in events:
                key.data(key, mask)
            self._flush_dirty()

    def _accept_ready(self, key, mask) -> None:  # on-loop
        while True:
            try:
                sock, _addr = self._lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return  # listener closed mid-shutdown
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = PumpConnection(sock, self,
                                  relay_budget=self.relay_budget)
            self._conns[sock] = conn
            self.accepted += 1
            self._selector.register(sock, selectors.EVENT_READ,
                                    self._make_io_cb(conn))

    def _make_io_cb(self, conn: PumpConnection):  # on-loop
        def _cb(key, mask) -> None:
            if mask & selectors.EVENT_READ:
                self._read_ready(conn)
            if mask & selectors.EVENT_WRITE and not conn.closed:
                self._write_ready(conn)
        return _cb

    def _wake_ready(self, key, mask) -> None:  # on-loop
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    def _read_ready(self, conn: PumpConnection) -> None:  # on-loop
        try:
            data = conn.sock.recv(_READ_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._purge(conn)
            return
        if not data:
            self._purge(conn)  # EOF
            return
        try:
            frames = conn.parser.feed(data)
            for payload in frames:
                self._on_frame(conn, json.loads(payload))
        except ValueError as exc:
            # oversized frame or broken JSON: the stream is garbage
            if self._mc is not None:
                self._mc.logger.send({"eventName": "pumpFrameError",
                                      "error": str(exc)})
            self._purge(conn)

    def _write_ready(self, conn: PumpConnection) -> None:  # on-loop
        try:
            drained = conn.flush()
        except OSError:
            self._purge(conn)
            return
        if drained and conn in self._want_write:
            self._want_write.discard(conn)
            self._set_interest(conn, selectors.EVENT_READ)

    def _flush_dirty(self) -> None:  # on-loop
        with self._dirty_lock:
            if not self._dirty:
                return
            dirty = list(self._dirty)
            self._dirty.clear()
        for conn in dirty:
            if conn.closed:
                self._purge(conn)
                continue
            try:
                drained = conn.flush()
            except OSError:
                self._purge(conn)
                continue
            if not drained and conn not in self._want_write:
                self._want_write.add(conn)
                self._set_interest(conn, selectors.EVENT_READ
                                   | selectors.EVENT_WRITE)

    def _set_interest(self, conn: PumpConnection, mask: int) -> None:
        # on-loop
        try:
            self._selector.modify(conn.sock, mask,
                                  self._make_io_cb(conn))
        except (KeyError, ValueError, OSError):
            pass  # already purged / socket closed under us

    def _purge(self, conn: PumpConnection) -> None:  # on-loop
        try:
            self._selector.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        self._conns.pop(conn.sock, None)
        self._want_write.discard(conn)
        was_open = not conn.closed
        conn.close()
        if was_open:
            self.dropped += 1
        if self._on_close is not None:
            self._on_close(conn)
