"""Durable per-document op log — the Scriptorium capability.

Capability-equivalent of the reference's ``ScriptoriumLambda`` + the Mongo
``deltas`` collection it writes (SURVEY.md §2.3; upstream paths UNVERIFIED —
empty reference mount): every sequenced message is appended durably, and
catch-up (a loading client, or the TPU bulk-replay service) reads ranged
tails ``(from_seq, to_seq]``.

Persistence is newline-delimited canonical JSON (one record per line, fsync
on ``flush()``), append-only — reopening a log replays the file.  This is
the host-side feed that gets packed into ragged device tensors; keeping it
as a flat append-only byte stream is what makes the native packer able to
mmap and scan it without touching Python objects.

Durability contract (SEMANTICS.md "Durability & retry"): ``append`` is
exception-safe — a write that fails (injected fault or real OSError)
leaves NEITHER the in-memory view NOR the file holding the record, so the
caller's retry re-appends cleanly instead of being silently deduplicated
against a half-applied state.  A crash can tear only the final line;
reopen repairs it (``repair_jsonl_tail``) before reads or appends resume.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
from typing import Dict, List, Optional

from ..protocol.messages import ColumnAppendError, SequencedMessage
from ..protocol.summary import canonical_json
from ..protocol.wire import (ColumnSegment, decode_sequenced_message,
                             encode_sequenced_message, entry_last_seq)
from ..utils.jsonl import iter_jsonl_tolerant, repair_jsonl_tail


class TruncatedRangeError(OSError):
    """A ranged read asked for seqs below the document's truncation
    floor: the log no longer holds them.  Callers that can re-anchor on
    a summary at or above ``floor`` should; anything else is a bug —
    truncation only ever cuts below the newest durable summary AND the
    sequencer's minimum sequence number, so no live client's gap repair
    can land here."""

    def __init__(self, doc_id: str, from_seq: int, floor: int) -> None:
        super().__init__(
            f"doc {doc_id!r}: range from_seq={from_seq} is below the "
            f"truncation floor {floor}")
        self.doc_id = doc_id
        self.from_seq = from_seq
        self.floor = floor


def shard_log_path(base_dir: str, shard_id: str) -> str:
    """The canonical per-shard durable log location of the out-of-process
    tier (fluidproc): every shard host writes its OWN log file under the
    shared deployment directory, and failover/migration readers derive a
    dead or source shard's log from nothing but ``(base_dir, shard_id)``.
    """
    return os.path.join(base_dir, "shards", shard_id, "oplog.ndjson")


class OpLog:
    """Append-only sequenced-op store for many documents.

    In-memory by default; pass ``path`` for a durable file-backed log that
    survives process restarts (the crash-resume tests reopen it).
    ``faults`` (a ``testing.faults.FaultInjector``) arms the
    ``oplog.append`` / ``oplog.flush`` fault sites.

    ``read_only=True`` opens a file-backed log for READS only (no append
    handle is held and every write raises): the fluidproc adoption path —
    a surviving shard importing a SIGKILLed peer's documents from that
    peer's log file — must never become a second writer of a log whose
    owner could, in principle, still be mid-death.  The torn-tail repair
    still runs (it is exactly what the dead owner's restart would do).
    """

    def __init__(self, path: Optional[str] = None,
                 autoflush: bool = False, faults=None,
                 read_only: bool = False) -> None:
        if read_only and path is None:
            raise ValueError("read_only needs a file-backed log")
        self._docs: Dict[str, List[SequencedMessage]] = {}  # durable-shadow: log view
        #: summary-anchored truncation floor per doc: seqs <= floor have
        #: been sealed and dropped; reads from below raise
        #: :class:`TruncatedRangeError`.  0 = never truncated.
        self._floors: Dict[str, int] = {}
        #: orderer checkpoint persisted with each truncation marker so
        #: recovery of a truncated doc restores from it instead of
        #: replaying from seq 1 (which the log can no longer serve).
        self._trunc_ckpts: Dict[str, dict] = {}
        #: lifetime truncation counters (for stats surfaces)
        self.truncated_msgs = 0
        self.truncations = 0
        self.bytes_reclaimed = 0
        self._path = path
        self._autoflush = autoflush
        self._faults = faults
        self._read_only = read_only
        #: >0 while inside batch(): per-append autoflush is deferred to
        #: ONE flush at outermost batch exit (group commit)
        self._batch_depth = 0
        self._batch_dirty = False
        self._file: Optional[io.TextIOWrapper] = None  # durable-handle: single-record
        if path is not None:
            # The op log is the highest-write-rate file in the store: a
            # crash mid-append is likeliest here.  Repair the torn tail
            # (losing only the unacked final record) before reading or
            # appending, or the reopen would raise / the next append
            # would merge onto the partial line.
            repair_jsonl_tail(path)
            for rec in iter_jsonl_tolerant(path):
                trunc = rec.get("truncate")
                if trunc is not None:
                    # Truncation marker: everything at or below ``below``
                    # is sealed.  In an uncompacted log (crash between
                    # seal and drop) the marker FOLLOWS the old records,
                    # so applying it here drops them exactly as the
                    # interrupted truncation would have; in a compacted
                    # log it leads and the drop is a no-op.
                    self._apply_marker(rec["doc"], int(trunc["below"]),
                                       trunc.get("checkpoint"))
                    continue
                msg = decode_sequenced_message(rec["msg"])
                if msg.seq <= self._floors.get(rec["doc"], 0):
                    continue  # pre-marker replay of a sealed record
                log = self._docs.setdefault(rec["doc"], [])
                if log and msg.seq <= log[-1].seq:
                    if msg.seq == log[-1].seq:
                        # Duplicate seq on disk: either a failed-then-
                        # retried append re-wrote the identical record,
                        # or a PHANTOM — an append whose bytes landed but
                        # whose fsync failed was rolled back, and a
                        # different op later won the same seq.  The LAST
                        # line is what the live history actually
                        # broadcast in both cases; the first would
                        # resurrect a message no client ever saw.
                        log[-1] = msg
                    continue
                log.append(msg)
            if not read_only:
                self._file = open(path, "a", encoding="utf-8")

    # -- write side (the scriptorium lambda) -----------------------------------

    def _check_writable(self) -> None:
        if self._read_only:
            raise OSError(f"op log {self._path!r} is read-only "
                          "(adoption/backfill view of a peer shard's log)")

    def append(self, doc_id: str, msg: SequencedMessage) -> None:
        self._check_writable()
        if msg.seq <= self._floors.get(doc_id, 0):
            return  # sealed below the truncation floor: a replay no-op
        log = self._docs.setdefault(doc_id, [])
        if log and msg.seq <= entry_last_seq(log[-1]):
            return  # exactly-once: replays after crash-resume are idempotent
        fault = (self._faults.fire("oplog.append", doc=doc_id)
                 if self._faults is not None else None)
        if fault is not None and (fault.kind == "fail"
                                  or self._file is None):
            # In-memory logs have no bytes to tear: every armed kind
            # degrades to a plain append failure.
            from ..testing.faults import FaultError

            raise FaultError("oplog.append", fault.kind, doc_id)
        log.append(msg)
        if self._file is not None:
            rec = {"doc": doc_id, "msg": encode_sequenced_message(msg)}
            line = canonical_json(rec).decode("utf-8") + "\n"
            if fault is not None and fault.kind == "torn":
                self._torn_append(log, line, fault)
            try:
                self._file.write(line)  # commit-point: op record; unwinds: _docs
                if self._autoflush:
                    if self._batch_depth:
                        # Group commit (batched ingress): defer the fsync
                        # to the single flush at batch() exit — see the
                        # SEMANTICS.md batched-ingress note for what this
                        # weakens (in-process subscribers may observe a
                        # record before the batch's fsync lands).
                        self._batch_dirty = True
                    else:
                        # Durable-before-broadcast: the append rides first
                        # in the sequencer broadcast chain, so flushing
                        # here means no client ever sees an op the log
                        # could lose (the reference's scriptorium-
                        # durability property).
                        self.flush()
            except OSError:
                # Exception safety: the record is not durable, so it must
                # not stay visible in memory either — a retry would be
                # deduped against it and the durable log would keep a
                # hole.  Best-effort tail repair clears any partial bytes
                # (a record torn at the newline may instead be SEALED
                # complete — then the reopen-dedup above absorbs the
                # retry's duplicate line).
                log.pop()
                self._repair_open_tail()
                raise

    def append_columns(self, doc_id: str, segment: ColumnSegment) -> None:
        """Bulk columnar append: one in-memory entry and ONE bulk line
        encode for a whole stamped segment — the durable half of the
        columnar ingress path (``Sequencer.submit_columns``'s gate).

        Failure contract: raises :class:`ColumnAppendError` carrying how
        many rows landed durably; rows ``[0, landed)`` stay in the log
        (they may already be fsync-scheduled), everything later was
        never written.  With a fault injector armed the bulk path drops
        to per-row boxed appends so every ``oplog.append`` occurrence
        fires exactly as it would under per-op ingress — fault schedules
        line up byte-for-byte across the columnar and boxed modes.
        """
        self._check_writable()
        n = len(segment)
        if n == 0:
            return
        if segment.last_seq <= self._floors.get(doc_id, 0):
            return  # wholly below the truncation floor: a replay no-op
        log = self._docs.setdefault(doc_id, [])
        if self._faults is not None or (
                log and segment.start_seq <= entry_last_seq(log[-1])):
            # Fault-exact (or replayed-prefix dedup) slow path: per-row
            # boxed appends keep occurrence counting and exactly-once
            # semantics identical to per-op ingress.
            for j in range(n):
                try:
                    self.append(doc_id, segment.materialize(j))
                except BaseException as err:
                    if not isinstance(err, Exception):
                        raise
                    raise ColumnAppendError(j, err) from err
            return
        log.append(segment)
        if self._file is None:
            return
        # ONE bulk encode; the writes ride the shared buffered handle so
        # a failure isolates to the row it hit, like per-op appends.
        lines = [canonical_json({"doc": doc_id,
                                 "msg": segment.wire_dict(j)}
                                ).decode("utf-8") + "\n"
                 for j in range(n)]
        landed = 0
        try:
            for line in lines:
                self._file.write(line)  # commit-point: columnar op records; unwinds: _docs
                landed += 1
            if self._autoflush:
                if self._batch_depth:
                    self._batch_dirty = True
                else:
                    self.flush()
        except OSError as err:
            # Keep the landed prefix (its bytes are written and may be
            # durable), drop the failed row and everything after it,
            # repair any partial final line.
            if landed:
                log[-1] = segment.prefix(landed)
            else:
                log.pop()
            self._repair_open_tail()
            raise ColumnAppendError(landed, err) from err

    def _torn_append(self, log: List[SequencedMessage], line: str,
                     fault) -> None:
        """Injected torn partial write: a strict prefix of the record
        reaches the disk (fsynced — the tear is as durable as a real
        crash would make it), then the append fails and the log
        self-repairs by truncating back to the record start.  The caller
        sees an OSError; the file never serves the torn bytes."""
        from ..testing.faults import FaultError

        self._file.flush()
        start = os.fstat(self._file.fileno()).st_size
        frac = fault.arg if 0.0 < fault.arg < 1.0 else 0.5
        cut = max(1, min(len(line) - 2, int(len(line) * frac)))
        self._file.write(line[:cut])
        self._file.flush()
        os.fsync(self._file.fileno())
        with open(self._path, "r+b") as g:
            g.truncate(start)
        log.pop()
        raise FaultError("oplog.append", "torn",
                         f"{cut}/{len(line)} bytes")

    def _repair_open_tail(self) -> None:
        """Best-effort: clear a partial final line left by a failed write
        so later appends do not merge onto it.  The append handle is
        O_APPEND — its next write lands at the repaired EOF.  Tolerates a
        concurrently-sealed handle (ValueError on a closed file): the
        on-disk repair below is the part that matters."""
        try:
            if self._file is not None:
                self._file.flush()
        except (OSError, ValueError):
            pass
        try:
            repair_jsonl_tail(self._path)
        except OSError:
            pass

    @contextlib.contextmanager
    def batch(self):
        """Group commit: appends inside the block skip their per-append
        autoflush; the outermost exit pays ONE flush (fsync) for the whole
        batch — the per-batch durability point of the batched ingress
        surface (``ShardedOrderingService.submit_many``).  Exception-safe:
        a batch that aborts partway still flushes the records that landed
        (they were broadcast; they must not be losable), and a FAILED
        deferred flush keeps the batch marked dirty — the records' bytes
        were already written to the file object, so the next successful
        flush (a later batch exit, an explicit ``flush()``, or ``close``)
        makes them durable; the failure itself propagates so no caller
        mistakes the batch for committed.  Nests: inner batches defer to
        the outermost.  In-memory logs (no file) make this a no-op."""
        self._batch_depth += 1
        try:
            yield self
        finally:
            self._batch_depth -= 1
            if self._batch_depth == 0 and self._batch_dirty:
                self.flush()
                self._batch_dirty = False

    def flush(self) -> None:
        if self._file is not None:
            fault = (self._faults.fire("oplog.flush")
                     if self._faults is not None else None)
            if fault is not None and fault.kind == "fail":
                from ..testing.faults import FaultError

                raise FaultError("oplog.flush", "fail")
            self._file.flush()
            if fault is not None and fault.kind == "skip_fsync":
                return  # delayed fsync: bytes sit in the page cache
            os.fsync(self._file.fileno())

    def close(self) -> None:
        if self._file is not None:
            self.flush()
            self._file.close()
            self._file = None

    # -- summary-anchored truncation -------------------------------------------

    def floor(self, doc_id: str) -> int:
        """The document's truncation floor: highest seq sealed and
        dropped (0 if never truncated).  Reads must start at or above
        it; ``get(doc, from_seq=floor)`` is the exact boundary read."""
        return self._floors.get(doc_id, 0)

    def truncation_checkpoint(self, doc_id: str) -> Optional[dict]:
        """The orderer checkpoint persisted with the newest truncation
        marker, or None.  Recovery of a truncated doc restores from this
        instead of full replay (the sealed prefix is gone)."""
        return self._trunc_ckpts.get(doc_id)

    def _apply_marker(self, doc_id: str, below: int,
                      checkpoint: Optional[dict]) -> int:
        """Apply a truncation floor to the in-memory view: raise the
        floor, remember the checkpoint, drop entries wholly at or below
        the cut.  A columnar segment straddling the cut stays whole —
        the floor still guards reads into its sealed prefix."""
        if below <= self._floors.get(doc_id, 0):
            return 0
        self._floors[doc_id] = below
        if checkpoint is not None:
            self._trunc_ckpts[doc_id] = checkpoint
        log = self._docs.get(doc_id)
        if not log:
            return 0
        kept = []
        dropped = 0
        for entry in log:
            if entry_last_seq(entry) <= below:
                dropped += (len(entry)
                            if isinstance(entry, ColumnSegment) else 1)
            else:
                kept.append(entry)
        self._docs[doc_id] = kept
        return dropped

    def truncate(self, doc_id: str, below_seq: int,
                 checkpoint: Optional[dict] = None) -> int:
        """Summary-anchored truncation: seal and drop every record with
        ``seq <= below_seq``.  Returns the number of messages dropped.

        The CALLER owns the safety argument — ``below_seq`` must be at
        or under both the newest durable summary's ref_seq (so catch-up
        can always re-anchor) and the sequencer's minimum sequence
        number (so no live client's gap repair lands below the cut);
        see ``service.streamfold``.  ``checkpoint`` (an orderer
        checkpoint) rides in the durable marker so a later recovery can
        restore without the sealed prefix.

        Crash discipline mirrors the PR 12 migration points: the
        ``oplog.truncate.seal`` site fires BEFORE the marker is durable
        (a crash here leaves the log byte-identical — nothing happened);
        the marker line is then appended and fsynced (the commit point);
        ``oplog.truncate.drop`` fires AFTER the marker but BEFORE
        physical compaction (a crash here reopens to the same floor —
        the marker re-applies the drop — with the dead bytes reclaimed
        by the next successful truncation's rewrite)."""
        self._check_writable()
        below_seq = min(below_seq, self.head(doc_id))
        if below_seq <= self._floors.get(doc_id, 0):
            return 0
        fault = (self._faults.fire("oplog.truncate.seal", doc=doc_id)
                 if self._faults is not None else None)
        if fault is not None:
            from ..testing.faults import FaultError

            raise FaultError("oplog.truncate.seal", fault.kind, doc_id)
        if self._file is not None:
            rec = {"doc": doc_id,
                   "truncate": {"below": below_seq,
                                "checkpoint": checkpoint}}
            self._file.write(canonical_json(rec).decode("utf-8") + "\n")
            self.flush()  # commit-point: truncation marker fsync
        dropped = self._apply_marker(doc_id, below_seq, checkpoint)
        self.truncations += 1
        self.truncated_msgs += dropped
        fault = (self._faults.fire("oplog.truncate.drop", doc=doc_id)
                 if self._faults is not None else None)
        if fault is not None:
            from ..testing.faults import FaultError

            raise FaultError("oplog.truncate.drop", fault.kind, doc_id)
        if self._file is not None:
            self._compact()
        return dropped

    def adopt_floor(self, doc_id: str, below: int,
                    checkpoint: Optional[dict] = None) -> None:
        """Import-side floor adoption (migration/failover of a TRUNCATED
        document): persist the source log's truncation marker into THIS
        log verbatim.  Unlike :meth:`truncate` there is no head clamp
        and no crash-point choreography — the sealed prefix never
        crossed the wire, so there is nothing here to seal or drop;
        the marker just records that seqs at or below ``below`` are
        vouched for by the summary anchor, and carries the recovery
        checkpoint along."""
        self._check_writable()
        if below <= self._floors.get(doc_id, 0):
            return
        if self._file is not None:
            rec = {"doc": doc_id,
                   "truncate": {"below": below, "checkpoint": checkpoint}}
            self._file.write(canonical_json(rec).decode("utf-8") + "\n")
            self.flush()  # commit-point: adopted truncation marker
        self._apply_marker(doc_id, below, checkpoint)

    def _compact(self) -> None:
        """Physically drop sealed bytes: rewrite the whole file from the
        in-memory view (markers first so a reopen raises each doc's
        floor before its surviving records), fsync the replacement, then
        atomically swap it in and reopen the append handle.  Atomicity
        rides ``os.replace`` — a crash mid-rewrite leaves the original
        intact and the tmp file as garbage."""
        before = os.path.getsize(self._path) if os.path.exists(
            self._path) else 0
        tmp = self._path + ".compact"
        with open(tmp, "w", encoding="utf-8") as out:
            for doc_id in sorted(set(self._docs) | set(self._floors)):
                floor = self._floors.get(doc_id, 0)
                if floor:
                    rec = {"doc": doc_id,
                           "truncate": {
                               "below": floor,
                               "checkpoint":
                                   self._trunc_ckpts.get(doc_id)}}
                    out.write(canonical_json(rec).decode("utf-8") + "\n")
                for entry in self._docs.get(doc_id, []):
                    if isinstance(entry, ColumnSegment):
                        for j in range(len(entry)):
                            rec = {"doc": doc_id,
                                   "msg": entry.wire_dict(j)}
                            out.write(canonical_json(rec)
                                      .decode("utf-8") + "\n")
                    else:
                        rec = {"doc": doc_id,
                               "msg": encode_sequenced_message(entry)}
                        out.write(canonical_json(rec)
                                  .decode("utf-8") + "\n")
            out.flush()
            os.fsync(out.fileno())
        if self._file is not None:
            self._file.close()
        os.replace(tmp, self._path)
        self._file = open(self._path, "a", encoding="utf-8")
        try:  # best-effort directory fsync so the rename is durable
            dfd = os.open(os.path.dirname(self._path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        self.bytes_reclaimed += max(
            0, before - os.path.getsize(self._path))

    # -- read side (catch-up) --------------------------------------------------

    def doc_ids(self) -> List[str]:
        return sorted(self._docs)

    def head(self, doc_id: str) -> int:
        """Highest sequenced seq for the document (0 if none).  A
        truncated-then-idle doc reports its floor: the sealed history
        still happened even though its bytes are gone."""
        log = self._docs.get(doc_id)
        if log:
            return entry_last_seq(log[-1])
        return self._floors.get(doc_id, 0)

    def get(
        self, doc_id: str, from_seq: int = 0, to_seq: Optional[int] = None
    ) -> List[SequencedMessage]:
        """Ranged read: messages with ``from_seq < seq <= to_seq`` in order
        (the loader's catch-up fetch; half-open so ``from_seq`` is 'the seq
        my summary already covers').  Columnar segments materialize their
        in-range rows on the fly — readers always see plain
        :class:`SequencedMessage` objects.

        Raises :class:`TruncatedRangeError` when ``from_seq`` is below
        the truncation floor: the sealed prefix is gone and serving a
        silently-gapped tail would corrupt the reader.  The boundary
        read ``from_seq == floor`` is legal (half-open range — the
        floor seq itself is never returned)."""
        floor = self._floors.get(doc_id, 0)
        if from_seq < floor:
            raise TruncatedRangeError(doc_id, from_seq, floor)
        log = self._docs.get(doc_id, [])
        out = []
        for entry in log:
            if isinstance(entry, ColumnSegment):
                if entry.last_seq <= from_seq:
                    continue
                if to_seq is not None and entry.start_seq > to_seq:
                    break
                for j in range(len(entry)):
                    s = entry.start_seq + j
                    if s <= from_seq:
                        continue
                    if to_seq is not None and s > to_seq:
                        break
                    out.append(entry.materialize(j))
                continue
            if entry.seq <= from_seq:
                continue
            if to_seq is not None and entry.seq > to_seq:
                break
            out.append(entry)
        return out

    def is_contiguous(self, doc_id: str) -> bool:
        """True iff the document's seqs are exactly 1..head with no gap
        or duplicate — O(entries), not O(messages): columnar segments
        are contiguous by construction (seqs are an arange), so only
        their boundaries need checking.  A truncated doc is contiguous
        from its floor: the sealed prefix is vouched for by the marker's
        summary anchor, not re-checked."""
        floor = self._floors.get(doc_id, 0)
        prev = floor
        for entry in self._docs.get(doc_id, []):
            if isinstance(entry, ColumnSegment):
                if len(entry) == 0:
                    continue
                # A segment straddling the truncation cut is kept whole;
                # only its live suffix (> floor) counts for contiguity.
                start = max(entry.start_seq, floor + 1)
                if start != prev + 1:
                    return False
                prev = entry.last_seq
            else:
                if entry.seq != prev + 1:
                    return False
                prev = entry.seq
        return True
