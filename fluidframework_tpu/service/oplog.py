"""Durable per-document op log — the Scriptorium capability.

Capability-equivalent of the reference's ``ScriptoriumLambda`` + the Mongo
``deltas`` collection it writes (SURVEY.md §2.3; upstream paths UNVERIFIED —
empty reference mount): every sequenced message is appended durably, and
catch-up (a loading client, or the TPU bulk-replay service) reads ranged
tails ``(from_seq, to_seq]``.

Persistence is newline-delimited canonical JSON (one record per line, fsync
on ``flush()``), append-only — reopening a log replays the file.  This is
the host-side feed that gets packed into ragged device tensors; keeping it
as a flat append-only byte stream is what makes the native packer able to
mmap and scan it without touching Python objects.
"""

from __future__ import annotations

import io
import json
import os
from typing import Dict, List, Optional

from ..protocol.messages import SequencedMessage
from ..protocol.summary import canonical_json
from ..protocol.wire import (decode_sequenced_message,
                             encode_sequenced_message)
from ..utils.jsonl import iter_jsonl_tolerant, repair_jsonl_tail


class OpLog:
    """Append-only sequenced-op store for many documents.

    In-memory by default; pass ``path`` for a durable file-backed log that
    survives process restarts (the crash-resume tests reopen it).
    """

    def __init__(self, path: Optional[str] = None,
                 autoflush: bool = False) -> None:
        self._docs: Dict[str, List[SequencedMessage]] = {}
        self._path = path
        self._autoflush = autoflush
        self._file: Optional[io.TextIOWrapper] = None
        if path is not None:
            # The op log is the highest-write-rate file in the store: a
            # crash mid-append is likeliest here.  Repair the torn tail
            # (losing only the unacked final record) before reading or
            # appending, or the reopen would raise / the next append
            # would merge onto the partial line.
            repair_jsonl_tail(path)
            for rec in iter_jsonl_tolerant(path):
                self._docs.setdefault(rec["doc"], []).append(
                    decode_sequenced_message(rec["msg"])
                )
            self._file = open(path, "a", encoding="utf-8")

    # -- write side (the scriptorium lambda) -----------------------------------

    def append(self, doc_id: str, msg: SequencedMessage) -> None:
        log = self._docs.setdefault(doc_id, [])
        if log and msg.seq <= log[-1].seq:
            return  # exactly-once: replays after crash-resume are idempotent
        log.append(msg)
        if self._file is not None:
            rec = {"doc": doc_id, "msg": encode_sequenced_message(msg)}
            self._file.write(canonical_json(rec).decode("utf-8") + "\n")
            if self._autoflush:
                # Durable-before-broadcast: the append rides first in the
                # sequencer broadcast chain, so flushing here means no
                # client ever sees an op the log could lose (the
                # reference's scriptorium-durability property).
                self.flush()

    def flush(self) -> None:
        if self._file is not None:
            self._file.flush()
            os.fsync(self._file.fileno())

    def close(self) -> None:
        if self._file is not None:
            self.flush()
            self._file.close()
            self._file = None

    # -- read side (catch-up) --------------------------------------------------

    def doc_ids(self) -> List[str]:
        return sorted(self._docs)

    def head(self, doc_id: str) -> int:
        """Highest sequenced seq for the document (0 if none)."""
        log = self._docs.get(doc_id)
        return log[-1].seq if log else 0

    def get(
        self, doc_id: str, from_seq: int = 0, to_seq: Optional[int] = None
    ) -> List[SequencedMessage]:
        """Ranged read: messages with ``from_seq < seq <= to_seq`` in order
        (the loader's catch-up fetch; half-open so ``from_seq`` is 'the seq
        my summary already covers')."""
        log = self._docs.get(doc_id, [])
        out = []
        for msg in log:
            if msg.seq <= from_seq:
                continue
            if to_seq is not None and msg.seq > to_seq:
                break
            out.append(msg)
        return out
