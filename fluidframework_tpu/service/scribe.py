"""Scribe — protocol-state keeper and summary validator.

Capability-equivalent of the reference's ``ScribeLambda`` + ``SummaryWriter``
(SURVEY.md §2.3/§3.3; upstream paths UNVERIFIED — empty reference mount):
watches the sequenced stream for ``summarize`` ops, validates them against
storage and the current protocol state, records the accepted commit, and
stamps a server-originated ``summaryAck`` (or ``summaryNack`` with a reason)
back into the stream so every client converges on the same
last-acked-summary.
"""

from __future__ import annotations

from typing import Optional

from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.sequencer import Sequencer
from ..protocol.summary import SummaryStorage, SummaryTree


class Scribe:
    """Per-document summary validation + ack."""

    def __init__(
        self, doc_id: str, sequencer: Sequencer, storage: SummaryStorage
    ) -> None:
        self.doc_id = doc_id
        self._sequencer = sequencer
        self._storage = storage
        self.last_acked_handle: Optional[str] = None
        self.last_acked_seq = 0  # ref_seq covered by the accepted summary
        self.acks = 0
        self.nacks = 0
        sequencer.subscribe(self._on_message)

    # -- the lambda ------------------------------------------------------------

    def _on_message(self, msg: SequencedMessage) -> None:
        if msg.type is not MessageType.SUMMARIZE:
            return
        handle = msg.contents.get("handle")
        ref_seq = msg.contents.get("seq", -1)
        reason = self._validate(handle, ref_seq, msg.seq)
        if reason is None:
            self.last_acked_handle = handle
            self.last_acked_seq = ref_seq
            self.acks += 1
            ack = {"handle": handle, "seq": ref_seq, "summarizeSeq": msg.seq}
            # Stamp the git-style commit this summary landed as (the
            # reference's ack carries the service's summary commit handle).
            commit = self._storage.commit_for(self.doc_id, handle, ref_seq)
            if commit is not None:
                ack["commit"] = commit
            self._sequencer.server_message(MessageType.SUMMARY_ACK, ack)
        else:
            self.nacks += 1
            self._sequencer.server_message(
                MessageType.SUMMARY_NACK,
                {"handle": handle, "seq": ref_seq, "reason": reason,
                 "summarizeSeq": msg.seq},
            )

    def _validate(
        self, handle: Optional[str], ref_seq: int, summarize_seq: int
    ) -> Optional[str]:
        """None = accept; otherwise the nack reason."""
        if not handle:
            return "missing summary handle"
        try:
            node = self._storage.read(handle)
        except KeyError:
            return "unknown summary handle (not uploaded)"
        if not isinstance(node, SummaryTree):
            return "summary handle does not address a tree"
        if ref_seq < 0 or ref_seq >= summarize_seq:
            return "summary reference sequence out of range"
        if ref_seq < self.last_acked_seq:
            return "summary older than last accepted summary"
        return None

    def replay(self, msg: SequencedMessage) -> None:
        """Crash-resume: reconstruct ack state from log messages stamped
        after the checkpoint (acks are durable; re-validating would
        double-stamp them)."""
        if msg.type is MessageType.SUMMARY_ACK:
            self.last_acked_handle = msg.contents["handle"]
            self.last_acked_seq = msg.contents["seq"]
            self.acks += 1
        elif msg.type is MessageType.SUMMARY_NACK:
            self.nacks += 1

    # -- checkpoint (crash-resume, like Deli's) --------------------------------

    def checkpoint(self) -> dict:
        return {
            "lastAckedHandle": self.last_acked_handle,
            "lastAckedSeq": self.last_acked_seq,
            "acks": self.acks,
            "nacks": self.nacks,
        }

    def restore(self, state: dict) -> None:
        self.last_acked_handle = state["lastAckedHandle"]
        self.last_acked_seq = state["lastAckedSeq"]
        self.acks = state["acks"]
        self.nacks = state["nacks"]
