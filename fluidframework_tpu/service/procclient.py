"""fluidproc client adapter: the service surface over the front door.

What lets the fluidscale swarm (``testing/scenarios.py``) — and any other
harness written against the in-process ``LocalOrderingService`` /
``ShardedOrderingService`` duck type — drive the REAL out-of-process
tier unchanged: batched ingress ships as ONE ``submit_mixed`` RPC per
tick (boxed op dicts + the struct-packed columnar batch), durable heads
and contiguity checks read back over bulk routes, and summary uploads
ride the existing ``upload_summary`` RPC.  The front door object runs
in-process (it IS the harness's supervisor); only the shards are real
processes.

Replica HA (ISSUE 18): the adapter takes an optional list of REPLICA
doors fronting the same shard fleet.  The data path pins to the newest
replica; when its socket dies (a replica SIGKILL drops every connection
with nothing flushed) the adapter rotates to the next live door and
resends — safe for every route it carries, because submits dedup by
(client, client_seq) server-side and reads are idempotent.  Control
calls (``tick``, ``router``, ``stats``) stay direct object calls on the
PRIMARY door: the fault-plan driver is the harness's supervisor, not a
wire client.

The adapter deliberately implements the NARROW surface the swarm
consumes — ``endpoint(doc).connect_many/connect_columns``,
``submit_mixed``, ``oplog.head/batch/is_contiguous``, ``storage.upload``,
``heads``, ``tick``, ``router`` — not the full service contract; real
clients use ``NetworkDocumentServiceFactory`` against the front door.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import numpy as np

from ..drivers.network_driver import (RpcTimeoutError, RpcTransportError,
                                      _RpcClient)
from ..protocol import errors as wire_errors
from ..protocol.summary import tree_to_obj
from ..protocol.wire import ColumnBatch, encode_column_batch, \
    encode_raw_operation
from .frontdoor import FrontDoor
from .orderer import SubmitOutcome


class ProcEndpoint:
    """Per-document ingress facade over the front door (JOIN cohorts;
    per-op routes ride the network driver, not this adapter)."""

    def __init__(self, client: "ProcServiceClient", doc_id: str) -> None:
        self._client = client
        self.doc_id = doc_id

    def connect_many(self, client_ids: List[str],
                     session: Optional[str] = None) -> None:
        self._client.request("connect_many", {
            "doc": self.doc_id, "clients": list(client_ids),
            "session": session, "columnar": False})

    def connect_columns(self, client_ids: List[str],
                        session: Optional[str] = None) -> None:
        self._client.request("connect_many", {
            "doc": self.doc_id, "clients": list(client_ids),
            "session": session, "columnar": True})


class _ProcLogView:
    """The swarm's ``service.oplog`` reads, over the wire.  ``batch()``
    is a no-op context: group commit happens server-side — each shard's
    ``submit_mixed`` already lands under ONE flush of ITS log."""

    def __init__(self, client: "ProcServiceClient") -> None:
        self._client = client

    def head(self, doc_id: str) -> int:
        return self._client.heads([doc_id])[doc_id]

    def is_contiguous(self, doc_id: str) -> bool:
        return bool(self._client.request("log_contiguous",
                                         {"doc": doc_id}))

    def batch(self):
        return contextlib.nullcontext(self)


class _ProcStorageView:
    """``service.storage.upload`` for the swarm's summary elections."""

    def __init__(self, client: "ProcServiceClient") -> None:
        self._client = client

    def upload(self, doc_id: str, tree, ref_seq: int) -> str:
        result = self._client.request("upload_summary", {
            "doc": doc_id, "summary": tree_to_obj(tree),
            "ref_seq": ref_seq})
        return result["handle"]


def _decode_outcome(wire: dict) -> SubmitOutcome:
    error: Optional[BaseException] = None
    if wire.get("error") is not None:
        # Typed-enough reconstruction: the swarm's recovery contract only
        # branches on "failed at all" (defer + whole-batch resubmit).
        # The code must still be a registered outcome-channel row
        # (protocol/errors.py); taxonomy drift is stamped into the text
        # instead of silently passing as a registered failure.
        code = wire.get("code")
        if not wire_errors.is_registered(code):
            code = f"unregistered:{code}"
        error = ConnectionError(f"[{code}] {wire['error']}")
    return SubmitOutcome(stamped=[], consumed=int(wire["consumed"]),
                         error=error, stamped_count=int(wire["stamped"]))


class ProcServiceClient:
    """The ordering-tier surface of a fluidproc deployment, for swarm
    harnesses.  One RPC connection to an (in-process) front door — the
    newest replica when replicas exist — with dead-door rotation; the
    fault-plan ``tick`` and the router are direct object calls on the
    primary — the supervisor is local even though every shard is a
    separate process."""

    def __init__(self, door: FrontDoor, timeout: float = 120.0,
                 replicas: Optional[List[FrontDoor]] = None) -> None:
        self.door = door
        self._timeout = float(timeout)
        #: every door fronting the fleet, primary first; the data path
        #: pins to the LAST (newest replica) so a replica-death drill
        #: kills the door the traffic actually rides.
        self.doors: List[FrontDoor] = [door] + list(replicas or [])
        self._at = len(self.doors) - 1
        self.rpc = _RpcClient("127.0.0.1", self.doors[self._at].port,
                              timeout=self._timeout)
        #: door rotations taken (the drill pins this went >= 1)
        self.door_failovers = 0
        self.oplog = _ProcLogView(self)
        self.storage = _ProcStorageView(self)

    def request(self, method: str, params: dict,
                timeout: Optional[float] = None):
        """One RPC with door failover: a dead socket (replica SIGKILL)
        rotates to the next live door and resends.  Typed refusals
        (nack / wrongShard / fence) pass through — they are the
        SERVICE talking, not the transport dying; only transport-shaped
        failures rotate.  Resends are safe on every adapter route:
        submits dedup by (client, client_seq), everything else is a
        read or an idempotent registration."""
        last: Optional[BaseException] = None
        for _attempt in range(len(self.doors) + 1):
            try:
                return self.rpc.request(method, params, timeout=timeout)
            except (RpcTransportError, RpcTimeoutError) as exc:
                last = exc
                if not self._rotate_door():
                    break
        raise last

    def _rotate_door(self) -> bool:
        """Reconnect to the next door not known-dead (``killed`` is the
        harness's own flag; a door killed out-of-band just fails its
        connect and the rotation continues).  Returns False when every
        candidate is exhausted."""
        try:
            self.rpc.close()
        except OSError:
            pass
        for step in range(1, len(self.doors) + 1):
            idx = (self._at - step) % len(self.doors)
            candidate = self.doors[idx]
            if candidate.killed:
                continue
            try:
                self.rpc = _RpcClient("127.0.0.1", candidate.port,
                                      timeout=self._timeout)
            except OSError:
                continue
            self._at = idx
            self.door_failovers += 1
            return True
        return False

    @property
    def router(self):
        return self.door.router

    def tick(self, now: int) -> List[str]:
        return self.door.tick(now)

    def endpoint(self, doc_id: str) -> ProcEndpoint:
        return ProcEndpoint(self, doc_id)

    def heads(self, doc_ids: List[str]) -> Dict[str, int]:
        if not doc_ids:
            return {}
        return self.request("heads", {"docs": list(doc_ids)})

    def contiguous(self, doc_ids: List[str]) -> Dict[str, bool]:
        if not doc_ids:
            return {}
        return self.request("log_contiguous", {"docs": list(doc_ids)})

    def doc_ids(self) -> List[str]:
        return self.door.doc_ids()

    def submit_mixed(self, batches: Optional[Dict[str, list]],
                     batch: Optional[ColumnBatch],
                     doc_rows: Optional[Dict[str, np.ndarray]]
                     ) -> Dict[str, SubmitOutcome]:
        """ONE RPC per tick: boxed batches as codec dicts, the columnar
        batch struct-packed (compact tables) with per-doc row RANGES —
        swarm rows are contiguous per document by construction, and the
        range form keeps the frame small."""
        payload: dict = {"batches": {
            doc: [encode_raw_operation(op) for op in ops]
            for doc, ops in (batches or {}).items()
        }}
        if batch is not None and doc_rows:
            ranges = {}
            for doc, rows in doc_rows.items():
                s, e = int(rows[0]), int(rows[-1]) + 1
                if e - s != rows.shape[0]:
                    raise ValueError(
                        f"non-contiguous row slice for {doc!r}")
                ranges[doc] = [s, e]
            payload["columns"] = encode_column_batch(batch)
            payload["doc_rows"] = ranges
        out = self.request("submit_mixed", payload)
        return {doc: _decode_outcome(w) for doc, w in out.items()}

    def submit_many(self, batches: Dict[str, list]
                    ) -> Dict[str, SubmitOutcome]:
        return self.submit_mixed(batches, None, None)

    def stats(self) -> dict:
        return self.door.stats()

    def close(self) -> None:
        self.rpc.close()
