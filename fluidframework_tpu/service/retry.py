"""RetryPolicy: bounded, deterministic, jittered exponential backoff.

The faultline engine (``testing/faults.py``) immediately exposes what the
stack was missing: nothing ever retried.  An ``RpcError`` killed the
caller; a transient durable-append failure unwound the whole submit.
This module is the one retry primitive every site uses — the
``FL-RACE-WAITFOREVER`` discipline applied to retry loops:

- **bounded**: ``max_attempts`` AND a total backoff ``budget`` (seconds
  of the injected clock); exhausting either surfaces the typed
  :class:`~..protocol.messages.RetryBudgetExhaustedError`, never a silent
  infinite loop;
- **deterministic**: backoff delays come from the *injected* clock/rng —
  a replay harness passes a ``VirtualClock`` (whose ``sleep`` advances
  virtual time) and a seeded ``random.Random``, making every retry
  schedule a pure function of its inputs; live hosts get wall-clock
  defaults and decorrelated jitter by passing their own rng;
- **nack-aware**: a :class:`NackError` hold waits ``max(backoff,
  retry_after)`` — the service's own pacing is never undercut;
- **fence-aware**: :class:`ShardFencedError` is only retryable when the
  caller supplies ``on_fence`` (re-resolve through the router); a plain
  retry against a fenced orderer can never succeed and re-raises
  immediately.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Tuple

from ..protocol.messages import (NackError, RetryBudgetExhaustedError,
                                 ShardFencedError)
from ..utils.telemetry import LockedCounterSet

#: transient failures worth a blind resend: the transport/durability
#: layer hiccupped and the SAME bytes may land next time.  (NackError is
#: a ConnectionError subclass and is handled specially — its hold is the
#: server's, not the policy's; ShardFencedError likewise.)
DEFAULT_RETRY_ON: Tuple[type, ...] = (ConnectionError, OSError,
                                      TimeoutError)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Deterministic jittered exponential backoff with a hard budget.

    Delay for attempt ``n`` (1-based, after the n-th failure):
    ``min(max_delay, base_delay * multiplier**(n-1)) * (1 - jitter * u)``
    with ``u`` drawn from the caller's rng — jitter only ever *shortens*
    a delay, so ``budget`` math stays a safe upper bound.
    """

    max_attempts: int = 5
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 5.0
    jitter: float = 0.5
    budget: float = 30.0

    def delay_for(self, attempt: int, rng: random.Random) -> float:
        raw = min(self.max_delay,
                  self.base_delay * self.multiplier ** (attempt - 1))
        return raw * (1.0 - self.jitter * rng.random())

    def run(
        self,
        fn: Callable[[], object],
        *,
        operation: str = "operation",
        sleep: Optional[Callable[[float], None]] = None,
        rng: Optional[random.Random] = None,
        retry_on: Tuple[type, ...] = DEFAULT_RETRY_ON,
        no_retry: Tuple[type, ...] = (),
        on_fence: Optional[Callable[[], None]] = None,
        counters: Optional[LockedCounterSet] = None,
    ) -> object:
        """Run ``fn`` under this policy.

        ``sleep`` is the backoff actuator (``time.sleep`` by default; a
        ``VirtualClock.sleep`` in replay harnesses).  ``no_retry`` takes
        precedence over ``retry_on`` (e.g. retry RpcError but never its
        EpochMismatchError subclass).  ``on_fence`` makes
        ShardFencedError retryable by re-resolving before the next
        attempt.  ``counters`` (when given) receives ``retry.attempts``,
        ``retry.retries``, ``retry.fence_resolves``,
        ``retry.nack_holds``, ``retry.exhausted`` bumps — the
        bench/oracle surface.
        """
        do_sleep = sleep if sleep is not None else time.sleep
        dice = rng if rng is not None else random.Random(0)
        slept = 0.0
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            if counters is not None:
                counters.bump("retry.attempts")
            try:
                return fn()
            except no_retry:
                # Checked FIRST: a site that declares e.g. NackError or
                # EpochMismatchError non-retryable keeps its own layer's
                # handling (the DeltaManager owns nack holds; epoch
                # mismatches need a reload, not a resend).
                raise
            except ShardFencedError as exc:
                if on_fence is None:
                    raise
                last = exc
                if counters is not None:
                    counters.bump("retry.fence_resolves")
                on_fence()
                delay = 0.0  # re-resolve IS the recovery; no backoff
            except NackError as exc:
                last = exc
                # The server's own pacing (retry_after) is never
                # undercut — with the round-15 adaptive admission it is
                # load-derived, not a constant, so the hold is the
                # overload signal worth counting.
                if counters is not None:
                    counters.bump("retry.nack_holds")
                delay = max(self.delay_for(attempt, dice),
                            float(exc.retry_after))
            except retry_on as exc:
                last = exc
                delay = self.delay_for(attempt, dice)
            if attempt == self.max_attempts or slept + delay > self.budget:
                break
            if counters is not None:
                counters.bump("retry.retries")
            if delay > 0.0:
                do_sleep(delay)
                slept += delay
        if counters is not None:
            counters.bump("retry.exhausted")
        raise RetryBudgetExhaustedError(operation, attempt, slept, last) \
            from last
