"""fluidproc shard host: ONE orderer shard as a standalone server process.

The out-of-process half of the sharded ordering tier (ISSUE 12).  PR 7's
``ShardedOrderingService`` partitioned documents across N orderer shards
but kept them in one process over ONE shared durable log — the last
single point of serialization.  A shard host is that shard taken out of
process, the way the reference scales Deli/Scriptorium as separate
services (PAPER.md §2.3):

- one :class:`~.orderer.LocalOrderingService` holding this shard's
  documents,
- its **own durable op log** file (``shard_log_path(base_dir, shard_id)``
  — per-shard logs are what make N shards N independent fsync streams),
- the **shared content-addressed summary store** on disk
  (``<base_dir>/summaries`` — content addressing makes cross-process
  sharing safe: objects publish by atomic rename, per-doc ref chains are
  only ever written by the doc's single owner),
- the existing :class:`~.server.OrderingServer` frame protocol over TCP,
  extended with the shard control plane the front door drives:

  ====================  =====================================================
  ``shard_info``        identity: shard id, pid, epoch, base dir
  ``stats``             per-shard counters (docs, ops, heads, retired)
  ``heads``             bulk ``{doc: durable head}`` read
  ``log_contiguous``    per-doc seq-contiguity check on the durable log
  ``submit_mixed``      batched ingress: boxed + columnar shapes, one
                        group commit (the swarm path over the wire)
  ``connect_many``      bulk JOIN cohort for one document
  ``bump_epoch``        adopt the fence epoch the front door derived
  ``freeze_doc``        migration step 1: fence the orderer, seal its
                        bytes, return the checkpoint at the frozen head
  ``export_doc``        migration step 2: the doc's full log span
  ``import_doc``        migration step 3 (target side): append the span
                        into OWN log, restore the orderer from the
                        frozen checkpoint (quorum + dedup floors continue)
  ``thaw_doc``          migration abort: drop the fenced orderer; the
                        next touch lazily recovers a live one from log
  ``retire_doc``        migration step 5 (source side): never serve the
                        doc again — answer ``wrongShard`` (redirect)
  ``adopt_doc``         failover: import the doc's span from a DEAD
                        peer's log file and recover the orderer
  ====================  =====================================================

Lifecycle: SIGTERM triggers **drain-and-seal** — in-flight work finishes
(a group commit in progress is never torn; the signal callback runs on
the same event loop the inline ingress does), new work is refused with a
typed retryable ``shuttingDown`` nack, and the per-shard log is flushed,
fsynced, and closed.  A restart over the same directory replays the
sealed log and resumes the sequence contiguously (the regression tests
pin no-duplicate-lines + contiguous seqs across restart).

Run standalone (what the front door spawns):

    python -m fluidframework_tpu.service.shardhost \
        --shard-id shard00 --dir /path/to/deployment --port 0
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from ..protocol import errors as wire_errors
from ..protocol.messages import DocRelocatedError, ShardFencedError
from ..protocol.wire import (decode_column_batch, decode_raw_operation,
                             decode_sequenced_message,
                             encode_sequenced_message)
from .oplog import OpLog, shard_log_path
from .orderer import DocumentOrderer, LocalOrderingService, SubmitOutcome
from .server import OrderingServer

#: doc-scoped methods a RETIRED document still answers on this host
#: (nothing — even reads must route to the live owner, whose log holds
#: the spans stamped after the migration).
_RETIRE_EXEMPT = frozenset({"ping", "stats", "shard_info", "adopt_doc",
                            "import_doc"})


def _outcome_wire(outcome: SubmitOutcome) -> dict:
    """One per-doc submit outcome as a wire dict (errors by code + text;
    exception objects do not cross processes).  The classification IS
    the outcome channel of the protocol/errors.py registry — every code
    emitted here must be a registered row (FL-ERR-CODE pins the literals
    statically; the assert pins the runtime)."""
    error = outcome.error
    if error is None:
        code = None
    elif isinstance(error, ShardFencedError):
        code = "fenced"
    elif isinstance(error, KeyError):
        code = "unknownDoc"
    else:
        code = "fault"
    assert code is None or wire_errors.is_registered(code)
    return {
        "stamped": outcome.n_stamped(),
        "consumed": outcome.consumed,
        "error": str(error) if error is not None else None,
        "code": code,
    }


class ShardHost:
    """One shard's service state plus the control-plane verbs.

    All handlers run INLINE on the server's event loop (single-threaded
    by construction — the per-shard log has exactly one writer thread,
    and a SIGTERM can never land mid-handler), except the catch-up/
    summary-upload routes the base server already offloads.
    """

    def __init__(self, shard_id: str, base_dir: str, faults=None) -> None:
        from ..drivers.file_driver import FileSummaryStorage

        self.shard_id = shard_id
        self.base_dir = base_dir
        log_path = shard_log_path(base_dir, shard_id)
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        self.oplog = OpLog(path=log_path, autoflush=True, faults=faults)
        self.storage = FileSummaryStorage(
            os.path.join(base_dir, "summaries"), faults=faults)
        self.service = LocalOrderingService(oplog=self.oplog,
                                            storage=self.storage)
        #: documents migrated AWAY: this host must never serve them again
        #: (its log keeps their pre-migration span, but the live span is
        #: elsewhere — serving would fork the sequence).
        self._retired: set = set()
        #: read-only views of dead peers' log files, one per peer (the
        #: file is static once its owner is dead, so the parse is paid
        #: once per peer, not once per adopted document).
        self._peer_logs: Dict[str, OpLog] = {}
        self._sealed = False

    # -- identity / introspection ----------------------------------------------

    def is_retired(self, doc_id: str) -> bool:
        return doc_id in self._retired

    def shard_info(self) -> dict:
        return {
            "shard": self.shard_id,
            "pid": os.getpid(),
            "epoch": self.storage.epoch,
            "dir": self.base_dir,
        }

    def stats(self) -> dict:
        docs = self.oplog.doc_ids()
        heads = {d: self.oplog.head(d) for d in docs}
        return {
            "shard": self.shard_id,
            "pid": os.getpid(),
            "docs": len(docs),
            "ops": sum(heads.values()),
            "heads": heads,
            "retired": sorted(self._retired),
            "epoch": self.storage.epoch,
        }

    def heads(self, doc_ids: List[str]) -> Dict[str, int]:
        return {d: self.oplog.head(d) for d in doc_ids}

    def log_contiguous(self, doc_id: str) -> bool:
        return self.oplog.is_contiguous(doc_id)

    def contiguous(self, doc_ids: List[str]) -> Dict[str, bool]:
        """Bulk contiguity check — one RPC for a whole doc set (the
        swarm's end-of-run verification is O(docs))."""
        return {d: self.oplog.is_contiguous(d) for d in doc_ids}

    # -- batched ingress over the wire -----------------------------------------

    def submit_mixed_wire(self, params: dict) -> Dict[str, dict]:
        """The swarm's batched-ingress RPC: boxed op lists and/or a
        columnar batch slice, stamped through the real
        ``service.submit_mixed`` under ONE group commit of this shard's
        own log."""
        batches = {
            doc: [decode_raw_operation(d) for d in ops]
            for doc, ops in (params.get("batches") or {}).items()
        } or None
        batch = None
        doc_rows = None
        if params.get("columns") is not None:
            batch = decode_column_batch(params["columns"])
            doc_rows = {
                doc: np.arange(int(s), int(e), dtype=np.int64)
                for doc, (s, e) in (params.get("doc_rows") or {}).items()
            }
        outcomes = self.service.submit_mixed(batches, batch, doc_rows)
        return {doc: _outcome_wire(o) for doc, o in outcomes.items()}

    def connect_many_wire(self, params: dict) -> bool:
        endpoint = self.service.endpoint(params["doc"])
        clients = list(params["clients"])
        with self.oplog.batch():  # one fsync per JOIN cohort
            if params.get("columnar", True):
                endpoint.connect_columns(clients, params.get("session"))
            else:
                endpoint.connect_many(clients, params.get("session"))
        return True

    # -- fence epoch -----------------------------------------------------------

    def bump_epoch(self, token: str) -> str:
        """Adopt the deterministic fence epoch the front door derived for
        a failover: every surviving shard lands on the same generation,
        and the shared epoch file persists it for late spawns."""
        return self.storage.bump_epoch(token)

    # -- migration (freeze → export → import → retire / thaw) ------------------

    def _orderer_of(self, doc_id: str) -> DocumentOrderer:
        self.service.endpoint(doc_id)  # ensure recovered (single-flight)
        with self.service.state_lock:
            return self.service._orderers[doc_id]

    def freeze_doc(self, doc_id: str) -> dict:
        """Migration step 1 (source): fence the document's orderer —
        every in-flight stamp lands or aborts before the fence returns
        (the durable-append gate shares the fence lock) — then seal its
        bytes and checkpoint at the frozen head.  The checkpoint is the
        quorum/dedup continuation the target restores, so the migrated
        orderer stamps the exact bytes the source would have."""
        orderer = self._orderer_of(doc_id)
        orderer.fence()
        self.oplog.flush()
        return {
            "head": self.oplog.head(doc_id),
            "checkpoint": orderer.checkpoint(),
        }

    def export_doc(self, doc_id: str) -> dict:
        """Migration step 2 (source): the document's full durable span,
        codec-encoded — plus the latest summary commit handle (the
        summary OBJECTS never move: the store is shared and
        content-addressed).  A TRUNCATED document exports its live
        suffix (the sealed prefix is gone by construction) plus the
        floor and the marker's recovery checkpoint, so the importer
        reconstructs the same guarded view."""
        floor = self.oplog.floor(doc_id)
        return {
            "records": [encode_sequenced_message(m)
                        for m in self.oplog.get(doc_id, from_seq=floor)],
            "head": self.oplog.head(doc_id),
            "summary": self.storage.head(doc_id),
            "floor": floor,
            "trunc_checkpoint": self.oplog.truncation_checkpoint(doc_id),
        }

    def import_doc(self, doc_id: str, records: List[dict],
                   checkpoint: Optional[dict] = None,
                   floor: int = 0,
                   trunc_checkpoint: Optional[dict] = None) -> dict:
        """Migration step 3 (target): append the span into THIS shard's
        log (idempotent — seq-deduped, so a retried import after a crash
        mid-transfer lands exactly once), fsync it, then install the
        orderer restored from the frozen checkpoint.  Without a
        checkpoint (failover adoption), the orderer recovers by full log
        replay instead.  ``floor``/``trunc_checkpoint`` carry a
        truncated source's sealed boundary: the marker is adopted into
        this log so reads below the floor keep failing loudly and a
        later recovery still has its checkpoint."""
        self._retired.discard(doc_id)
        # The previous owner appended this doc's summary-commit chain to
        # the shared store from ITS process; merge those records into
        # this instance's chain view before anything reads or extends it
        # (a stale head would fork the chain on the next upload).
        self.storage.refresh_doc(doc_id)
        with self.oplog.batch():
            for rec in records:
                self.oplog.append(doc_id, decode_sequenced_message(rec))
        self.oplog.flush()  # commit-point: imported span fsync
        if floor > 0:
            self.oplog.adopt_floor(doc_id, int(floor), trunc_checkpoint)
        if checkpoint is not None:
            self.service.adopt_orderer(
                doc_id,
                DocumentOrderer.restore(doc_id, self.oplog, self.storage,
                                        checkpoint))
        elif self.oplog.head(doc_id) > 0:
            self.service.endpoint(doc_id)  # eager recovery from own log
        elif self.storage.head(doc_id) is not None:
            try:
                self.service.create_document(doc_id)  # summary-only doc
            except ValueError:
                pass  # lost a benign create race with a concurrent touch
        else:
            raise KeyError(f"document {doc_id!r} has no records, no "
                           "checkpoint and no summary to adopt")
        return {"head": self.oplog.head(doc_id)}

    def thaw_doc(self, doc_id: str) -> bool:
        """Migration ABORT (target died before the flip): drop the
        frozen orderer so the next touch lazily recovers a LIVE one from
        this shard's own log — the document never left."""
        self.service.drop_orderer(doc_id)
        return True

    def retire_doc(self, doc_id: str) -> bool:
        """Migration step 5 (source, post-flip): never serve this
        document again.  Requests answer ``wrongShard`` (the redirect
        code) — the stale pre-migration span in this log must not be
        replayable into a second live sequence."""
        self._retired.add(doc_id)
        self.service.drop_orderer(doc_id)
        return True

    def adopt_doc(self, doc_id: str, from_shard: str) -> dict:
        """Failover: import the document's span from a DEAD peer's log
        file (read-only view; the front door SIGKILLs the peer before
        re-owning, so the file has exactly zero writers) and recover the
        orderer by replay.  Idempotent — re-adoption dedups.

        A document with NOTHING durable anywhere (created but never
        joined/summarized before its shard died) answers a structured
        ``{"nothing": true}`` verdict — the in-proc-parity "the document
        no longer exists" outcome — distinct from any real import
        failure (corrupt object, replay error), which RAISES and must
        keep the caller's orphan mark so the history is never silently
        abandoned."""
        peer = self._peer_logs.get(from_shard)
        if peer is None:
            path = shard_log_path(self.base_dir, from_shard)
            if os.path.exists(path):
                peer = OpLog(path=path, read_only=True)
            else:
                peer = OpLog()  # peer never wrote: empty view
            self._peer_logs[from_shard] = peer
        peer_floor = peer.floor(doc_id)
        records = [encode_sequenced_message(m)
                   for m in peer.get(doc_id, from_seq=peer_floor)]
        if not records and peer_floor == 0:
            self.storage.refresh_doc(doc_id)
            if self.storage.head(doc_id) is None \
                    and self.oplog.head(doc_id) == 0:
                return {"head": 0, "nothing": True}
        return self.import_doc(
            doc_id, records, checkpoint=None, floor=peer_floor,
            trunc_checkpoint=peer.truncation_checkpoint(doc_id))

    # -- lifecycle -------------------------------------------------------------

    def seal(self) -> None:
        """Flush, fsync, and close the per-shard log (the drain
        sequence's final step).  Idempotent."""
        if self._sealed:
            return
        self._sealed = True
        self.oplog.close()


class ShardHostServer(OrderingServer):
    """The shard host's TCP surface: the full OrderingServer protocol
    plus the control-plane verbs, with retired documents answering the
    ``wrongShard`` redirect on every doc-scoped route."""

    def __init__(self, host: ShardHost, tcp_host: str = "127.0.0.1",
                 port: int = 0, faults=None) -> None:
        super().__init__(host.service, host=tcp_host, port=port,
                         faults=faults)
        self.shard = host
        self.extra_methods.update({
            "shard_info": lambda s, p: host.shard_info(),
            "stats": lambda s, p: self._shard_stats(),
            "heads": lambda s, p: host.heads(list(p.get("docs") or ())),
            "log_contiguous": lambda s, p: (
                host.contiguous(list(p["docs"])) if "docs" in p
                else host.log_contiguous(p["doc"])),
            "submit_mixed": lambda s, p: self._submit_mixed(p),
            "connect_many": lambda s, p: host.connect_many_wire(p),
            "bump_epoch": lambda s, p: host.bump_epoch(p["token"]),
            "freeze_doc": lambda s, p: host.freeze_doc(p["doc"]),
            "export_doc": lambda s, p: host.export_doc(p["doc"]),
            "import_doc": lambda s, p: host.import_doc(
                p["doc"], p.get("records") or [], p.get("checkpoint"),
                floor=int(p.get("floor") or 0),
                trunc_checkpoint=p.get("trunc_checkpoint")),
            "thaw_doc": lambda s, p: host.thaw_doc(p["doc"]),
            "retire_doc": lambda s, p: host.retire_doc(p["doc"]),
            "adopt_doc": lambda s, p: host.adopt_doc(
                p["doc"], p["from_shard"]),
        })
        self.drain_exempt = {"ping", "stats", "shard_info"}

    def _submit_mixed(self, params: dict) -> Dict[str, dict]:
        """Batched ingress + streaming cadence: the group commit lands
        first (batch closed, bytes durable), THEN a due streaming round
        folds — never inside the commit's ``oplog.batch()``, the
        truncation marker needs a real flush for its commit point."""
        out = self.shard.submit_mixed_wire(params)
        if self.stream_enabled:
            streamfold = self._ensure_streamfold()
            if streamfold is not None:
                streamfold.poll()
        return out

    def _shard_stats(self) -> dict:
        out = self.shard.stats()
        out["admission"] = self.admission.snapshot()
        out["stream"] = (self.streamfold.stats()
                         if self.streamfold is not None else None)
        out["truncations"] = self.shard.oplog.truncations
        out["truncated_msgs"] = self.shard.oplog.truncated_msgs
        out["log_bytes_reclaimed"] = self.shard.oplog.bytes_reclaimed
        return out

    def _dispatch(self, session, method: str, params: dict):
        doc = params.get("doc")
        if doc is not None and method not in _RETIRE_EXEMPT \
                and self.shard.is_retired(doc):
            raise DocRelocatedError(doc)
        return super()._dispatch(session, method, params)


def apply_shard_flags(server, argv) -> None:
    """Apply the tuning subset of the shardhost CLI to a LIVE server.

    Shared by ``main()`` (real processes) and ``ThreadShard`` (in-thread
    shards): both spawn modes take the same ``--shard-arg`` vocabulary,
    and a failover RESPAWN re-applies it automatically — a restarted
    storm shard comes back with the same wire-clock admission shape as
    the one that died.  Deployment knobs, not config gates: post-ctor
    attributes exactly like the in-proc harnesses set them (the gate
    registry stays the single source of DEFAULTS; these override
    per-process)."""
    argv = list(argv)
    i = 0
    while i < len(argv):
        flag = argv[i]
        if flag == "--virtual-admission":
            server.admission_control.virtual = True
            i += 1
            continue
        if i + 1 >= len(argv):
            raise ValueError(f"shard flag {flag!r} needs a value")
        value = argv[i + 1]
        if flag == "--catchup-hold":
            server.catchup_hold_seconds = float(value)
        elif flag == "--catchup-max-inflight":
            server.admission_control.max_inflight = max(1, int(value))
        elif flag == "--catchup-degrade-after":
            server.admission_control.degrade_after = max(0, int(value))
        else:
            raise ValueError(f"unknown shard flag {flag!r}")
        i += 2


def main(argv=None) -> None:
    import argparse
    import asyncio
    import signal

    parser = argparse.ArgumentParser(
        description="fluidproc shard host (one orderer shard, own "
                    "durable log, shared summary store)")
    parser.add_argument("--shard-id", required=True)
    parser.add_argument("--dir", required=True,
                        help="shared deployment directory (per-shard logs "
                             "under shards/<id>/, summaries under "
                             "summaries/)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--fault-plan", default=None,
                        help="optional faultline plan JSON arming this "
                             "host's oplog/storage seams (chaos runs)")
    parser.add_argument("--stream", action="store_true",
                        help="attach the streaming fold (ISSUE 16): fold "
                             "committed micro-batches continuously and "
                             "truncate the per-shard log below durable "
                             "summaries")
    parser.add_argument("--stream-cadence", type=int, default=None,
                        help="fold once a doc has this many unfolded ops")
    parser.add_argument("--stream-retention", type=int, default=None,
                        help="never truncate the newest N ops")
    parser.add_argument("--virtual-admission", action="store_true",
                        help="wire-clock catchup admission (ISSUE 18): "
                             "the controller's clock advances only on "
                             "vnow values carried by catchup requests — "
                             "deterministic out-of-proc storm verdicts")
    parser.add_argument("--catchup-hold", type=float, default=None,
                        help="modeled fold duration: extra clock seconds "
                             "an admission lease occupies its slot after "
                             "release (storm harness load model)")
    parser.add_argument("--catchup-max-inflight", type=int, default=None,
                        help="override the catchup fold lane's admission "
                             "slot count")
    parser.add_argument("--catchup-degrade-after", type=int, default=None,
                        help="consecutive sheds before the verdict "
                             "degrades to stored-summary serving")
    args = parser.parse_args(argv)

    faults = None
    if args.fault_plan:
        import json

        from ..testing.faults import FaultInjector, FaultPlan, FaultPoint

        with open(args.fault_plan, "r", encoding="utf-8") as f:
            doc = json.load(f)
        faults = FaultInjector(FaultPlan(
            seed=doc.get("seed", 0),
            points=tuple(
                FaultPoint(site=p["site"], kind=p["kind"],
                           at=int(p.get("at", 1)),
                           count=int(p.get("count", 1)),
                           doc=p.get("doc"), shard=p.get("shard"),
                           arg=float(p.get("arg", 0.0)))
                for p in doc.get("points", ())
            )))

    host = ShardHost(args.shard_id, args.dir, faults=faults)
    # The injector arms the server-side seams too (catchup.fail /
    # catchup.slow / session.write), not just the durable tier's.
    server = ShardHostServer(host, tcp_host=args.host, port=args.port,
                             faults=faults)
    if args.stream:
        server.enable_streaming(cadence_ops=args.stream_cadence,
                                retention_floor=args.stream_retention)
    # One application point for both spawn modes: re-encode the parsed
    # tuning flags and run them through the same helper ThreadShard uses.
    flags: list = []
    if args.virtual_admission:
        flags.append("--virtual-admission")
    if args.catchup_hold is not None:
        flags += ["--catchup-hold", str(args.catchup_hold)]
    if args.catchup_max_inflight is not None:
        flags += ["--catchup-max-inflight", str(args.catchup_max_inflight)]
    if args.catchup_degrade_after is not None:
        flags += ["--catchup-degrade-after", str(args.catchup_degrade_after)]
    apply_shard_flags(server, flags)

    async def _run():
        await server.start()
        print(f"shardhost {host.shard_id} listening on "
              f"{server.host}:{server.port} pid={os.getpid()}", flush=True)
        loop = asyncio.get_running_loop()
        stop = loop.create_future()

        def _on_term():
            if not stop.done():
                stop.set_result(None)

        loop.add_signal_handler(signal.SIGTERM, _on_term)
        await stop
        # Drain-and-seal: the signal callback above ran BETWEEN loop
        # callbacks, so no inline dispatch (no group commit) is mid
        # flight; refuse new work, wait out offloaded folds, seal the
        # per-shard log so a restart resumes contiguous.
        await server.drain_and_seal(seal=host.seal)
        print(f"shardhost {host.shard_id} sealed", flush=True)

    asyncio.run(_run())


if __name__ == "__main__":
    main()
