"""The network front door: a TCP ordering server over LocalOrderingService.

Capability-equivalent of the reference's Alfred/Nexus socket ingress plus
Tinylicious's standalone single-process server (SURVEY.md §2.3; upstream
paths UNVERIFIED — empty reference mount): clients in OTHER processes speak
a length-prefixed JSON frame protocol over localhost/LAN TCP to create
documents, connect, submit ops, receive the sequenced broadcast, exchange
signals, read delta ranges, and read/write summaries.

Frame protocol (version-stamped; little deliberately, since the payloads
are the same dicts the in-proc path uses):

    [4-byte big-endian length][json bytes]

    request:   {"v": 1, "id": N, "method": str, "params": {...}}
    response:  {"v": 1, "re": N, "ok": true, "result": ...}
               {"v": 1, "re": N, "ok": false, "error": str}
    event:     {"v": 1, "event": "op"|"signal", "doc": str, ...}

Broadcast ordering guarantee: `subscribe_doc`'s response is written to the
socket before any subsequent op event for that document (asyncio per-
connection FIFO), and the deltas snapshot a client then requests rides the
same socket — so the client sees (response, snapshot, live tail) with any
overlap deduplicated client-side by the DeltaManager's delivery watermark.

Run standalone (the Tinylicious shape):

    python -m fluidframework_tpu.service.server --port 7070 [--dir path]
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Dict, Optional, Set, Tuple

from ..protocol.messages import (DocRelocatedError, NackError,
                                 ShardFencedError)
from ..protocol.summary import tree_from_obj, tree_to_obj
from ..protocol.wire import (LEN as _LEN, MAX_FRAME, WIRE_VERSION,
                             decode_raw_operation,
                             encode_sequenced_message, frame_bytes)
from . import gates
from .broadcaster import Broadcaster
from .orderer import LocalOrderingService


class EpochMismatch(Exception):
    """A storage request pinned to a DIFFERENT storage generation (odsp
    EpochTracker capability): the client's cached snapshots/deltas came
    from a store that no longer exists — fail loudly, never mix."""

    def __init__(self, client_epoch: str, server_epoch: str) -> None:
        super().__init__(
            f"storage epoch mismatch: client pinned {client_epoch!r}, "
            f"server is {server_epoch!r} (the store was recreated; cached "
            f"state is from a dead generation)"
        )
        self.server_epoch = server_epoch


#: fold-cost EMA seed (seconds): the controller's pacing estimate before
#: any lease has released.  Module-level so a harness that mirrors the
#: EMA from its own observations (the storm verdict's cost_ema
#: cross-check, testing/scenarios.py) shares the exact seed.
ADMISSION_COST_INIT = 0.25


class AdmissionController:
    """Adaptive admission for the catch-up fold lane (ISSUE 15).

    The round-9 controller was a fixed-size semaphore whose shed nack
    carried a hardcoded ``retry_after=0.5`` — pacing that had never been
    hit by the storm it exists for.  This controller derives both the
    shed decision and the pacing from MEASURED load, entirely off an
    injectable clock, so a deterministic harness (VirtualClock) replays
    every admission decision bit-identically:

    - a fold holds a **lease** from admit until release; ``release`` may
      carry a ``hold`` — extra clock time the slot stays occupied after
      the synchronous call returns.  Production releases with hold 0
      (the slot frees when the fold thread finishes); the swarm storm
      harness models fold DURATION in virtual time this way, which is
      what lets a single-threaded deterministic driver produce real
      overlapping-fold admission pressure.
    - ``retry_after`` = measured fold cost (EMA over released leases) ×
      backlog-per-slot, clamped to ``[retry_floor, retry_cap]``: a
      deeper queue paces retries further out, a fast fold tier calls
      the herd back sooner.
    - sustained overload — ``degrade_after`` consecutive overflow
      verdicts with no slot freed between them — flips the verdict from
      ``shed`` to ``degrade``: the server answers with the stored
      summary at an older ref_seq (see ``_degraded_serve``) instead of
      pure refusal.

    **Wire-clock mode** (ISSUE 18, the storm-verdict replay debt): an
    out-of-proc shard cannot share the harness's VirtualClock object,
    so remote admission used to ride wall time — every verdict landed
    OUTSIDE replay identity.  With ``virtual = True`` the controller
    instead advances on clock values the CALLERS carry on the wire
    (:meth:`observe`, monotone max): a deterministic driver that stamps
    its virtual tick onto each catchup request makes every lease
    expiry, backlog depth, and load-derived ``retry_after`` a pure
    function of the request sequence — bit-identical on replay, process
    boundary or not.
    """

    def __init__(self, max_inflight: int, clock=None,
                 retry_floor: float = 0.05, retry_cap: float = 5.0,
                 degrade_after: int = 2,
                 cost_init: float = ADMISSION_COST_INIT) -> None:
        #: injected clock (seconds); time.monotonic in production,
        #: a VirtualClock in deterministic harnesses.
        self._clock = clock if clock is not None else time.monotonic
        self.max_inflight = max(1, int(max_inflight))
        self.retry_floor = float(retry_floor)
        self.retry_cap = float(retry_cap)
        self.degrade_after = max(0, int(degrade_after))
        self._lock = threading.Lock()
        #: token -> [admitted_at, expires]; expires None = still in
        #: flight (never expires), a float = released-with-hold lease
        #: that keeps occupying its slot until that clock time.
        self._leases: Dict[int, list] = {}  # guarded-by: _lock
        self._next_token = 0  # guarded-by: _lock
        self._cost_ema = float(cost_init)  # guarded-by: _lock
        #: consecutive overflow verdicts since the last admit — the
        #: sustained-overload signal and the queue-depth estimate (each
        #: consecutive shed implies another caller waiting out there).
        self._shed_streak = 0  # guarded-by: _lock
        #: wire-clock mode: time advances only via observe() — see the
        #: class doc.  Flipped post-ctor (a deployment flag, not config).
        self.virtual = False
        self._vnow = 0.0  # guarded-by: _lock

    def observe(self, vnow: float) -> None:
        """Wire-clock input: a caller reported ITS clock.  Monotone max
        — requests may arrive reordered across connections, and time
        never runs backwards."""
        vnow = float(vnow)
        with self._lock:
            if vnow > self._vnow:
                self._vnow = vnow

    def _now_locked(self) -> float:
        # holds-lock: _lock
        return self._vnow if self.virtual else self._clock()

    def _purge_locked(self, now: float) -> None:
        expired = [token for token, lease in self._leases.items()
                   if lease[1] is not None and lease[1] <= now]
        for token in expired:
            self._leases.pop(token)

    def admit(self) -> Tuple[str, object]:
        """One admission decision: ``("admit", token)`` — the caller
        runs its fold and MUST ``release(token)`` (try/finally) — or
        ``("shed" | "degrade", retry_after)`` under overload."""
        with self._lock:
            now = self._now_locked()
            self._purge_locked(now)
            if len(self._leases) >= self.max_inflight:
                self._shed_streak += 1
                backlog = len(self._leases) + self._shed_streak
                retry_after = min(self.retry_cap, max(
                    self.retry_floor,
                    self._cost_ema * backlog / self.max_inflight))
                verdict = ("degrade"
                           if self._shed_streak > self.degrade_after
                           else "shed")
                return verdict, retry_after
            token = self._next_token
            self._next_token += 1
            self._leases[token] = [now, None]
            self._shed_streak = 0
            return "admit", token

    def release(self, token: int, hold: float = 0.0) -> None:
        """Fold done: record its measured cost (clock delta + ``hold``)
        in the EMA the pacing derives from; with ``hold`` > 0 the lease
        keeps its slot until ``now + hold`` (purged lazily by later
        admits), else the slot frees immediately."""
        with self._lock:
            now = self._now_locked()
            lease = self._leases.get(token)
            if lease is None:
                return
            cost = max(0.0, now - lease[0]) + max(0.0, hold)
            if cost > 0.0:
                self._cost_ema = 0.5 * self._cost_ema + 0.5 * cost
            if hold > 0.0:
                lease[1] = now + hold
            else:
                self._leases.pop(token)

    def snapshot(self) -> dict:
        """Self-contained pacing record: everything a remote harness
        needs to RE-DERIVE a shed verdict's retry_after (the clamp
        bounds included), so out-of-proc storm pacing can be audited
        against the snapshot the nack carried."""
        with self._lock:
            return {
                "inflight": len(self._leases),
                "max_inflight": self.max_inflight,
                "cost_ema": round(self._cost_ema, 6),
                "shed_streak": self._shed_streak,
                "retry_floor": self.retry_floor,
                "retry_cap": self.retry_cap,
            }


#: Methods offloaded to executor threads.  Shared-state discipline: lazy
#: endpoint/orderer creation and the handle-grant map are guarded by
#: ``service.state_lock``; oplog READS during an offloaded fold rely on the
#: append-only contract (ranged reads see a prefix that never mutates —
#: a concurrent append only extends beyond the requested range).
OFFLOADED_METHODS = frozenset({"catchup", "upload_summary"})


async def read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    try:
        header = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    try:
        payload = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    return json.loads(payload)


class _ClientSession:
    """One TCP connection's server-side state — and the production
    broadcast SINK: sequenced frames arrive already encoded from the
    shared :class:`Broadcaster` (one serialization per message for every
    subscriber on the server), this class only meters and writes them."""

    def __init__(self, server: "OrderingServer",
                 writer: asyncio.StreamWriter) -> None:
        self.server = server
        self.writer = writer
        self.subscribed_docs: Set[str] = set()
        self.signal_docs: Set[str] = set()
        self.connected_clients: Dict[str, str] = {}  # client_id -> doc_id
        self._tapped_by_wire: Dict[str, str] = {}  # out_doc -> internal doc
        self.tenant: Optional[str] = None  # set by a successful "auth"
        self._closed = False
        # Broadcast-frame accounting: bytes accepted by write_frame but
        # not yet handed to the transport (the cross-thread hop).  The
        # transport's own buffer is added at admission time, so the
        # budget covers the whole path to the socket.
        self._pending_lock = threading.Lock()
        self._pending_bytes = 0  # guarded-by: _pending_lock

    #: Disconnect a session whose unread RESPONSE backlog exceeds this
    #: (broadcast frames never ride this path anymore — they are metered
    #: by ``write_frame`` and demoted at ``server.broadcast_high_water``;
    #: this hard cap only guards the request/response and notification
    #: writes, which are client-paced).
    WRITE_HIGH_WATER = 32 << 20

    def send(self, obj: dict) -> None:
        """Thread-safe-ish frame write: always scheduled on the loop."""
        self.server.loop.call_soon_threadsafe(self._write, obj)

    def _write(self, obj: dict) -> None:
        if self.writer.is_closing():
            return
        transport = self.writer.transport
        if transport is not None and \
                transport.get_write_buffer_size() > self.WRITE_HIGH_WATER:
            # Laggard: drop the connection rather than buffer unboundedly.
            self.close()
            self.writer.close()
            return
        self.writer.write(frame_bytes(obj))

    # -- broadcast sink (Broadcaster protocol) ---------------------------------

    def write_frame(self, data: bytes) -> bool:
        """Accept one pre-encoded broadcast frame, or report saturation.
        Admission is metered against transport backlog + in-flight bytes:
        a stalled reader saturates here and gets DEMOTED by the
        broadcaster instead of growing the server's buffers or stalling
        the other subscribers of its documents."""
        if self._closed:
            return True  # connection is tearing down; drop silently
        fault = (self.server.faults.fire("session.write")
                 if self.server.faults is not None else None)
        if fault is not None and fault.kind == "stall":
            # Injected stalled client: report saturation exactly as a
            # full transport buffer would — the broadcaster demotes this
            # session and the client backfills from the durable log.
            return False
        transport = self.writer.transport
        buffered = (transport.get_write_buffer_size()
                    if transport is not None else 0)
        with self._pending_lock:
            if (buffered + self._pending_bytes + len(data)
                    > self.server.broadcast_high_water):
                return False
            self._pending_bytes += len(data)
        self.server.loop.call_soon_threadsafe(self._write_bytes, data)
        return True

    def _write_bytes(self, data: bytes) -> None:
        with self._pending_lock:
            self._pending_bytes -= len(data)
        if self.writer.is_closing():
            return
        self.writer.write(data)

    def write_signal(self, data: bytes, signal: dict) -> bool:
        """Signal frames share the encoded bytes across sessions; the
        per-client TARGET filter is the only per-session work left."""
        target = signal.get("targetClientId")
        if target is not None and target not in self.connected_clients:
            return True  # not addressed to this session — filtered, not lagging
        return self.write_frame(data)

    def on_demoted(self, out_doc: str, head_seq: int) -> None:
        """Broadcaster removed this session (buffer budget exceeded):
        tell the client once — it backfills the missed range from the
        durable op log (``deltas``) and re-subscribes when it catches
        up.  The notification rides the response path (small frame)."""
        doc_id = self._tapped_by_wire.get(out_doc)
        if doc_id is not None:
            self.subscribed_docs.discard(doc_id)
        self.send({"v": WIRE_VERSION, "event": "demoted", "doc": out_doc,
                   "head": head_seq})

    def on_fence(self, out_doc: str, epoch: str, head_seq: int) -> None:
        """Shard failover: the storage generation changed and this doc's
        broadcast now rides the recovered owner.  Push the new epoch so
        pinned clients unpin/drop caches proactively instead of tripping
        over epochMismatch on their next request."""
        self.send({"v": WIRE_VERSION, "event": "fence", "doc": out_doc,
                   "epoch": epoch, "head": head_seq})

    # -- broadcast taps --------------------------------------------------------

    def tap(self, doc_id: str, wire_doc: Optional[str] = None) -> None:
        if doc_id in self.subscribed_docs:
            return
        endpoint = self.server.service.endpoint(doc_id)
        out_doc = wire_doc if wire_doc is not None else doc_id
        self.server.broadcaster.attach(doc_id, endpoint, self,
                                       out_doc=out_doc)
        self.subscribed_docs.add(doc_id)
        self._tapped_by_wire[out_doc] = doc_id

    def close(self) -> None:
        # Idempotent (fluidleak FL-LEAK-DOUBLE-CLOSE): the laggard-drop
        # path (_write) closes mid-connection and _handle's finally
        # closes again on unwind; the second call must not re-run the
        # unsubscribe/disconnect sweep against re-registered state.
        if self._closed:
            return
        self._closed = True
        self.server.broadcaster.detach_all(self)
        self.subscribed_docs.clear()
        self._tapped_by_wire.clear()
        for client_id, doc_id in list(self.connected_clients.items()):
            try:
                self.server.service.endpoint(doc_id).disconnect(client_id)
            except KeyError:
                pass
        self.connected_clients.clear()


class OrderingServer:
    """Asyncio TCP server exposing a LocalOrderingService to the network."""

    def __init__(self, service: Optional[LocalOrderingService] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 tenants: Optional[Dict[str, str]] = None,
                 broadcast_high_water: int = 8 << 20,
                 catchup_max_inflight: int = 4,
                 faults=None, clock=None, mc=None) -> None:
        #: any object with the LocalOrderingService surface — including
        #: ShardedOrderingService (the front door dispatches by its
        #: router transparently: every access goes through endpoint()).
        self.service = service if service is not None else \
            LocalOrderingService()
        self.host = host
        self.port = port
        #: tenant id -> shared secret (the Riddler capability).  When set,
        #: every connection must "auth" first; document ids are namespaced
        #: per tenant so tenants cannot see each other's documents.
        self.tenants = tenants
        #: serialize-once broadcast fan-out: sessions are sinks, one
        #: encode per sequenced message regardless of subscriber count.
        self.broadcaster = Broadcaster()
        #: per-session broadcast buffer budget; a session exceeding it is
        #: demoted to catch-up-from-oplog instead of stalling the shard.
        self.broadcast_high_water = int(broadcast_high_water)
        if hasattr(self.service, "add_fence_listener"):
            # Sharded tier: on failover, move live broadcast channels to
            # the recovered owners and push fence events to subscribers.
            self.service.add_fence_listener(self._on_shard_fence)

        #: faultline hook for the ``session.write`` stall site
        #: (testing/faults.py); None in production.
        self.faults = faults
        #: extension point (fluidproc): method name -> fn(session, params),
        #: consulted BEFORE the built-in table so a shard host can add its
        #: control-plane RPC (freeze/export/import/adopt/stats) — or
        #: override a built-in — without forking the dispatch loop.
        self.extra_methods: Dict[str, callable] = {}
        #: instance copy of OFFLOADED_METHODS so subclasses can offload
        #: their own slow routes.
        self.offloaded_methods = set(OFFLOADED_METHODS)
        #: drain mode (SIGTERM): mutating/new work is refused with a
        #: typed retryable ``shuttingDown`` nack while in-flight work
        #: finishes and the durable log is sealed.  Methods listed in
        #: ``drain_exempt`` still answer (supervision probes).
        self.draining = False
        self.drain_exempt = {"ping", "stats", "shard_info"}
        #: in-flight EXECUTOR dispatches (offloaded methods only; inline
        #: dispatches run on the event loop, which the drain sequence
        #: shares, so they can never be mid-flight when it runs).
        self._inflight_lock = threading.Lock()
        self._inflight = 0  # guarded-by: _inflight_lock
        from ..utils.telemetry import LockedCounterSet, MonitoringContext

        #: logger + feature gates (Catchup.* / Server.* keys below); the
        #: lazy CatchupService inherits it so its own cache gates read
        #: the same config.
        self.mc = mc if mc is not None else MonitoringContext()
        cfg = self.mc.config

        #: injected clock for every admission/pacing decision —
        #: time.monotonic in production, a VirtualClock (whose reads and
        #: ``sleep`` advance virtual time) in deterministic harnesses.
        self.clock = clock if clock is not None else time.monotonic
        #: admission control for the catchup RPC: device folds are the
        #: most expensive op the server runs — beyond this many in
        #: flight, new requests are SHED with an "overloaded" nack
        #: whose retry_after is derived from measured fold cost and
        #: queue depth (clients catch up from the durable op log
        #: instead), or — under SUSTAINED overload — served DEGRADED
        #: from the stored summary at an older ref_seq.
        self.catchup_max_inflight = gates.get_int(
            cfg, "Catchup.MaxInflight",
            fallback=int(catchup_max_inflight))
        self.admission_control = AdmissionController(
            self.catchup_max_inflight, clock=self.clock,
            retry_floor=gates.get_float(cfg, "Catchup.ShedRetryFloor"),
            retry_cap=gates.get_float(cfg, "Catchup.ShedRetryCap"),
            degrade_after=gates.get_int(cfg, "Catchup.DegradeAfter"))
        #: Catchup.DegradedServe gate (default ON): under sustained
        #: overload serve the tier-1 stored summary at an older ref_seq
        #: — the client replays the durable tail via normal gap repair —
        #: instead of pure shedding.
        self.degraded_serve = gates.is_on(cfg, "Catchup.DegradedServe")
        #: retry_after on the ``shuttingDown`` drain nack
        #: (Server.DrainRetryAfter gate; was a hardcoded 0.5).
        self.drain_retry_after = gates.get_float(cfg, "Server.DrainRetryAfter")
        #: bound on the warm lane's single-flight join
        #: (Catchup.WarmJoinTimeout): a wedged leader must turn joiners
        #: into FOLD-LANE requests — where admission sheds with pacing —
        #: after seconds, not park them on executor threads for the full
        #: crashed-leader JoinTimeout (60 s).
        self.warm_join_timeout = gates.get_float(cfg,
                                                 "Catchup.WarmJoinTimeout")
        #: modeled fold duration: extra clock seconds an admission lease
        #: stays occupied AFTER the synchronous fold returns.  0 in
        #: production; the deterministic storm harness sets it so
        #: sequentially-driven folds overlap in virtual time.
        self.catchup_hold_seconds = 0.0
        #: the overload surface: ``catchup.requests`` counts fold-lane
        #: entries and balances exactly — requests = admitted + shed +
        #: degraded; ``catchup.warm`` counts priority-lane serves that
        #: never entered the fold lane at all.
        self.admission = LockedCounterSet(
            "catchup.requests", "catchup.admitted", "catchup.shed",
            "catchup.degraded", "catchup.degraded_docs", "catchup.warm",
            "catchup.stream")
        #: streaming fold (ISSUE 16): when the ``Catchup.Stream`` gate is
        #: on, a sequencer-attached :class:`~.streamfold.StreamFoldService`
        #: folds committed micro-batches continuously (pinned device
        #: state, summary-anchored oplog truncation) and catch-up serves
        #: the STREAMING HEAD lane — summaries at most one cadence behind
        #: the durable head, no fold, no admission.
        self.stream_enabled = gates.is_on(cfg, "Catchup.Stream")
        self.stream_cadence = gates.get_int(cfg, "Catchup.StreamCadence")
        self.stream_retention = gates.get_int(cfg, "Catchup.StreamRetention")
        self.streamfold = None  # guarded-by: _catchup_init (lazy)
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.base_events.Server] = None
        # lazy CatchupService (the "catchup" method); executor threads
        # race the init.
        self._catchup = None  # guarded-by: _catchup_init
        self._catchup_init = threading.Lock()

    def _on_shard_fence(self, shard_id: str, doc_ids, epoch: str) -> None:
        """A shard died: every affected document with live subscribers is
        recovered NOW (endpoint() on the new owner replays the durable
        log) and its broadcast channel re-attached; sessions get a fence
        event carrying the new storage epoch.  Documents WITHOUT live
        channels are skipped — they recover lazily on next touch, so a
        shard full of idle documents fails over in O(live subscriptions),
        not O(documents × log replay)."""
        live = set(self.broadcaster.docs_with_channels())
        for doc_id in doc_ids:
            if doc_id not in live:
                continue
            try:
                endpoint = self.service.endpoint(doc_id)
            except KeyError:
                continue  # summary-only doc; recovered lazily on next use
            self.broadcaster.refence(doc_id, endpoint, epoch)

    # -- tenancy scoping -------------------------------------------------------

    def _grant_tree(self, tree, tenant: Optional[str]) -> None:
        """Grant the tenant read access to EVERY node digest of a summary
        (incremental uploads reference arbitrary subtree handles)."""
        if tenant is None:
            return
        memo: dict = {}
        digests: list = []

        def walk(node):
            from ..protocol.summary import SummaryTree

            digests.append(node.digest(memo) if isinstance(node, SummaryTree)
                           else node.digest())
            if isinstance(node, SummaryTree):
                for child in node.children.values():
                    walk(child)

        # Hash OUTSIDE the lock (digest() is pure over immutable nodes);
        # the lock covers only the dict updates — executor threads
        # (OFFLOADED_METHODS) mutate the grant map concurrently with
        # event-loop dispatches (ADVICE r3).
        walk(tree)
        grants = self.service.handle_tenants
        with self.service.state_lock:
            for digest in digests:
                grants.setdefault(digest, set()).add(tenant)

    def _check_epoch(self, params: dict) -> None:
        client_epoch = params.get("epoch")
        server_epoch = self.service.storage.epoch
        if client_epoch is not None and client_epoch != server_epoch:
            raise EpochMismatch(client_epoch, server_epoch)

    def _check_readable(self, handle: str, tenant: Optional[str]) -> None:
        if self.tenants is None:
            return
        with self.service.state_lock:
            granted = tenant in self.service.handle_tenants.get(handle, ())
        if not granted:
            raise PermissionError("unknown handle for this tenant")

    def _check_incremental_refs(self, obj, tenant: Optional[str]) -> None:
        """Every {"h": ...} node an incremental upload references must be
        readable by the uploader — resolving unowned handles would
        materialize another tenant's snapshot into this tenant's doc."""
        if self.tenants is None or not isinstance(obj, dict):
            return
        if "h" in obj:
            self._check_readable(obj["h"], tenant)
        for child in (obj.get("t") or {}).values():
            self._check_incremental_refs(child, tenant)

    # -- request dispatch ------------------------------------------------------

    def _dispatch(self, session: _ClientSession, method: str,
                  params: dict):
        service = self.service
        if method == "auth":
            if self.tenants is None:
                return True  # open server: auth is a no-op
            tenant = params.get("tenant")
            if self.tenants.get(tenant) != params.get("secret"):
                raise PermissionError("invalid tenant credentials")
            session.tenant = tenant
            return True
        if method == "ping":
            return "pong"
        if self.draining and method not in self.drain_exempt:
            # Typed retryable refusal: clients hold their encoded ops and
            # retry after the restart (NackError semantics); nothing new
            # may touch the log once the drain sequence armed the seal.
            raise NackError(
                "server is draining for shutdown; retry after restart",
                retry_after=self.drain_retry_after, code="shuttingDown")
        extra = self.extra_methods.get(method)
        if extra is not None:
            return extra(session, params)
        if method == "stats":
            return self._stats()
        # Generation check for EVERY doc/storage method in one place —
        # deltas, submits, and catchup included, not just the summary RPCs
        # (review r4: op-stream generation mixing must fail loudly too).
        self._check_epoch(params)
        client_doc = params.get("doc")
        if self.tenants is not None:
            if session.tenant is None:
                raise PermissionError("authenticate first")
            # Namespace every document id under the tenant: tenants can
            # never address each other's documents.
            if "doc" in params:
                params = dict(params, doc=f"{session.tenant}/{params['doc']}")
        if method == "create_document":
            service.create_document(params["doc"])
            if "summary" in params:
                tree = tree_from_obj(params["summary"])
                service.storage.upload(
                    params["doc"], tree, params.get("ref_seq", 0),
                )
                self._grant_tree(tree, session.tenant)
            return True
        if method == "has_document":
            return service.has_document(params["doc"])
        if method == "subscribe_doc":
            # Broadcast frames carry the CLIENT-visible doc id (tenant
            # namespacing is server-internal).
            session.tap(params["doc"], wire_doc=client_doc)
            return service.endpoint(params["doc"]).head_seq
        if method == "connect":
            endpoint = service.endpoint(params["doc"])
            endpoint.connect(params["client"], params.get("session"))
            session.connected_clients[params["client"]] = params["doc"]
            return True
        if method == "disconnect":
            service.endpoint(params["doc"]).disconnect(params["client"])
            session.connected_clients.pop(params["client"], None)
            return True
        if method == "submit":
            msg = service.endpoint(params["doc"]).submit(
                decode_raw_operation(params["op"])
            )
            if self.stream_enabled:
                # Streaming cadence: the commit watcher recorded the new
                # head; fold it once the unfolded span reaches the
                # cadence.  Synchronous and cadence-gated — almost every
                # call is a no-op dict check, and a due round folds one
                # micro-batch, not a cold tail.
                streamfold = self._ensure_streamfold()
                if streamfold is not None:
                    streamfold.poll()
            return encode_sequenced_message(msg) if msg is not None else None
        if method == "stream_poll":
            # Control-plane poke for the streaming fold (tests, the
            # swarm tick, operators): one poll round now; force=True
            # folds every pending doc regardless of cadence.
            streamfold = self._ensure_streamfold()
            if streamfold is None:
                return None
            folded = streamfold.poll(force=bool(params.get("force")))
            return {"folded": {d: [h, s] for d, (h, s) in folded.items()},
                    "stats": streamfold.stats()}
        if method == "update_ref_seq":
            service.endpoint(params["doc"]).update_ref_seq(
                params["client"], params["ref_seq"]
            )
            return True
        if method == "deltas":
            msgs = service.endpoint(params["doc"]).deltas(
                params.get("from_seq", 0), params.get("to_seq")
            )
            return [encode_sequenced_message(m) for m in msgs]
        if method == "head":
            return service.endpoint(params["doc"]).head_seq
        if method == "signal":
            service.endpoint(params["doc"]).submit_signal(
                params["client"], params.get("content"),
                params.get("target"),
            )
            return True
        if method == "catchup":
            return self._catchup_entry(session, params)
        if method == "latest_summary":
            epoch = service.storage.epoch
            tree, ref_seq = service.storage.latest(
                params["doc"], at_or_below=params.get("at_or_below")
            )
            if tree is None:
                # Still carry the epoch: a CREATING client must adopt the
                # generation before its first upload, or its caches go
                # unpinned and the EpochTracker protection is inactive
                # for the writer path (review r4).
                return {"handle": None, "ref_seq": 0, "epoch": epoch}
            handle = tree.digest()
            self._grant_tree(tree, session.tenant)
            if handle in (params.get("have") or []):
                # Client-side snapshot cache hit: the body never crosses
                # the wire (odsp-driver caching capability).
                return {"handle": handle, "ref_seq": ref_seq,
                        "epoch": epoch}
            return {"handle": handle, "summary": tree_to_obj(tree),
                    "ref_seq": ref_seq, "epoch": epoch}
        if method == "upload_summary":
            # Incremental upload: {"h": ...} nodes resolve against the
            # server store (unchanged subtrees never cross the wire) —
            # but only handles this tenant may read (a foreign handle
            # would materialize another tenant's snapshot).
            self._check_incremental_refs(params["summary"], session.tenant)
            handle = service.storage.upload_obj(
                params["doc"], params["summary"], params["ref_seq"],
            )
            self._grant_tree(service.storage.read(handle), session.tenant)
            return {"handle": handle, "epoch": service.storage.epoch}
        if method == "read_summary":
            # Handles are content-addressed and global; scope reads to
            # granted tenants or snapshots would leak across tenants.
            self._check_readable(params["handle"], session.tenant)
            node = service.storage.read(params["handle"])
            path = params.get("path")
            if path:
                # Partial snapshot virtualization: fetch one subtree/blob
                # instead of the whole snapshot (odsp capability).
                node = node.get(path)
            from ..protocol.summary import SummaryBlob

            if isinstance(node, SummaryBlob):
                from ..protocol.summary import _encode_blob

                return {"v": 1, **_encode_blob(node)}
            return tree_to_obj(node)
        raise ValueError(f"unknown method {method!r}")

    def _stats(self) -> dict:
        """The ``stats`` RPC: service-level counters every deployment
        shape answers (the fluidproc shard host extends this with
        per-shard identity and log heads)."""
        service = self.service
        docs = service.doc_ids()
        with self._catchup_init:
            streamfold = self.streamfold
        return {
            "docs": len(docs),
            "ops": sum(service.oplog.head(d) for d in docs),
            "epoch": service.storage.epoch,
            "admission": self.admission.snapshot(),
            # live controller state (inflight leases, measured fold-cost
            # EMA, shed streak) next to the monotonic counters
            "admissionControl": self.admission_control.snapshot(),
            # streaming fold health (None while the gate is off): poll/
            # fold/publish counters, truncation totals, summary lag
            # high-water in sequence numbers.
            "stream": (streamfold.stats()
                       if streamfold is not None else None),
        }

    def _track_dispatch(self, session: _ClientSession, method: str,
                        params: dict):
        """Executor-side dispatch wrapper: counts in-flight offloaded
        work so the drain sequence can wait it out before sealing."""
        with self._inflight_lock:
            self._inflight += 1
        try:
            return self._dispatch(session, method, params)
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    async def drain_and_seal(self, seal=None, timeout: float = 30.0) -> None:
        """SIGTERM drain: refuse new work (typed ``shuttingDown`` nacks),
        stop accepting connections, wait out in-flight offloaded
        dispatches, then run ``seal`` (the shard host flushes + closes
        its durable log).  Inline dispatches — submits and their group
        commits — run to completion on this same event loop before the
        signal callback that starts this coroutine can execute, so a
        SIGTERM landing mid-group-commit drains the in-flight batch by
        construction; the seal's flush then makes its bytes durable."""
        self.draining = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            with self._inflight_lock:
                idle = self._inflight == 0
            if idle:
                break
            await asyncio.sleep(0.02)
        if seal is not None:
            seal()

    def _ensure_catchup(self):
        from .catchup import CatchupService

        with self._catchup_init:
            if self._catchup is None:
                self._catchup = CatchupService(self.service, mc=self.mc)
            # Hand the instance out of the critical section as a
            # local: every later use reads the local, not the guarded
            # attribute (fluidrace FL-RACE-GUARD — the instance is
            # immutable-once-set, the attribute slot is not).
            return self._catchup

    def _ensure_streamfold(self):
        """Lazy streaming-fold service (gate: ``Catchup.Stream``).
        Returns None when streaming is off."""
        if not self.stream_enabled:
            return None
        catchup = self._ensure_catchup()
        with self._catchup_init:
            if self.streamfold is None:
                from .streamfold import StreamFoldService

                self.streamfold = StreamFoldService(
                    self.service, catchup,
                    cadence_ops=self.stream_cadence,
                    retention_floor=self.stream_retention,
                    faults=self.faults,
                ).attach()
            return self.streamfold

    def enable_streaming(self, cadence_ops: Optional[int] = None,
                         retention_floor: Optional[int] = None):
        """Turn the streaming fold on programmatically (tests and the
        swarm harness; production uses the ``Catchup.Stream`` gate).
        Returns the attached :class:`~.streamfold.StreamFoldService`."""
        if cadence_ops is not None:
            self.stream_cadence = int(cadence_ops)
        if retention_floor is not None:
            self.stream_retention = int(retention_floor)
        self.stream_enabled = True
        return self._ensure_streamfold()

    def _catchup_docs(self, session: _ClientSession, params: dict):
        """(resolved doc ids, tenant prefix) for one catchup request."""
        doc_ids = params.get("docs")
        prefix = f"{session.tenant}/" if self.tenants is not None else ""
        if doc_ids is not None:
            doc_ids = [f"{prefix}{d}" for d in doc_ids]
        else:
            doc_ids = [d for d in self.service.doc_ids()
                       if d.startswith(prefix)]
        return doc_ids, prefix

    def _catchup_entry(self, session: _ClientSession, params: dict):
        """The ``catchup`` method: admission-orchestrated (ISSUE 15).

        Lanes, in order:

        1. **warm** — requests fully servable from tiers 0/1 (including
           a single-flight ``join`` on another caller's in-flight fold)
           never touch the device and BYPASS the fold admission
           entirely: a herd of warm readers must not queue behind cold
           folds, and N concurrent catch-ups of one document cost ONE
           admission slot (the leader's).
        2. **fold** — an :class:`AdmissionController` lease per real
           fold; the shed nack's retry_after is load-derived.
        3. **degraded** — under sustained overload, the stored summary
           at an older ref_seq instead of pure shed (the client replays
           the durable tail via normal gap repair); falls back to shed
           when nothing is servable.

        Counter balance (asserted by the storm harness):
        ``catchup.requests == admitted + shed + degraded``, with
        ``catchup.warm`` counting lane-1 serves outside that balance.
        """
        # Wire-clock admission (ISSUE 18): a deterministic out-of-proc
        # caller stamps its virtual tick onto the request; in virtual
        # mode the controller advances ONLY on these, so every verdict
        # below is a pure function of the request sequence.
        vnow = params.get("vnow")
        if vnow is not None and self.admission_control.virtual:
            self.admission_control.observe(float(vnow))
        catchup = self._ensure_catchup()
        # Epoch-keyed invalidation (EpochTracker parity for the SERVER's
        # own fold caches): entries are keyed by the storage generation
        # so a recreated store can never be served a stale fold —
        # dropping dead-generation entries here just frees the budget
        # (and the HBM tier 2.5 held) immediately.  ONE sweep covers
        # every tier of every kernel family (round 14).
        catchup.invalidate_epoch(self.service.storage.epoch)
        doc_ids, prefix = self._catchup_docs(session, params)
        # Streaming head (ISSUE 16): with the streaming fold attached,
        # a summary within one fold cadence of the durable head is
        # final enough — serve it at its ref_seq (the client replays
        # the bounded tail) instead of folding the last few ops.
        streamfold = self._ensure_streamfold()
        stream_docs: list = []
        stream_lag = (streamfold.cadence_ops
                      if streamfold is not None else None)
        served, complete = catchup.catch_up_cached(
            doc_ids, join_timeout=self.warm_join_timeout,
            stream_lag=stream_lag, stream_docs=stream_docs)
        if complete:
            if stream_docs:
                self.admission.bump("catchup.stream")
                lane = "stream"
            else:
                self.admission.bump("catchup.warm")
                lane = "warm"
            return self._catchup_response(
                session, catchup, prefix, doc_ids, served,
                self._zero_fold_stats(), lane=lane,
                stream=stream_docs)
        self.admission.bump("catchup.requests")
        verdict, grant = self.admission_control.admit()
        if verdict != "admit":
            if verdict == "degrade" and self.degraded_serve:
                degraded = self._degraded_serve(session, catchup, prefix,
                                                doc_ids, served)
                if degraded is not None:
                    self.admission.bump("catchup.degraded")
                    return degraded
            self.admission.bump("catchup.shed")
            raise NackError(
                "catch-up tier overloaded; backfill from deltas "
                "or retry", retry_after=float(grant), code="overloaded",
                admission=self.admission_control.snapshot())
        self.admission.bump("catchup.admitted")
        try:
            # The warm pre-pass's partial serves ride along so the fold
            # never re-scans (or re-counts hits for) those documents.
            return self._catchup_rpc(session, params, catchup=catchup,
                                     doc_ids=doc_ids, prefix=prefix,
                                     prefetched=served,
                                     stream=stream_docs)
        finally:
            self.admission_control.release(
                grant, hold=self.catchup_hold_seconds)

    @staticmethod
    def _zero_fold_stats() -> dict:
        return dict(deviceDocs=0, cpuDocs=0, hostChannels=0,
                    fallbackChannels=0)

    def _hold_fold(self, seconds: float) -> None:
        """``catchup.slow`` actuator: an injected fold delay, advanced
        on the injected clock (virtual under a VirtualClock — the
        admission controller then measures the slow fold's cost
        deterministically; wall sleep in production)."""
        sleep = getattr(self.clock, "sleep", None)
        if sleep is not None:
            sleep(float(seconds))
        else:
            time.sleep(float(seconds))

    def _degraded_serve(self, session: _ClientSession, catchup,
                        prefix: str, doc_ids, warm_served=None):
        """Degraded-mode serving (ISSUE 15): under SUSTAINED overload,
        answer with each document's newest STORED summary at its
        (older) ref_seq instead of pure-shedding the request.  The
        client loads that summary and replays the durable op tail
        through normal DeltaManager gap repair — freshness is weakened
        (the served ref_seq may trail the head), convergence is not
        (the tail is durable and contiguous; see SEMANTICS.md "Overload
        & degradation").  ``warm_served`` seeds the answer with the
        warm pre-pass's partial results: a document the cache already
        served FRESH must not be re-answered stale (nor re-read).
        Returns None when nothing is servable (no stored summaries at
        all): the caller sheds instead."""
        storage = self.service.storage
        results: Dict[str, tuple] = dict(warm_served or {})
        degraded = []
        for doc_id in doc_ids:
            if doc_id in results:
                continue  # warm pre-pass already served it fresh
            summary, ref_seq, handle = storage.latest_with_handle(doc_id)
            if summary is None:
                continue
            results[doc_id] = (handle, ref_seq)
            if self.service.oplog.head(doc_id) > ref_seq:
                degraded.append(doc_id)
        if not results:
            return None
        self.admission.bump("catchup.degraded_docs", len(degraded))
        self.mc.logger.send({
            "eventName": "catchupDegraded", "docs": len(results),
            "stale": len(degraded)})
        return self._catchup_response(
            session, catchup, prefix, doc_ids, results,
            self._zero_fold_stats(), lane="degraded",
            degraded=degraded)

    def _catchup_rpc(self, session: _ClientSession, params: dict,
                     catchup=None, doc_ids=None, prefix=None,
                     prefetched=None, stream=()):
        """The catchup FOLD body, run under an admission lease.

        The north-star maintenance op in the deployed server shape:
        fold the named documents' op tails (or every document of the
        caller's namespace) into fresh summaries centrally, routing
        kernel-backed channels through the device (service.catchup).
        (_handle runs this method on an executor thread — the fold
        can take seconds and must not stall the event loop.)  The
        ``catchup.fail`` / ``catchup.slow`` faultline seams fire here:
        an injected failure takes the real recovery paths (the
        single-flight finally-abandon, the caller's retry policy, the
        admission release), an injected delay registers in the measured
        fold cost the shed pacing derives from."""
        if catchup is None:  # direct callers (tests, legacy paths)
            catchup = self._ensure_catchup()
            catchup.invalidate_epoch(self.service.storage.epoch)
        if doc_ids is None:
            doc_ids, prefix = self._catchup_docs(session, params)
        if self.faults is not None:
            point = self.faults.fire("catchup.fail")
            if point is not None:
                from ..testing.faults import FaultError

                raise FaultError("catchup.fail", point.kind)
            point = self.faults.fire("catchup.slow")
            if point is not None:
                self._hold_fold(point.arg)
        stats: dict = {}
        results = catchup.catch_up(doc_ids, stats=stats,
                                   prefetched=prefetched)
        return self._catchup_response(session, catchup, prefix, doc_ids,
                                      results, stats, lane="fold",
                                      stream=stream)

    def _catchup_response(self, session: _ClientSession, catchup,
                          prefix: str, doc_ids, results: dict,
                          stats: dict, lane: str, degraded=(),
                          stream=()):
        """ONE response shape for every catchup lane."""
        service = self.service
        out = {}
        for doc_id, (handle, seq) in results.items():
            self._grant_tree(service.storage.read(handle),
                             session.tenant)
            out[doc_id[len(prefix):]] = [handle, seq]
        return {
            "docs": out,
            # Explicitly-requested documents the fold could not serve
            # (unknown id, or nothing to fold from): callers must be
            # able to tell success from a typo.
            "skipped": sorted(
                d[len(prefix):] for d in doc_ids if d not in results
            ),
            # Which lane answered ("warm" | "fold" | "degraded") and —
            # for degraded serves — which documents were answered at a
            # ref_seq older than the durable head (the client's cue
            # that a tail replay is coming via gap repair).
            "lane": lane,
            "degraded": sorted(d[len(prefix):] for d in degraded),
            # Documents answered from the STREAMING HEAD: a summary at
            # most one fold cadence behind the durable head, served at
            # its ref_seq with the client replaying the bounded tail.
            "stream": sorted(d[len(prefix):] for d in stream),
            "deviceDocs": stats.get("deviceDocs", 0),
            "cpuDocs": stats.get("cpuDocs", 0),
            # Per-channel split inside device-routed documents:
            # non-kernel channels folded host-side vs kernel channels
            # that FELL BACK to their oracle (ISSUE 14 satellite — the
            # two were indistinguishable before).
            "hostChannels": stats.get("hostChannels", 0),
            "fallbackChannels": stats.get("fallbackChannels", 0),
            # Cumulative fold-cache health (hits/misses/evictions/
            # waits + bytes) — operators watching a herd of loading
            # clients see the single-flight amortization here.
            "cache": (catchup.cache.stats()
                      if catchup.cache is not None else None),
            # Tier-0 delta-download health: documents whose rows
            # never crossed the d2h link + the bytes that saved.
            "deltaCache": (catchup.delta_cache.stats()
                           if catchup.delta_cache is not None
                           else None),
            # Tier-2.5 resident-upload health: chunks dispatched with
            # zero h2d pack bytes (served), donated suffix splices
            # (spliced), and the upload bytes the tier kept off the link.
            "deviceCache": (catchup.device_cache.stats()
                            if catchup.device_cache is not None
                            else None),
        }

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        session = _ClientSession(self, writer)
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                if frame.get("v", 1) > WIRE_VERSION:
                    response = {"v": WIRE_VERSION, "re": frame.get("id"),
                                "ok": False,
                                "error": f"unsupported wire version "
                                         f"{frame.get('v')}"}
                else:
                    try:
                        method = frame.get("method")
                        params = frame.get("params", {})
                        if method in self.offloaded_methods:
                            # Device folds take seconds and storage
                            # mutations hold the commit-chain lock across
                            # disk writes; running either inline would
                            # stall every connection (all tenants) until
                            # the work — or a wedged accelerator —
                            # returns.
                            result = await asyncio.get_running_loop() \
                                .run_in_executor(
                                    None, self._track_dispatch, session,
                                    method, params,
                                )
                        else:
                            result = self._dispatch(session, method, params)
                        response = {"v": WIRE_VERSION,
                                    "re": frame.get("id"),
                                    "ok": True, "result": result}
                    except EpochMismatch as em:
                        response = {"v": WIRE_VERSION,
                                    "re": frame.get("id"),
                                    "ok": False, "error": str(em),
                                    "code": "epochMismatch",
                                    "epoch": em.server_epoch}
                    except DocRelocatedError as dr:
                        # Out-of-process redirect: this shard no longer
                        # owns the document (migrated away / stale
                        # route).  Distinct code so callers re-resolve
                        # the owner instead of treating it as a fence of
                        # a live assignment.
                        response = {"v": WIRE_VERSION,
                                    "re": frame.get("id"),
                                    "ok": False, "error": str(dr),
                                    "code": "wrongShard",
                                    "doc": dr.doc_id}
                    except ShardFencedError as sf:
                        # Mid-failover race: the request reached an
                        # orderer in the instant between its fence and
                        # the router flip.  Typed so drivers retry
                        # through the re-resolved owner instead of
                        # treating it as a generic server error.
                        response = {"v": WIRE_VERSION,
                                    "re": frame.get("id"),
                                    "ok": False, "error": str(sf),
                                    "code": "shardFenced",
                                    "doc": sf.doc_id}
                    except NackError as nack:
                        nack_body = {"retryAfter": nack.retry_after,
                                     "reason": nack.reason,
                                     "code": nack.code}
                        if nack.admission is not None:
                            nack_body["admission"] = nack.admission
                        response = {"v": WIRE_VERSION,
                                    "re": frame.get("id"),
                                    "ok": False, "error": nack.reason,
                                    "nack": nack_body}
                    except Exception as exc:  # surfaced to the client
                        # Typed catch-all (protocol/errors.py "internal",
                        # fatal): a handler fault is a deterministic
                        # rejection — framed with a registered code so it
                        # can never masquerade as transport and be
                        # blindly resent.
                        response = {"v": WIRE_VERSION,
                                    "re": frame.get("id"),
                                    "ok": False, "error": str(exc),
                                    "code": "internal"}
                session._write(response)
                await writer.drain()
        finally:
            session.close()
            writer.close()

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> None:
        self.loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        await self.start()
        async with self._server:
            await self._server.serve_forever()

    def start_in_thread(self) -> threading.Thread:
        """Run the server on a daemon thread (tests, embedded use);
        returns once the port is bound."""
        started = threading.Event()

        async def _run():
            await self.start()
            started.set()
            async with self._server:
                try:
                    await self._server.serve_forever()
                except asyncio.CancelledError:
                    pass  # server.close() from another thread: normal
                    # shutdown of an embedded server, not an error

        thread = threading.Thread(
            target=lambda: asyncio.run(_run()), daemon=True
        )
        thread.start()
        started.wait(timeout=10)
        return thread


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        description="Standalone ordering server (Tinylicious capability)"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7070)
    parser.add_argument(
        "--dir", default=None,
        help="persist the op log AND summary store under this directory "
             "(documents survive server restarts)",
    )
    parser.add_argument(
        "--shards", type=int, default=0,
        help="run a document-partitioned ordering tier with this many "
             "orderer shards (0 = single orderer); shards share the "
             "durable log/store, so --dir persistence works unchanged",
    )
    parser.add_argument(
        "--platform", default=None,
        help="pin the jax platform for the device catch-up path (e.g. "
             "'cpu').  Must be applied before the first backend use: a "
             "site-forced accelerator platform with an unhealthy tunnel "
             "would HANG the catchup RPC, and the env var alone loses to "
             "sitecustomize",
    )
    args = parser.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    oplog = storage = None
    if args.dir:
        import os

        from ..drivers.file_driver import FileSummaryStorage
        from .oplog import OpLog

        os.makedirs(args.dir, exist_ok=True)
        oplog = OpLog(path=os.path.join(args.dir, "oplog.ndjson"),
                      autoflush=True)
        storage = FileSummaryStorage(os.path.join(args.dir, "summaries"))
    if args.shards > 0:
        from .sharding import ShardedOrderingService

        service = ShardedOrderingService(
            n_shards=args.shards, oplog=oplog, storage=storage
        )
    else:
        service = LocalOrderingService(oplog=oplog, storage=storage)
    server = OrderingServer(service, host=args.host, port=args.port)

    async def _run():
        await server.start()
        print(f"ordering server listening on {server.host}:{server.port}",
              flush=True)
        async with server._server:
            await server._server.serve_forever()

    asyncio.run(_run())


if __name__ == "__main__":
    main()
