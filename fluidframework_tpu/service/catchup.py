"""Bulk catch-up: fold many documents' op tails into fresh summaries.

The north-star service path (BASELINE.json; SURVEY.md §3.2): the reference
serves catch-up by handing the client a summary plus the scriptorium op
tail, and *every client* replays that tail itself.  Here the service does
the replay centrally, in bulk, on the device: op tails for thousands of
documents are packed into ragged tensors and folded by the merge-tree
kernel in one vmapped scan, producing summaries byte-identical to the CPU
oracle — so loading clients start from a fresh summary and replay nothing.

Device routing covers every kernel-backed channel type — string, map,
matrix, and tree channels (cold AND warm starts; a warm channel's summary
re-enters its kernel as base state), including mixed-type documents.
Channels of types with no device kernel (cell, counter, directory,
consensus) fold host-side per channel inside an otherwise-device document;
only container-level disqualifiers (runtime ops, GC state, blobs) fall all
the way back to the CPU container-runtime path.  The split/scatter is the
shared :func:`partition_replay` bookkeeping.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..ops.batching import partition_replay
from ..ops.mergetree_kernel import MergeTreeDocInput
from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.summary import SummaryTree, canonical_json
from ..runtime.container import ContainerRuntime
from ..runtime.op_pipeline import decode_stream
from ..runtime.registry import ChannelRegistry, default_registry
from . import gates
from .orderer import LocalOrderingService

def jax_profiler_trace(log_dir: str):
    """``jax.profiler.trace`` context for one bulk fold (xprof); import is
    deferred so the profiler never loads on the plain CPU path."""
    import jax.profiler

    return jax.profiler.trace(log_dir)


STRING_TYPE = "sequence-tpu"
MAP_TYPE = "map-tpu"
MATRIX_TYPE = "matrix-tpu"
TREE_TYPE = "tree-tpu"
#: types with a device kernel; every other registered type folds host-side
#: per channel (still inside a device-routed document).
KERNEL_TYPES = (STRING_TYPE, MAP_TYPE, MATRIX_TYPE, TREE_TYPE)

import weakref

#: registry -> {type_name: empty digest}; weak keys so a dropped registry
#: frees its entries and a recycled address can never serve stale digests.
_EMPTY_DIGESTS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _gc_state_empty(summary: SummaryTree) -> bool:
    """Prior summary carries no gc stamps/sweeps and no blobs."""
    try:
        gc = json.loads(summary.blob_bytes(".gc"))
        if gc.get("unreferenced") or gc.get("swept") \
                or gc.get("unreferencedBlobs"):
            return False
    except KeyError:
        pass
    try:
        blobs = summary.get(".blobs")
        if isinstance(blobs, SummaryTree) and blobs.children:
            return False
    except KeyError:
        pass
    return True


def _empty_digest(registry: ChannelRegistry, type_name: str) -> str:
    """Digest of a fresh, empty channel summary for a type (id-independent:
    no built-in channel summary embeds its id).  Cached per registry OBJECT
    (weakly) — two services with different factories for the same type name
    must not poison each other's cache."""
    per_registry = _EMPTY_DIGESTS.setdefault(registry, {})
    digest = per_registry.get(type_name)
    if digest is None:
        channel = registry.get(type_name).create("-")
        digest = channel.summarize(0).digest()
        per_registry[type_name] = digest
    return digest


@dataclasses.dataclass
class _DocWork:
    doc_id: str
    summary: SummaryTree
    ref_seq: int
    tail: List[SequencedMessage]
    # device plan: [(ds_id, channel_id, type_name, channel_tree_or_None)]
    # or None (CPU fallback); computed once at partition time.
    plan: Optional[List[tuple]] = None
    # decoded (msg, batch) pairs — chunk/compression resolved once
    decoded: Optional[list] = None
    # attribution-enabled document (prior .metadata stamp): the device
    # fold must add the container .attribution table and the string
    # channels' key blobs.
    attribution: bool = False
    # result-cache key this fold will publish under (None = cache off)
    cache_key: Optional[tuple] = None


def flatten_channel_ops(
    decoded: Sequence, ds_id: str, channel_id: str
) -> List[SequencedMessage]:
    """Unwrap decoded grouped batches into the flat per-channel op stream a
    replay kernel folds over.  Sub-ops keep the batch's sequence number —
    the same view the oracle applies them under.  ``decoded`` is the
    (msg, batch) stream from :func:`decode_stream` (chunked/compressed
    batches already resolved)."""
    out = []
    for msg, batch in decoded:
        for sub in batch["ops"]:
            if sub.get("ds") == ds_id and sub.get("channel") == channel_id:
                # Direct construction — dataclasses.replace is ~4.5× the
                # cost and this rewrap runs once per sub-op of every doc
                # on the bulk catch-up path (keywords: robust to field
                # insertion at ~the same cost).
                out.append(SequencedMessage(
                    seq=msg.seq, client_id=msg.client_id,
                    client_seq=msg.client_seq, ref_seq=msg.ref_seq,
                    min_seq=msg.min_seq, type=msg.type,
                    contents=sub["contents"], timestamp=msg.timestamp,
                ))
    return out


class CatchupService:
    """Scriptorium-fed bulk summarizer over (storage, oplog).

    ``catch_up`` calls are serialized process-wide (``_serial``): bulk
    maintenance gains nothing from overlap, the device/cpu counters stay
    consistent per call, and the optional JAX profiler trace (which allows
    one active trace per process) can never nest.  Requests fully
    servable from the seq-anchored result cache bypass ``_serial``
    entirely (they do no device work), so a thundering herd of identical
    catch-ups costs ONE fold: the first caller leads, later callers
    either wait on the in-flight fold (single-flight ``join``) or hit the
    published entry."""

    _serial = threading.RLock()

    #: Longest a cache follower blocks on another thread's in-flight fold
    #: before abandoning the flight and folding itself — a leader that
    #: died without reaching its finally (killed executor thread, OOM)
    #: must not hang followers forever.  Configurable via the
    #: ``Catchup.JoinTimeout`` gate; folds themselves are unaffected.
    JOIN_TIMEOUT = float(gates.default("Catchup.JoinTimeout"))

    def __init__(
        self,
        service: LocalOrderingService,
        registry: Optional[ChannelRegistry] = None,
        mc=None,
        mesh="auto",
        cache="default",
        pack_cache="default",
        delta_cache="default",
        device_cache="default",
    ) -> None:
        from ..utils.telemetry import MonitoringContext

        self.service = service
        self.registry = registry if registry is not None else default_registry()
        self.mc = (mc or MonitoringContext()).child("catchup")
        # -- two-tier seq-anchored catch-up cache (ISSUE 3) ---------------
        # Tier 1: folded results keyed (epoch, doc, base digest, seq
        # range) with single-flight; tier 2: packed-chunk reuse inside
        # the string pipeline.  ``"default"`` builds per-instance caches
        # (gated by Catchup.Cache / Catchup.PackCache = "off"); pass an
        # instance to share across services OVER THE SAME STORE (the
        # server's per-RPC ``invalidate_epoch`` treats any other store's
        # epoch as a dead generation), or None to disable.
        from ..ops.pipeline import PackCache
        from .catchup_cache import CatchupResultCache, DeltaExportCache

        def _gated(value, gate_key, bytes_key, ctor):
            # Defaults come from the gates registry — the single source
            # the FL-DUR-GATE drift check pins call sites against.
            if value != "default":
                return value
            if not gates.is_on(self.mc.config, gate_key):
                return None
            return ctor(gates.get_int(self.mc.config, bytes_key))

        self.cache = _gated(cache, "Catchup.Cache", "Catchup.CacheBytes",
                            CatchupResultCache)
        self._pack_cache = _gated(pack_cache, "Catchup.PackCache",
                                  "Catchup.PackCacheBytes",
                                  PackCache)
        # Tier 0 (ISSUE 6): digest-gated delta download — summaries stay
        # device-resident; only changed documents' export rows cross the
        # d2h link on a warm catch-up.  Gate Catchup.DeltaDownload
        # (default ON) / Catchup.DeltaCacheBytes.
        self.delta_cache = _gated(delta_cache, "Catchup.DeltaDownload",
                                   "Catchup.DeltaCacheBytes",
                                   DeltaExportCache)
        # Tier 2.5 (ISSUE 13): device-resident pack buffers — the upload
        # mirror of tier 0.  Packed chunk arrays stay in device memory
        # keyed by the chunk's token tuple: an exact warm hit dispatches
        # with ZERO h2d pack bytes, a grown tail uploads only its suffix
        # rows through a donated in-place splice.  Gate
        # Catchup.DeviceResident (default ON) / Catchup.DeviceCacheBytes.
        from ..ops.device_cache import DevicePackCache

        self.device_cache = _gated(device_cache, "Catchup.DeviceResident",
                                    "Catchup.DeviceCacheBytes",
                                    DevicePackCache)
        # The SECOND kernel family (ISSUE 14): tree channels ride the
        # same four-tier pipeline.  Tier 0/1 are family-agnostic and
        # SHARED (entries key by channel-scoped token / doc);
        # tiers 2/2.5 hold family-typed arrays, so the tree route gets
        # its own instances behind the SAME gates — an operator turning
        # a tier off turns it off for every family.
        from ..ops.tree_pipeline import tree_device_cache, tree_pack_cache

        # Each family gets its OWN budget of the configured size (the
        # bytes keys bound a tier per family, not summed across them —
        # an operator tuning Catchup.DeviceCacheBytes down bounds the
        # tree planes exactly like the merge-tree ones).
        self.tree_pack_cache = (
            tree_pack_cache(
                gates.get_int(self.mc.config, "Catchup.PackCacheBytes"))
            if isinstance(self._pack_cache, PackCache) else None)
        self.tree_device_cache = (
            tree_device_cache(
                gates.get_int(self.mc.config, "Catchup.DeviceCacheBytes"))
            if isinstance(self.device_cache, DevicePackCache) else None)
        #: kernel channels that fell back to the oracle path (ISSUE 14
        #: satellite: hostChannels alone could not distinguish a
        #: non-kernel channel from a kernel channel that fell back).
        self.fallback_channels = 0  # guarded-by: _serial
        # Tolerant parse, explicit-None default: a configured 0 means
        # "never wait on a leader, always fold" and must not fall back
        # to the default.
        self.join_timeout = gates.get_float(
            self.mc.config, "Catchup.JoinTimeout",
            fallback=self.JOIN_TIMEOUT)
        #: busy-seconds per pipeline stage (pack/upload/dispatch/
        #: device_wait/download/extract, plus the h2d_bytes/d2h_bytes
        #: integer counters) and device/fallback doc counts, accumulated
        #: across this instance's folds — schema-identical on the
        #: single-device and mesh paths — the warm-vs-cold perf gate
        #: asserts a full cache hit leaves ``pipeline_stage["pack"]``
        #: untouched.
        self.pipeline_stage: dict = {}  # guarded-by: _serial
        self.pipeline_stats: dict = {}  # guarded-by: _serial
        #: device mesh for the bulk fold (VERDICT r4 item 7 — the north-star
        #: path is the SERVICE path, so its fold must shard too):
        #: ``"auto"`` = build a doc mesh lazily when >1 device is visible
        #: (single device keeps the plain vmapped path — no pjit overhead),
        #: a ``jax.sharding.Mesh`` = use it, ``None`` = force single-device.
        #: The ``Catchup.Mesh`` config gate ("off") disables auto detection.
        self._mesh = mesh  # guarded-by: _serial
        self._mesh_resolved = mesh != "auto"  # guarded-by: _serial
        self.device_docs = 0  # guarded-by: _serial
        self.cpu_docs = 0  # guarded-by: _serial
        self.host_channels = 0  # guarded-by: _serial (host-side channel folds)
        #: whether the CURRENT fold pass pins its folded device chunks
        #: into the tier-2.5 resident-state tier (streaming fold only).
        self._pin_resident = False  # guarded-by: _serial

    def invalidate_epoch(self, epoch: str) -> None:
        """ONE epoch sweep over every epoch-keyed cache tier this
        service holds — tier 1 (results), tier 0 (delta export), and
        BOTH families' tier-2.5 resident buffers (the server's per-RPC
        sweep calls this so a new family can never be forgotten).  The
        tier-2 pack caches need no sweep: their tokens carry the epoch
        as component 0, so dead-generation windows simply never match
        and age out of the LRU."""
        if self.cache is not None:
            self.cache.invalidate_epoch(epoch)
        if self.delta_cache is not None:
            self.delta_cache.invalidate_epoch(epoch)
        if self.device_cache is not None:
            self.device_cache.invalidate_epoch(epoch)
        if self.tree_device_cache is not None:
            self.tree_device_cache.invalidate_epoch(epoch)

    def _resolve_mesh(self):  # holds-lock: _serial
        """Lazy mesh detection: touch ``jax.devices()`` only on the first
        device fold (init must stay cheap and never probe a possibly-sick
        accelerator tunnel).  Callers hold ``_serial`` (fold path only)."""
        if not self._mesh_resolved:
            self._mesh_resolved = True
            self._mesh = None
            if gates.is_on(self.mc.config, "Catchup.Mesh"):
                import jax

                from ..parallel.shard import doc_mesh

                devices = jax.devices()
                if len(devices) > 1:
                    self._mesh = doc_mesh(devices)
        return self._mesh

    # -- public API ------------------------------------------------------------

    def catch_up_cached(
        self,
        doc_ids: Optional[Sequence[str]] = None,
        upload: bool = True,
        join_timeout: Optional[float] = None,
        stream_lag: Optional[int] = None,
        stream_docs: Optional[list] = None,
    ) -> Tuple[Dict[str, Tuple[str, int]], bool]:
        """The tier-0/1 WARM pass alone: ``(results, complete)`` where
        ``complete`` means every requested document was served without
        any device work — from the result cache, a single-flight join
        on another caller's in-flight fold, or the no-new-ops fast path
        — and the caller can skip the fold lane entirely.  This is the
        server's admission priority lane (ISSUE 15): warm readers must
        never queue behind cold folds, and a herd joining one in-flight
        fold costs the leader's ONE admission slot.  ``join_timeout``
        bounds the single-flight wait (defaults to the service's
        ``Catchup.JoinTimeout``); the server passes a SHORT bound so a
        wedged leader turns joiners into fold-lane requests — where
        admission sheds with pacing — instead of parking them on
        executor threads.  ``({}, False)`` when the result cache is
        disabled.

        ``stream_lag`` (round 16, set by the server when a streaming
        fold is attached) widens the no-new-ops fast path into the
        STREAMING-HEAD lane: a document whose durable head is within
        ``stream_lag`` ops of its newest summary serves that summary at
        its ref_seq — the client gap-repairs the bounded tail from the
        op log, exactly the reference's summary+tail contract — instead
        of falling to the fold lane.  The bound is the fold cadence, so
        with the streaming fold healthy EVERY doc qualifies and the
        warm lane hit rate goes to ~1.0.  Docs served laggy are
        appended to ``stream_docs`` (when given) so the server can
        label the lane."""
        if self.cache is None:
            return {}, False
        return self._serve_cached(doc_ids, upload,
                                  join_timeout=join_timeout,
                                  stream_lag=stream_lag,
                                  stream_docs=stream_docs)

    def catch_up(
        self,
        doc_ids: Optional[Sequence[str]] = None,
        upload: bool = True,
        stats: Optional[dict] = None,
        prefetched: Optional[Dict[str, Tuple[str, int]]] = None,
        pin_resident: bool = False,
    ) -> Dict[str, Tuple[str, int]]:
        """Fold each document's tail; returns {doc_id: (handle, seq)}.
        Documents with no new ops keep their current summary handle.
        ``stats`` (optional dict) receives this call's own
        ``deviceDocs``/``cpuDocs``/``hostChannels`` deltas, computed under
        the serialization lock so concurrent callers' documents never leak
        into each other's numbers.  ``prefetched`` carries results a
        caller's OWN :meth:`catch_up_cached` pass already served (the
        server's warm lane): the internal cached pass is skipped so those
        documents' metadata scans — and their cache hit counts — never
        run twice.  ``pin_resident`` (the streaming fold) pins the folded
        chunks' device buffers into the tier-2.5 resident-state tier so
        the NEXT micro-batch splices onto them instead of re-uploading.

        With the ``Catchup.ProfileDir`` config gate set (or
        ``FLUID_TPU_CATCHUP_PROFILEDIR``), each bulk fold is wrapped in a
        JAX profiler trace written there — the per-replay-batch xprof hook
        of the telemetry design (SURVEY.md §5 tracing)."""
        import contextlib

        from ..utils.telemetry import PerformanceEvent

        # None = no warm pass ran yet (run ours); a dict — even an empty
        # one — means the CALLER's warm pass already scanned, and
        # re-scanning here would duplicate the metadata/tail reads and
        # double-count cache hits.
        skip_warm = prefetched is not None
        prefetched = dict(prefetched or {})
        if self.cache is not None and not skip_warm:
            served, complete = self._serve_cached(doc_ids, upload)
            if complete:
                # Pure cache serve: no fold ran, all deltas are zero.
                if stats is not None:
                    stats.update(deviceDocs=0, cpuDocs=0, hostChannels=0,
                                 fallbackChannels=0)
                # stats() is the LOCKED snapshot — reading the counter
                # dict directly would race concurrent leaders bumping it
                # under the cache lock (fluidrace cannot see cross-object
                # guarding, but the discipline still applies).
                self.mc.logger.send({
                    "eventName": "cacheServe", **self.cache.stats(),
                    "docs": len(served),
                })
                return served
            # Partially cached: carry the already-served docs into the
            # fold pass so their metadata scan (latest + tail + digest)
            # and hit counting never run twice.
            prefetched = served
        profile_dir = gates.raw(self.mc.config, "Catchup.ProfileDir")
        with CatchupService._serial:
            self._pin_resident = pin_resident
            tracer = (
                jax_profiler_trace(str(profile_dir))
                if profile_dir else contextlib.nullcontext()
            )
            device_before, cpu_before = self.device_docs, self.cpu_docs
            host_before = self.host_channels
            fb_before = self.fallback_channels
            with tracer, PerformanceEvent.timed_exec(
                    self.mc.logger, "bulkCatchup") as perf:
                results = self._catch_up(doc_ids, upload, prefetched)
                deltas = dict(
                    deviceDocs=self.device_docs - device_before,
                    cpuDocs=self.cpu_docs - cpu_before,
                    hostChannels=self.host_channels - host_before,
                    # Kernel channels that fell back to the oracle this
                    # call — distinguishable from hostChannels (channel
                    # types with no kernel at all) since round 14.
                    fallbackChannels=self.fallback_channels - fb_before,
                )
                perf["extra"].update(docs=len(results), **deltas)
            if stats is not None:
                stats.update(deltas)
            return results

    def _cache_key_at(self, doc_id: str, base_handle: str, ref_seq: int,
                      head_seq: int) -> tuple:
        """Seq-anchored identity of one fold's full input: the store
        generation pins the namespace, the base summary HANDLE (the
        commit's tree digest — never re-hashed here) pins the summary
        bytes, and (ref_seq, head seq) pins the tail bytes — the op log
        is append-only, so the range IS the content."""
        return (self.service.storage.epoch, doc_id, base_handle,
                ref_seq, head_seq)

    def _cache_key(self, doc_id: str, base_handle: str, ref_seq: int,
                   tail: Sequence[SequencedMessage]) -> tuple:
        """:meth:`_cache_key_at` over a materialized tail (seqs are
        contiguous, so the last message's seq IS the durable head)."""
        return self._cache_key_at(doc_id, base_handle, ref_seq,
                                  tail[-1].seq)

    def _finish_result(self, doc_id: str, fold, seq: int,
                       upload: bool) -> Tuple[str, int]:
        """``fold`` is a CachedFold (tree + handle digested once at
        publish) — a cache hit never re-walks the tree."""
        if upload:
            # Idempotent publish (atomic check-and-upload under the store
            # lock): N cache-served followers of one fold chain ONE
            # commit onto the document's history, not N duplicates.
            return self.service.storage.upload_absent(
                doc_id, fold.tree, seq, handle=fold.handle), seq
        return fold.handle, seq

    def _serve_cached(self, doc_ids, upload: bool,
                      join_timeout: Optional[float] = None,
                      stream_lag: Optional[int] = None,
                      stream_docs: Optional[list] = None):
        """As much of the request as tier 1 can serve: ``(results,
        complete)`` where ``complete`` means every document was served
        and the caller can skip the fold path entirely.  Runs WITHOUT
        the serialization lock: a request for an in-flight key waits on
        that fold (single-flight) instead of queueing behind the device.
        Stops at the first miss — the fold pass re-reads the remaining
        docs under the lock anyway, so scanning past the miss would be
        pure duplicated work.  Deliberately O(1) per document on the
        storage side: the cache key needs only the durable HEAD seq
        (appends are contiguous, so the head IS the last tail seq), so
        a request that ends up SHED never materialized a single op —
        the pre-admission warm probe must not cost what admission
        exists to bound."""
        if join_timeout is None:
            join_timeout = self.join_timeout
        results: Dict[str, Tuple[str, int]] = {}
        for doc_id in (doc_ids if doc_ids is not None
                       else self.service.doc_ids()):
            summary, ref_seq, handle = \
                self.service.storage.latest_with_handle(doc_id)
            if summary is None:
                continue
            head = self.service.oplog.head(doc_id)
            if head <= ref_seq:
                results[doc_id] = (handle, ref_seq)
                continue
            if stream_lag is not None and head - ref_seq <= stream_lag:
                # Streaming-head serve: the summary trails the durable
                # head by at most the fold cadence — hand it out at its
                # ref_seq and let the client replay the bounded tail
                # (summary + tail, the reference contract).  No fold, no
                # admission, no device work.
                results[doc_id] = (handle, ref_seq)
                if stream_docs is not None:
                    stream_docs.append(doc_id)
                continue
            fold = self.cache.join(
                self._cache_key_at(doc_id, handle, ref_seq, head),
                timeout=join_timeout,
                # Only a wait that exhausted the service's full
                # crashed-leader bound may reap the flight; a caller's
                # deliberately shorter wait (the warm priority lane)
                # just stops waiting.
                reap_on_timeout=join_timeout >= self.join_timeout,
            )
            if fold is None:
                # Nothing cached/in flight — or the bounded wait expired
                # on a leader that crashed without reaching its
                # finally-abandon (join() already removed the dead
                # flight and woke its other waiters).  Either way the
                # fold path re-claims the key: begin() leads.
                return results, False  # at least one real fold needed
            results[doc_id] = self._finish_result(
                doc_id, fold, head, upload)
        return results, True

    def _catch_up(  # holds-lock: _serial
        self,
        doc_ids: Optional[Sequence[str]] = None,
        upload: bool = True,
        prefetched: Optional[Dict[str, Tuple[str, int]]] = None,
    ) -> Dict[str, Tuple[str, int]]:
        works: List[_DocWork] = []
        results: Dict[str, Tuple[str, int]] = dict(prefetched or {})
        leading: set = set()
        try:
            for doc_id in (doc_ids if doc_ids is not None
                           else self.service.doc_ids()):
                if results.get(doc_id) is not None:
                    continue  # served by the pre-lock cache pass
                summary, ref_seq, handle = \
                    self.service.storage.latest_with_handle(doc_id)
                if summary is None:
                    continue  # never attached: nothing to summarize from
                tail = self.service.oplog.get(doc_id, from_seq=ref_seq)
                if not tail:
                    results[doc_id] = (handle, ref_seq)
                    continue
                key = None
                if self.cache is not None:
                    key = self._cache_key(doc_id, handle, ref_seq, tail)
                    status, fold = self.cache.begin(key)
                    if status == "hit":
                        results[doc_id] = self._finish_result(
                            doc_id, fold, tail[-1].seq, upload)
                        continue
                    leading.add(key)
                work = _DocWork(doc_id, summary, ref_seq, tail)
                work.cache_key = key
                work.decoded = list(decode_stream(tail))
                work.plan = self._device_plan(work)
                works.append(work)

            trees = partition_replay(
                works,
                known_fallback=lambda w: w.plan is None,
                fallback_fn=self._cpu_fold,
                batch_fn=self._device_fold,
            )
            from .catchup_cache import CachedFold

            for work, tree in zip(works, trees):
                if work.cache_key is not None:
                    # Publish BEFORE the upload so single-flight waiters
                    # unblock as early as possible; finish() hands back
                    # the one digest it computed.
                    fold = self.cache.finish(work.cache_key, tree)
                    leading.discard(work.cache_key)
                else:
                    fold = CachedFold(tree, tree.digest())
                results[work.doc_id] = self._finish_result(
                    work.doc_id, fold, work.tail[-1].seq, upload)
            return results
        finally:
            # A failed fold must never strand single-flight waiters.
            if self.cache is not None:
                for key in sorted(leading):
                    self.cache.abandon(key)

    # -- CPU path --------------------------------------------------------------

    def _cpu_fold(self, work: _DocWork) -> SummaryTree:  # holds-lock: _serial
        self.cpu_docs += 1
        runtime = ContainerRuntime(self.registry)
        runtime.load(work.summary)
        for msg in work.tail:
            runtime.process(msg)
        return runtime.summarize()

    # -- device path -----------------------------------------------------------

    def _device_plan(self, work: _DocWork):
        """Device-eligible shape: only container-level state must be
        trivially foldable (no runtime ops, empty GC/blob state).  Every
        registered channel type participates — kernel types fold on device
        (cold or warm; a warm channel's summary re-enters its kernel as
        base state), others fold host-side per channel.  Returns
        [(ds_id, channel_id, type_name, channel_tree_or_None)] where None
        marks a cold (empty prior summary) channel; None = CPU path."""
        try:
            ds_root = work.summary.get(".datastores")
        except KeyError:
            return None
        # GC/blob state must be trivially foldable host-side.
        if not _gc_state_empty(work.summary):
            return None
        try:
            meta = json.loads(work.summary.blob_bytes(".metadata"))
        except KeyError:
            meta = {}
        attribution = bool(meta.get("attribution"))
        for _msg, batch in work.decoded:
            if any("runtime" in sub for sub in batch["ops"]):
                return None  # blob/ds/channel attaches, sweeps: CPU path
        plan = []
        for ds_id, subtree in ds_root.children.items():
            if not isinstance(subtree, SummaryTree):
                return None
            try:
                attrs = json.loads(subtree.blob_bytes(".attributes"))
            except KeyError:
                return None
            if not attrs.get("rooted", True):
                return None  # GC-collectible datastore: CPU path
            channels = attrs.get("channels")
            if channels is None:
                return None  # unrecognized attributes shape: CPU path
            for channel_id, type_name in channels.items():
                try:
                    self.registry.get(type_name)
                except KeyError:
                    return None  # unknown type: CPU path decides
                channel_tree = subtree.children[channel_id]
                if channel_tree.digest() == _empty_digest(
                        self.registry, type_name):
                    channel_tree = None  # cold fold
                plan.append((ds_id, channel_id, type_name, channel_tree))
        if plan:
            work.attribution = attribution
        return plan or None

    @staticmethod
    def _string_base_kwargs(channel_tree: Optional[SummaryTree]) -> dict:
        if channel_tree is None:
            return {}
        header = json.loads(channel_tree.blob_bytes("header"))
        records = json.loads(channel_tree.blob_bytes("body"))
        if "attribution" in channel_tree.children:
            # Warm base carrying pre-clamp keys: the ONE shared splitter
            # (SharedString.load uses it too), so the re-summarize
            # regenerates identical body AND keys.
            from ..dds.merge_tree import MergeTreeOracle

            MergeTreeOracle.split_records_by_attribution_keys(
                records, json.loads(channel_tree.blob_bytes("attribution"))
            )
        try:
            intervals = json.loads(channel_tree.blob_bytes("intervals"))
        except KeyError:
            intervals = None
        return {
            "base_records": records,
            "base_seq": header["seq"],
            "base_msn": header["minSeq"],
            "base_intervals": intervals,
        }

    def _host_channel_fold(self, type_name: str, channel_id: str,
                           channel_tree: Optional[SummaryTree],
                           ops: List[SequencedMessage], work: _DocWork,
                           final_msn: int) -> SummaryTree:
        """Fold one non-kernel channel host-side, byte-identical to what the
        container runtime would produce: its op stream interleaved with the
        tail's JOIN/LEAVE (consensus channels re-queue a departed client's
        held items via ``observe_protocol``) and per-message window
        advances."""
        factory = self.registry.get(type_name)
        if channel_tree is None:
            channel = factory.create(channel_id)
        else:
            channel = factory.load(channel_id, channel_tree)
        by_seq: Dict[int, List[SequencedMessage]] = {}
        for m in ops:
            by_seq.setdefault(m.seq, []).append(m)
        observe = getattr(channel, "observe_protocol", None)
        advance = getattr(channel, "advance", None)
        for msg in work.tail:
            if msg.type in (MessageType.JOIN, MessageType.LEAVE) \
                    and observe is not None:
                observe(msg)
            for m in by_seq.get(msg.seq, []):
                channel.process(m, local=False)
            if advance is not None:
                advance(msg.seq, msg.min_seq)
        return channel.summarize(final_msn)

    def _device_fold(self, works: List[_DocWork]) -> List[SummaryTree]:
        # holds-lock: _serial
        """Batch every (doc, channel) pair into its kernel's batch (one
        device call per kernel type); fold non-kernel channels host-side;
        reassemble full container summary trees, byte-identical to
        ``ContainerRuntime.summarize()``."""
        from ..ops.map_kernel import MapDocInput, replay_map_batch
        from ..ops.matrix_kernel import MatrixDocInput, replay_matrix_batch
        from ..ops.tree_kernel import TreeDocInput, replay_tree_batch

        # Collect per-kernel inputs; (work_idx, plan_idx) → result slot.
        string_in: List[MergeTreeDocInput] = []
        map_in: List[MapDocInput] = []
        matrix_in: List[MatrixDocInput] = []
        tree_in: List[TreeDocInput] = []
        slots: Dict[Tuple[int, int], Tuple[str, int]] = {}
        host_trees: Dict[Tuple[int, int], SummaryTree] = {}
        epoch = self.service.storage.epoch
        for wi, work in enumerate(works):
            self.device_docs += 1
            final_seq = work.tail[-1].seq
            final_msn = max(m.min_seq for m in work.tail)
            for pi, (ds_id, channel_id, type_name, channel_tree) in \
                    enumerate(work.plan):
                cid = f"{work.doc_id}/{ds_id}/{channel_id}"
                ops = flatten_channel_ops(work.decoded, ds_id, channel_id)

                def channel_token(tree=channel_tree, cid=cid):
                    # THE append-only cache identity (tiers 0/2/2.5)
                    # every kernel family packs under: the channel's op
                    # stream extends append-only under a fixed (epoch,
                    # base summary, ref_seq) anchor.  ONE derivation
                    # point — two hand-synced copies could silently give
                    # one family a weaker key — called lazily: only the
                    # pipelined families consume it, and the digest is a
                    # full Merkle walk the other channels must not pay.
                    return (epoch, cid, work.ref_seq,
                            tree.digest() if tree is not None else "")

                if type_name not in KERNEL_TYPES:
                    self.host_channels += 1
                    host_trees[wi, pi] = self._host_channel_fold(
                        type_name, channel_id, channel_tree, ops, work,
                        final_msn,
                    )
                elif type_name == STRING_TYPE:
                    slots[wi, pi] = (STRING_TYPE, len(string_in))
                    string_in.append(MergeTreeDocInput(
                        doc_id=cid, ops=ops, final_seq=final_seq,
                        final_msn=final_msn,
                        attribution=work.attribution,
                        cache_token=channel_token(),
                        **self._string_base_kwargs(channel_tree),
                    ))
                elif type_name == MAP_TYPE:
                    base = None
                    if channel_tree is not None:
                        base = json.loads(
                            channel_tree.blob_bytes("header"))["data"]
                    slots[wi, pi] = (MAP_TYPE, len(map_in))
                    map_in.append(MapDocInput(doc_id=cid, ops=ops, base=base))
                elif type_name == MATRIX_TYPE:
                    slots[wi, pi] = (MATRIX_TYPE, len(matrix_in))
                    matrix_in.append(MatrixDocInput(
                        doc_id=cid, ops=ops, base_summary=channel_tree,
                        final_seq=final_seq, final_msn=final_msn,
                    ))
                else:
                    assert type_name == TREE_TYPE
                    slots[wi, pi] = (TREE_TYPE, len(tree_in))
                    tree_in.append(TreeDocInput(
                        doc_id=cid, ops=ops, base_summary=channel_tree,
                        final_seq=final_seq, final_msn=final_msn,
                        attribution=work.attribution,
                        cache_token=channel_token(),
                    ))
        mesh = self._resolve_mesh()
        if mesh is not None:
            # Mesh-sharded service fold: the same byte-identical
            # summaries, document axis partitioned over the mesh
            # (parallel/shard.py), serving the IDENTICAL four-tier cache
            # stack and stage-counter schema as the single-device
            # pipeline below (round 13 paid the mesh-parity debt): tier-2
            # pack reuse, tier-0 digest-gated delta download, tier-2.5
            # resident upload buffers (doc-sharded placement), and the
            # pack/upload/dispatch/device_wait/download/extract busy
            # split with h2d/d2h byte counters.
            import functools

            from ..parallel.shard import (
                replay_map_sharded,
                replay_matrix_sharded,
                replay_mergetree_sharded,
                replay_tree_sharded,
            )

            replay = {
                STRING_TYPE: functools.partial(
                    replay_mergetree_sharded, mesh=mesh,
                    stats=self.pipeline_stats,
                    stage=self.pipeline_stage,
                    pack_cache=self._pack_cache,
                    delta_cache=self.delta_cache,
                    device_cache=self.device_cache),
                MAP_TYPE: functools.partial(
                    replay_map_sharded, mesh=mesh,
                    stats=self.pipeline_stats),
                MATRIX_TYPE: functools.partial(
                    replay_matrix_sharded, mesh=mesh,
                    stats=self.pipeline_stats),
                # The second kernel family (ISSUE 14): the tree route
                # serves the IDENTICAL four-tier stack and stage schema
                # as the string route — tier 0 shared, tiers 2/2.5 its
                # own family-typed instances.
                TREE_TYPE: functools.partial(
                    replay_tree_sharded, mesh=mesh,
                    stats=self.pipeline_stats,
                    stage=self.pipeline_stage,
                    pack_cache=self.tree_pack_cache,
                    delta_cache=self.delta_cache,
                    device_cache=self.tree_device_cache),
            }
        else:
            import functools

            from ..ops.pipeline import pipelined_mergetree_replay
            from ..ops.tree_pipeline import pipelined_tree_replay

            # String + tree channels (the two PAPER §0 kernel families)
            # ride the chunked, single-device-thread family pipeline —
            # the same code path bench.py measures; the remaining
            # kernels' batches are small enough to fold in one dispatch
            # each (matrix is the named third family candidate).  Stage
            # busy seconds + doc counts accumulate on this instance (the
            # warm-vs-cold gates read them), and packed windows reuse
            # through the per-family tier-2 pack caches.
            replay = {
                STRING_TYPE: functools.partial(
                    pipelined_mergetree_replay,
                    stats=self.pipeline_stats,
                    stage=self.pipeline_stage,
                    pack_cache=self._pack_cache,
                    delta_cache=self.delta_cache,
                    device_cache=self.device_cache,
                    pin_resident=self._pin_resident,
                ),
                MAP_TYPE: functools.partial(
                    replay_map_batch, stats=self.pipeline_stats),
                MATRIX_TYPE: functools.partial(
                    replay_matrix_batch, stats=self.pipeline_stats),
                TREE_TYPE: functools.partial(
                    pipelined_tree_replay,
                    stats=self.pipeline_stats,
                    stage=self.pipeline_stage,
                    pack_cache=self.tree_pack_cache,
                    delta_cache=self.delta_cache,
                    device_cache=self.tree_device_cache,
                    pin_resident=self._pin_resident,
                ),
            }
        fb_before = self.pipeline_stats.get("fallback_docs", 0)
        results = {
            STRING_TYPE: replay[STRING_TYPE](string_in),
            MAP_TYPE: replay[MAP_TYPE](map_in) if map_in else [],
            MATRIX_TYPE: replay[MATRIX_TYPE](matrix_in) if matrix_in else [],
            TREE_TYPE: replay[TREE_TYPE](tree_in) if tree_in else [],
        }
        # Kernel channels that fell back to their oracle (pre-pack
        # routing + post-fold overflow alike bump fallback_docs at the
        # one shared counting point) — the hostChannels disambiguator.
        self.fallback_channels += (
            self.pipeline_stats.get("fallback_docs", 0) - fb_before)

        out: List[SummaryTree] = []
        for wi, work in enumerate(works):
            final_seq = work.tail[-1].seq
            final_msn = max(m.min_seq for m in work.tail)
            tree = SummaryTree()
            tree.add_blob(
                ".metadata",
                canonical_json(
                    ContainerRuntime.container_metadata(
                        final_seq, final_msn,
                        attribution=work.attribution,
                    )
                ),
            )
            tree.add_blob(
                ".protocol", canonical_json(self._fold_protocol(work))
            )
            tree.add_blob(
                ".idCompressor",
                canonical_json(self._fold_id_compressor(work)),
            )
            if work.attribution:
                tree.add_blob(
                    ".attribution",
                    canonical_json(self._fold_attribution(work)),
                )
            # Eligibility guaranteed nothing becomes unreferenced and no
            # blobs exist: the folded gc/blob state is the empty state.
            from ..runtime.gc import GarbageCollector

            tree.add_blob(".gc",
                          canonical_json(GarbageCollector.empty_state()))
            tree.add_tree(".blobs")
            ds_tree = tree.add_tree(".datastores")
            by_ds: Dict[str, List[Tuple[str, str, int]]] = {}
            for pi, (ds_id, channel_id, type_name, _base) in \
                    enumerate(work.plan):
                by_ds.setdefault(ds_id, []).append(
                    (channel_id, type_name, pi)
                )
            for ds_id in sorted(by_ds):
                sub = SummaryTree()
                channel_types = {}
                for channel_id, type_name, pi in sorted(by_ds[ds_id]):
                    if (wi, pi) in host_trees:
                        sub.children[channel_id] = host_trees[wi, pi]
                    else:
                        kind, idx = slots[wi, pi]
                        sub.children[channel_id] = results[kind][idx]
                    channel_types[channel_id] = type_name
                sub.add_blob(".attributes", canonical_json(
                    {"channels": channel_types, "rooted": True}
                ))
                ds_tree.children[ds_id] = sub
            out.append(tree)
        return out

    def _fold_attribution(self, work: _DocWork) -> dict:
        """Replicate the runtime's attribution recording over the tail on
        top of the prior summary's table (container.py: observe AFTER
        chunk reassembly — only the final chunk's seq is ever stamped —
        and only when contents resolved non-None)."""
        from ..runtime.attributor import Attributor
        from ..runtime.op_pipeline import ChunkReassembler, maybe_decompress

        try:
            prior = json.loads(work.summary.blob_bytes(".attribution"))
        except KeyError:
            prior = None
        attr = Attributor.deserialize(prior)
        chunks = ChunkReassembler()
        for msg in work.tail:
            contents = msg.contents
            if msg.type is MessageType.OP and isinstance(contents, dict):
                if contents.get("type") == "chunk":
                    contents = chunks.feed(msg.client_id, contents)
                else:
                    contents = maybe_decompress(contents)
            elif msg.type is MessageType.LEAVE:
                # The runtime drops a departed client's partial chunk
                # train (container.py LEAVE handling); a later same-id
                # chunk must not complete it here either, or the device
                # and CPU folds would stamp different tables.
                chunks.drop(msg.contents["clientId"])
            if contents is not None:
                attr.observe(msg)
        return attr.serialize()

    def _fold_id_compressor(self, work: _DocWork) -> dict:
        """Replicate the runtime's sequenced id-range finalization for the
        host-composed summary (byte-parity with the CPU fold)."""
        from ..runtime.id_compressor import IdCompressor

        try:
            prior = json.loads(work.summary.blob_bytes(".idCompressor"))
            comp = IdCompressor.deserialize(prior)
        except KeyError:
            comp = IdCompressor()
        for _msg, batch in work.decoded:
            if "idRange" in batch:
                comp.finalize_range(batch["idRange"])
        return comp.serialize()

    def _fold_protocol(self, work: _DocWork) -> dict:
        """Replay the tail over the prior protocol state: quorum membership
        (JOIN/LEAVE) and propose/accept (PROPOSAL + MSN advancement) — the
        exact fold ContainerRuntime.process performs."""
        from ..protocol.quorum import QuorumProposals

        protocol = json.loads(work.summary.blob_bytes(".protocol"))
        order: List[str] = list(protocol["quorum"])
        proposals = QuorumProposals.deserialize(protocol.get("proposals"))
        for msg in work.tail:
            if msg.type is MessageType.JOIN:
                cid = msg.contents["clientId"]
                if cid not in order:
                    order.append(cid)
            elif msg.type is MessageType.LEAVE:
                cid = msg.contents["clientId"]
                if cid in order:
                    order.remove(cid)
            proposals.observe(msg)
        return {"proposals": proposals.serialize(), "quorum": order}
