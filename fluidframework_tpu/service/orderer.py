"""Per-document orderer and the multi-document front door.

Capability-equivalent of the reference's ``LocalOrderer`` (memory-orderer:
deli + scribe + scriptorium lambdas wired in one process) plus the Alfred
front door (document creation, per-client delta connections, signal fan-out)
— SURVEY.md §2.3/§3.5; upstream paths UNVERIFIED, empty reference mount.

The shape differs from Routerlicious deliberately: there is no Kafka hop —
the sequencer broadcast *is* the bus, and the durable :class:`OpLog` append
happens inside the broadcast (first subscriber), so the log is always at or
ahead of any client's view and strictly ahead of the checkpoint.  Crash
resume = restore checkpoint + replay the log tail into the sequencer/scribe
state (exactly-once: ``replay`` never re-stamps).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..protocol.messages import RawOperation, SequencedMessage
from ..protocol.sequencer import Sequencer
from ..protocol.summary import SummaryStorage
from .oplog import OpLog
from .scribe import Scribe

SignalListener = Callable[[dict], None]


class DocumentOrderer:
    """One document's service state: sequencer + scribe + durable log."""

    def __init__(
        self,
        doc_id: str,
        oplog: OpLog,
        storage: SummaryStorage,
        sequencer: Optional[Sequencer] = None,
        throttle=None,
    ) -> None:
        self.doc_id = doc_id
        self.oplog = oplog
        self.storage = storage
        self.sequencer = sequencer or Sequencer(throttle=throttle)
        # Durable append rides first in the broadcast chain: by the time any
        # client sees a message it is already in the log (scriptorium-before-
        # broadcast, collapsing the reference's Kafka fan-out).
        self.sequencer.subscribe(lambda msg: oplog.append(doc_id, msg))
        self.scribe = Scribe(doc_id, self.sequencer, storage)
        self._signal_listeners: List[SignalListener] = []

    # -- signals (unsequenced ephemeral broadcast — presence rides this) -------

    def submit_signal(self, client_id: str, content,
                      target_client_id: Optional[str] = None) -> None:
        signal = {
            "clientId": client_id,
            "content": content,
            "targetClientId": target_client_id,
        }
        for fn in list(self._signal_listeners):
            fn(signal)

    def subscribe_signals(self, fn: SignalListener) -> None:
        self._signal_listeners.append(fn)

    def unsubscribe_signals(self, fn: SignalListener) -> None:
        if fn in self._signal_listeners:
            self._signal_listeners.remove(fn)

    # -- checkpoint / crash-resume ---------------------------------------------

    def checkpoint(self) -> dict:
        return {
            "sequencer": self.sequencer.checkpoint(),
            "scribe": self.scribe.checkpoint(),
        }

    @staticmethod
    def restore(
        doc_id: str,
        oplog: OpLog,
        storage: SummaryStorage,
        checkpoint: dict,
    ) -> "DocumentOrderer":
        """Resume after a crash: the checkpoint may lag the durable log;
        the tail is replayed into sequencer + scribe state exactly-once.

        Clients that died with the process remain in the quorum (their
        dedup floors must survive for reconnect); the host is responsible
        for ``disconnect``-ing ones that never return, or the MSN stays
        pinned at their last ref_seq."""
        checkpoint_seq = checkpoint["sequencer"]["seq"]
        sequencer = Sequencer.restore(
            checkpoint["sequencer"],
            log=oplog.get(doc_id, to_seq=checkpoint_seq),
        )
        orderer = DocumentOrderer(doc_id, oplog, storage, sequencer=sequencer)
        orderer.scribe.restore(checkpoint["scribe"])
        for msg in oplog.get(doc_id, from_seq=checkpoint_seq):
            sequencer.replay(msg)
            orderer.scribe.replay(msg)
        return orderer

    @staticmethod
    def recover(
        doc_id: str, oplog: OpLog, storage: SummaryStorage
    ) -> "DocumentOrderer":
        """No checkpoint at all: rebuild everything from the durable log."""
        orderer = DocumentOrderer(doc_id, oplog, storage)
        for msg in oplog.get(doc_id):
            orderer.sequencer.replay(msg)
            orderer.scribe.replay(msg)
        return orderer


class DocumentEndpoint:
    """A per-document connection facade handed to clients/drivers.

    Satisfies the ``ContainerRuntime.connect`` contract — ``submit``,
    ``subscribe``, ``connect``, ``log`` — plus signals and ranged delta
    reads, so the same runtime code runs against the in-proc sequencer,
    this service, or a remote driver.
    """

    def __init__(self, orderer: DocumentOrderer) -> None:
        self._orderer = orderer

    @property
    def doc_id(self) -> str:
        return self._orderer.doc_id

    @property
    def log(self) -> List[SequencedMessage]:
        return self._orderer.oplog.get(self._orderer.doc_id)

    @property
    def head_seq(self) -> int:
        return self._orderer.sequencer.seq

    def connect(self, client_id: str, session: Optional[str] = None) -> None:
        self._orderer.sequencer.connect(client_id, session)

    def disconnect(self, client_id: str) -> None:
        self._orderer.sequencer.disconnect(client_id)

    def submit(self, op: RawOperation) -> Optional[SequencedMessage]:
        return self._orderer.sequencer.submit(op)

    def subscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        self._orderer.sequencer.subscribe(fn)

    def unsubscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        self._orderer.sequencer.unsubscribe(fn)

    def update_ref_seq(self, client_id: str, ref_seq: int) -> None:
        self._orderer.sequencer.update_ref_seq(client_id, ref_seq)

    def deltas(self, from_seq: int = 0,
               to_seq: Optional[int] = None) -> List[SequencedMessage]:
        return self._orderer.oplog.get(self._orderer.doc_id, from_seq, to_seq)

    def submit_signal(self, client_id: str, content,
                      target_client_id: Optional[str] = None) -> None:
        self._orderer.submit_signal(client_id, content, target_client_id)

    def subscribe_signals(self, fn: SignalListener) -> None:
        self._orderer.subscribe_signals(fn)

    def unsubscribe_signals(self, fn: SignalListener) -> None:
        self._orderer.unsubscribe_signals(fn)


class LocalOrderingService:
    """Multi-document ordering service in one process — the Tinylicious
    capability point: create/load documents, connect clients, store
    summaries, serve catch-up deltas."""

    def __init__(
        self,
        oplog: Optional[OpLog] = None,
        storage: Optional[SummaryStorage] = None,
        throttle=None,
    ) -> None:
        self.oplog = oplog if oplog is not None else OpLog()
        self.storage = storage if storage is not None else SummaryStorage()
        #: optional per-submit throttle policy handed to every document's
        #: sequencer: callable(client_id) -> retry-after seconds | None.
        self.throttle = throttle
        #: summary-node digest -> {tenant ids allowed to read it}.  Lives on
        #: the SHARED service (not a front-door instance) so multi-instance
        #: deployments agree; content-addressed nodes can be owned by many
        #: tenants at once.  A production store would prune these with
        #: summary eviction; entries are per-node and tiny.
        self.handle_tenants: Dict[str, set] = {}  # guarded-by: state_lock
        self._orderers: Dict[str, DocumentOrderer] = {}  # guarded-by: state_lock
        #: guards handle_tenants and lazy orderer creation: the network
        #: front door offloads catchup/upload_summary to executor THREADS
        #: that mutate these maps concurrently with event-loop dispatches
        #: (ADVICE r3) — GIL atomicity alone is not a contract.
        self.state_lock = threading.RLock()

    def create_document(self, doc_id: str) -> DocumentEndpoint:
        with self.state_lock:
            if doc_id in self._orderers:
                raise ValueError(f"document {doc_id!r} already exists")
            self._orderers[doc_id] = DocumentOrderer(
                doc_id, self.oplog, self.storage, throttle=self.throttle
            )
            return DocumentEndpoint(self._orderers[doc_id])

    def has_document(self, doc_id: str) -> bool:
        with self.state_lock:  # executor threads mutate the map (ADVICE r4)
            known = doc_id in self._orderers
        return known or self.oplog.head(doc_id) > 0

    def endpoint(self, doc_id: str) -> DocumentEndpoint:
        """Connect-or-recover: an existing orderer is reused; a document
        present only in the durable log (service restart) is recovered by
        replaying the log into a fresh orderer."""
        with self.state_lock:
            orderer = self._orderers.get(doc_id)
        if orderer is None:
            if self.oplog.head(doc_id) == 0:
                raise KeyError(f"document {doc_id!r} does not exist")
            # Recover OUTSIDE the lock: a full log replay can take seconds
            # and the lock must stay a dict-operations-only lock.  Two
            # racing recoveries replay the same immutable log prefix; the
            # first insert wins.
            recovered = DocumentOrderer.recover(
                doc_id, self.oplog, self.storage
            )
            with self.state_lock:
                orderer = self._orderers.setdefault(doc_id, recovered)
        return DocumentEndpoint(orderer)

    def doc_ids(self) -> List[str]:
        with self.state_lock:
            known = set(self._orderers)
        return sorted(known | set(self.oplog.doc_ids()))

    def checkpoint(self) -> dict:
        with self.state_lock:
            snapshot = sorted(self._orderers.items())
        return {doc_id: orderer.checkpoint() for doc_id, orderer in snapshot}

    @staticmethod
    def restore(
        oplog: OpLog, storage: SummaryStorage, checkpoint: dict
    ) -> "LocalOrderingService":
        service = LocalOrderingService(oplog, storage)
        # Replay OUTSIDE the lock — state_lock is a dict-operations-only
        # lock (see endpoint()), and per-document restore is seconds of
        # work — then publish everything in one locked dict update.
        restored = {
            doc_id: DocumentOrderer.restore(
                doc_id, oplog, storage, doc_checkpoint
            )
            for doc_id, doc_checkpoint in checkpoint.items()
        }
        with service.state_lock:
            service._orderers.update(restored)
        return service
