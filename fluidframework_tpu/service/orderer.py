"""Per-document orderer and the multi-document front door.

Capability-equivalent of the reference's ``LocalOrderer`` (memory-orderer:
deli + scribe + scriptorium lambdas wired in one process) plus the Alfred
front door (document creation, per-client delta connections, signal fan-out)
— SURVEY.md §2.3/§3.5; upstream paths UNVERIFIED, empty reference mount.

The shape differs from Routerlicious deliberately: there is no Kafka hop —
the sequencer broadcast *is* the bus, and the durable :class:`OpLog` append
happens inside the broadcast (first subscriber), so the log is always at or
ahead of any client's view and strictly ahead of the checkpoint.  Crash
resume = restore checkpoint + replay the log tail into the sequencer/scribe
state (exactly-once: ``replay`` never re-stamps).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ..protocol.messages import (BatchAbortedError, RawOperation,
                                 SequencedMessage, ShardFencedError)
from ..protocol.sequencer import Sequencer
from ..protocol.summary import SummaryStorage
from ..protocol.wire import ColumnBatch, ColumnSegment, OpColumnSegment
from .oplog import OpLog
from .scribe import Scribe

SignalListener = Callable[[dict], None]


@dataclasses.dataclass
class SubmitOutcome:
    """Per-document result of a batched submit (``submit_many``).

    ``stamped`` holds the sequenced messages (duplicates dedup'd away);
    ``consumed`` counts ops fully handled (stamped OR dedup'd) — on
    success it equals the batch length.  ``error`` is the underlying
    failure when the batch stopped early (fence, injected append fault):
    ops ``[consumed:]`` were untouched, and the recovery contract is a
    whole-batch resubmit once the failure clears (dedup absorbs the
    stamped prefix).

    Columnar outcomes (``submit_columns``) leave ``stamped`` EMPTY and
    set ``stamped_count`` instead — the lazy-materialization contract:
    the stamped messages exist only as a column segment in the op log,
    so counting them must not box them.  Use :meth:`n_stamped` to count
    either shape."""

    stamped: List[SequencedMessage]
    consumed: int
    error: Optional[BaseException] = None
    stamped_count: Optional[int] = None

    def n_stamped(self) -> int:
        return (self.stamped_count if self.stamped_count is not None
                else len(self.stamped))


def submit_mixed_batches(service,
                         batches: Optional[Dict[str, List[RawOperation]]],
                         batch: Optional[ColumnBatch],
                         doc_rows: Optional[Dict[str, np.ndarray]],
                         endpoint_of=None) -> Dict[str, "SubmitOutcome"]:
    """THE batched-ingress loop, shared by both services and BOTH wire
    shapes: every document — boxed op lists from ``batches`` and
    :class:`ColumnBatch` row slices from ``doc_rows`` — in ONE globally
    sorted order, under ONE durable-log flush (group commit over the
    shared ``service.oplog``).  The single sorted interleaving is a
    parity requirement, not a style choice: occurrence-indexed fault
    schedules (the Nth ``oplog.append`` overall) must hit the same op
    whether a given document rode the boxed or the columnar shape this
    tick.  Failures are isolated per document — a fenced or faulted
    document reports its :class:`SubmitOutcome.error` while every other
    document's batch lands normally; the caller resubmits the failed
    documents' whole batches after recovery (dedup absorbs stamped
    prefixes).  Per-document sequencers make cross-document order
    irrelevant to the stamped bytes, so sorted-by-doc is both
    deterministic and sufficient.  ``endpoint_of`` overrides endpoint
    resolution (the sharded service passes its fence-refreshed
    assignment cache).  A document may appear in only ONE of the two
    shapes per call."""
    if endpoint_of is None:
        endpoint_of = service.endpoint
    batches = batches if batches is not None else {}
    doc_rows = doc_rows if doc_rows is not None else {}
    both = set(batches) & set(doc_rows)
    if both:
        raise ValueError(
            f"documents submitted in both shapes: {sorted(both)}")
    out: Dict[str, SubmitOutcome] = {}
    with service.oplog.batch():
        for doc_id in sorted(set(batches) | set(doc_rows)):
            try:
                endpoint = endpoint_of(doc_id)
                if doc_id in batches:
                    ops = batches[doc_id]
                    out[doc_id] = SubmitOutcome(
                        stamped=endpoint.submit_batch(ops),
                        consumed=len(ops))
                else:
                    rows = doc_rows[doc_id]
                    stamped = endpoint.submit_columns(batch, rows)
                    if isinstance(stamped, ColumnSegment):
                        out[doc_id] = SubmitOutcome(
                            stamped=[], consumed=int(rows.shape[0]),
                            stamped_count=len(stamped))
                    else:
                        out[doc_id] = SubmitOutcome(
                            stamped=stamped, consumed=int(rows.shape[0]))
            except BatchAbortedError as err:
                out[doc_id] = SubmitOutcome(
                    stamped=err.stamped, consumed=err.consumed,
                    error=err.cause)
            except (ConnectionError, OSError, KeyError) as err:
                # Fence fast-fail / unrecovered document: nothing of this
                # batch was consumed.
                out[doc_id] = SubmitOutcome(stamped=[], consumed=0,
                                            error=err)
    return out


def submit_batches(service, batches: Dict[str, List[RawOperation]]
                   ) -> Dict[str, "SubmitOutcome"]:
    """Boxed-only form of :func:`submit_mixed_batches` (``submit_many``)."""
    return submit_mixed_batches(service, batches, None, None)


def submit_column_batches(service, batch: ColumnBatch,
                          doc_rows: Dict[str, np.ndarray],
                          endpoint_of=None) -> Dict[str, "SubmitOutcome"]:
    """Columnar-only form of :func:`submit_mixed_batches`
    (``submit_columns``)."""
    return submit_mixed_batches(service, None, batch, doc_rows,
                                endpoint_of=endpoint_of)



#: bound for a recovery follower's wait on the leading replay (the same
#: crashed-leader discipline as CatchupResultCache.DEFAULT_JOIN_TIMEOUT:
#: a waiter must never hang forever on a leader that died mid-replay).
RECOVERY_JOIN_TIMEOUT = 60.0


class DocumentOrderer:
    """One document's service state: sequencer + scribe + durable log."""

    def __init__(
        self,
        doc_id: str,
        oplog: OpLog,
        storage: SummaryStorage,
        sequencer: Optional[Sequencer] = None,
        throttle=None,
    ) -> None:
        self.doc_id = doc_id
        self.oplog = oplog
        self.storage = storage
        self.sequencer = sequencer or Sequencer(throttle=throttle)
        #: fenced = this orderer's shard was marked dead and the document
        #: re-owned elsewhere.  The flag is checked by the durable-append
        #: subscriber below UNDER the fence lock, so ANY stamp attempt
        #: (submit, tick, scribe ack) aborts before the log — the
        #: log-append-before-broadcast invariant is what keeps sequencing
        #: from forking: a fenced orderer can advance its private counters
        #: but nothing it stamps becomes durable or visible.
        self._fence_lock = threading.Lock()
        self.fenced = False  # guarded-by: _fence_lock
        # Durable append rides first in the broadcast chain: by the time any
        # client sees a message it is already in the log (scriptorium-before-
        # broadcast, collapsing the reference's Kafka fan-out).
        self.sequencer.subscribe(self._durable_append)
        self.scribe = Scribe(doc_id, self.sequencer, storage)
        # Listener list is mutated by caller threads (server sessions
        # subscribe/unsubscribe) while fan-out iterates it; snapshot under
        # the lock, deliver outside it (the server's broadcaster is the
        # usual single listener — per-client fan-out happens there).
        self._signal_lock = threading.Lock()
        self._signal_listeners: List[SignalListener] = []  # guarded-by: _signal_lock
        #: the subscribers KNOWN passive for client OP columns (the
        #: durable gate handles columns in bulk; the scribe ignores OP) —
        #: precomputed once so the per-batch fast-path probe allocates
        #: no bound methods.
        self._op_passive_subscribers = (self._durable_append,
                                        self.scribe._on_message)

    def _durable_append(self, msg: SequencedMessage) -> None:
        # Check-and-append in ONE fence-lock critical section: a submit
        # that raced fence() either completes its append before the fence
        # is set (the failover replay then includes it) or observes the
        # fence and aborts — there is no window where a fenced orderer's
        # stamp lands in the log after the new owner started replaying.
        with self._fence_lock:
            if self.fenced:
                raise ShardFencedError(self.doc_id)
            self.oplog.append(self.doc_id, msg)

    def submit_batch(self, ops: List[RawOperation]
                     ) -> List[SequencedMessage]:
        """Batch stamping: the whole batch sequences through
        ``Sequencer.submit_many`` (one MSN recomputation); each message
        still rides the durable-append-first broadcast chain.  The
        one-flush-per-batch group commit lives one level up, in the
        services' ``submit_many`` (the flush is a property of the SHARED
        log, not of one document).  Raises :class:`BatchAbortedError` on
        a mid-batch failure."""
        return self.sequencer.submit_many(ops)

    def _append_columns(self, segment: ColumnSegment) -> None:
        # The columnar form of _durable_append: same one-critical-section
        # fence-check-and-append discipline, one bulk log call for the
        # whole stamped segment.
        with self._fence_lock:
            if self.fenced:
                raise ShardFencedError(self.doc_id)
            self.oplog.append_columns(self.doc_id, segment)

    def columnar_ready(self) -> bool:
        """True when client OP columns can stamp without materializing
        messages: the only subscribers are the durable gate and the
        scribe (a no-op for OP messages), and no throttle policy needs
        per-op consultation.  A live broadcast subscriber (a client
        session, the Broadcaster) makes the document materialize
        per-message — through the boxed path, which IS the
        materialization."""
        return (self.sequencer.throttle is None
                and not self.sequencer.has_subscribers_besides(
                    *self._op_passive_subscribers))

    def submit_columns(self, batch: ColumnBatch, rows: np.ndarray):
        """Columnar batch stamping for one document's row slice.

        Fast path: ``Sequencer.submit_columns`` (vectorized dedup/stamp,
        lazy segment, bulk durable append) — returns the
        :class:`OpColumnSegment`.  Documents with live broadcast
        subscribers, or slices the vectorized validator refuses, fall
        back to materialize + :meth:`submit_batch` — returning the boxed
        stamped list — so semantics (and bytes) never depend on which
        path ran."""
        if self.columnar_ready():
            segment = self.sequencer.submit_columns(
                batch, rows, self._append_columns)
            if segment is not None:
                return segment
        ops = [batch.materialize(int(i)) for i in rows.tolist()]
        return self.submit_batch(ops)

    def connect_columns(self, client_ids: List[str],
                        session: Optional[str] = None) -> None:
        """Columnar JOIN cohort (fresh clients): vectorized quorum insert
        + one lazy JOIN segment through the bulk durable gate; falls back
        to the boxed ``connect_many`` for resume/re-join semantics or
        documents with live broadcast subscribers."""
        if self.columnar_ready():
            if self.sequencer.connect_columns(client_ids, session,
                                              self._append_columns):
                return
        self.sequencer.connect_many(client_ids, session)

    def fence(self) -> None:
        """Mark this orderer dead (shard failover): every later stamp
        aborts before the durable log, so the re-owned orderer recovered
        from that log is the single continuation of the sequence.  Takes
        the fence lock — by the time this returns, any in-flight append
        has either landed (and is part of what the new owner replays) or
        will abort; the log is quiescent for this document."""
        with self._fence_lock:
            self.fenced = True

    # -- signals (unsequenced ephemeral broadcast — presence rides this) -------

    def submit_signal(self, client_id: str, content,
                      target_client_id: Optional[str] = None) -> None:
        with self._fence_lock:
            fenced = self.fenced
        if fenced:
            return  # signals are ephemeral: a dead shard's are dropped
        signal = {
            "clientId": client_id,
            "content": content,
            "targetClientId": target_client_id,
        }
        with self._signal_lock:
            listeners = list(self._signal_listeners)
        for fn in listeners:
            fn(signal)

    def subscribe_signals(self, fn: SignalListener) -> None:
        with self._signal_lock:
            self._signal_listeners.append(fn)

    def unsubscribe_signals(self, fn: SignalListener) -> None:
        with self._signal_lock:
            if fn in self._signal_listeners:
                self._signal_listeners.remove(fn)

    # -- checkpoint / crash-resume ---------------------------------------------

    def checkpoint(self) -> dict:
        return {
            "sequencer": self.sequencer.checkpoint(),
            "scribe": self.scribe.checkpoint(),
        }

    @staticmethod
    def restore(
        doc_id: str,
        oplog: OpLog,
        storage: SummaryStorage,
        checkpoint: dict,
    ) -> "DocumentOrderer":
        """Resume after a crash: the checkpoint may lag the durable log;
        the tail is replayed into sequencer + scribe state exactly-once.

        Clients that died with the process remain in the quorum (their
        dedup floors must survive for reconnect); the host is responsible
        for ``disconnect``-ing ones that never return, or the MSN stays
        pinned at their last ref_seq."""
        checkpoint_seq = checkpoint["sequencer"]["seq"]
        floor = oplog.floor(doc_id)
        if checkpoint_seq < floor:
            # The checkpoint predates a truncation cut: the log can no
            # longer back-fill it.  The truncation marker carries a
            # checkpoint taken at the cut — restore from that instead
            # (absent one, the ranged read below raises loudly rather
            # than silently resuming over a gap).
            trunc = oplog.truncation_checkpoint(doc_id)
            if trunc is not None:
                checkpoint = trunc
                checkpoint_seq = checkpoint["sequencer"]["seq"]
        from_seq = floor if floor <= checkpoint_seq else 0
        sequencer = Sequencer.restore(
            checkpoint["sequencer"],
            log=oplog.get(doc_id, from_seq=from_seq,
                          to_seq=checkpoint_seq),
        )
        orderer = DocumentOrderer(doc_id, oplog, storage, sequencer=sequencer)
        orderer.scribe.restore(checkpoint["scribe"])
        for msg in oplog.get(doc_id, from_seq=checkpoint_seq):
            sequencer.replay(msg)
            orderer.scribe.replay(msg)
        return orderer

    @staticmethod
    def recover(
        doc_id: str, oplog: OpLog, storage: SummaryStorage
    ) -> "DocumentOrderer":
        """No host checkpoint at all: rebuild everything from the durable
        log.  A TRUNCATED log cannot replay from seq 1 — its sealed
        prefix is gone — so recovery pivots to the checkpoint the
        truncation marker persisted at the cut (restore + tail replay),
        which carries the JOIN/LEAVE quorum and dedup floors the dropped
        records once established."""
        trunc = oplog.truncation_checkpoint(doc_id)
        if trunc is not None:
            return DocumentOrderer.restore(doc_id, oplog, storage, trunc)
        orderer = DocumentOrderer(doc_id, oplog, storage)
        for msg in oplog.get(doc_id):
            orderer.sequencer.replay(msg)
            orderer.scribe.replay(msg)
        return orderer


class DocumentEndpoint:
    """A per-document connection facade handed to clients/drivers.

    Satisfies the ``ContainerRuntime.connect`` contract — ``submit``,
    ``subscribe``, ``connect``, ``log`` — plus signals and ranged delta
    reads, so the same runtime code runs against the in-proc sequencer,
    this service, or a remote driver.
    """

    def __init__(self, orderer: DocumentOrderer) -> None:
        self._orderer = orderer

    @property
    def doc_id(self) -> str:
        return self._orderer.doc_id

    @property
    def log(self) -> List[SequencedMessage]:
        return self._orderer.oplog.get(self._orderer.doc_id)

    # The endpoint-level fence checks below are advisory fast-fails for
    # clean errors; they read the flag without the fence lock.  The
    # AUTHORITATIVE gate is DocumentOrderer._durable_append, which
    # re-checks under the lock — a submit that slips past an endpoint
    # check mid-kill still aborts before the durable log.

    @property
    def head_seq(self) -> int:
        if self._orderer.fenced:
            # A dead shard's counter is stale the moment the re-owned
            # orderer stamps: refuse rather than serve a head the durable
            # log has moved past.
            raise ShardFencedError(self.doc_id)
        return self._orderer.sequencer.seq

    def connect(self, client_id: str, session: Optional[str] = None) -> None:
        if self._orderer.fenced:
            raise ShardFencedError(self.doc_id)
        self._orderer.sequencer.connect(client_id, session)

    def disconnect(self, client_id: str) -> None:
        if self._orderer.fenced:
            # Leaving a dead shard needs no LEAVE: the recovered owner's
            # quorum governs now, and a fenced orderer could not make the
            # LEAVE durable anyway.  No-op so reconnect teardown of the
            # stale connection never trips over the fence.
            return
        self._orderer.sequencer.disconnect(client_id)

    def submit(self, op: RawOperation) -> Optional[SequencedMessage]:
        if self._orderer.fenced:
            raise ShardFencedError(self.doc_id)
        return self._orderer.sequencer.submit(op)

    def submit_batch(self, ops: List[RawOperation]
                     ) -> List[SequencedMessage]:
        if self._orderer.fenced:
            raise ShardFencedError(self.doc_id)
        return self._orderer.submit_batch(ops)

    def connect_many(self, client_ids: List[str],
                     session: Optional[str] = None) -> None:
        if self._orderer.fenced:
            raise ShardFencedError(self.doc_id)
        self._orderer.sequencer.connect_many(client_ids, session)

    def submit_columns(self, batch: ColumnBatch, rows: np.ndarray):
        if self._orderer.fenced:
            raise ShardFencedError(self.doc_id)
        return self._orderer.submit_columns(batch, rows)

    def connect_columns(self, client_ids: List[str],
                        session: Optional[str] = None) -> None:
        if self._orderer.fenced:
            raise ShardFencedError(self.doc_id)
        self._orderer.connect_columns(client_ids, session)

    def subscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        self._orderer.sequencer.subscribe(fn)

    def unsubscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        self._orderer.sequencer.unsubscribe(fn)

    def update_ref_seq(self, client_id: str, ref_seq: int) -> None:
        if self._orderer.fenced:
            return  # heartbeat to a dead shard: the new owner tracks MSN
        self._orderer.sequencer.update_ref_seq(client_id, ref_seq)

    def deltas(self, from_seq: int = 0,
               to_seq: Optional[int] = None) -> List[SequencedMessage]:
        return self._orderer.oplog.get(self._orderer.doc_id, from_seq, to_seq)

    def submit_signal(self, client_id: str, content,
                      target_client_id: Optional[str] = None) -> None:
        self._orderer.submit_signal(client_id, content, target_client_id)

    def subscribe_signals(self, fn: SignalListener) -> None:
        self._orderer.subscribe_signals(fn)

    def unsubscribe_signals(self, fn: SignalListener) -> None:
        self._orderer.unsubscribe_signals(fn)


class _RecoveryFlight:
    """One in-flight log replay: the leader publishes the recovered
    orderer into ``_orderers``; waiters block on the event and re-claim
    (the single-flight begin/publish/abandon shape of catchup_cache)."""

    def __init__(self) -> None:
        self.done = threading.Event()


class LocalOrderingService:
    """Multi-document ordering service in one process — the Tinylicious
    capability point: create/load documents, connect clients, store
    summaries, serve catch-up deltas."""

    def __init__(
        self,
        oplog: Optional[OpLog] = None,
        storage: Optional[SummaryStorage] = None,
        throttle=None,
    ) -> None:
        self.oplog = oplog if oplog is not None else OpLog()
        self.storage = storage if storage is not None else SummaryStorage()
        #: optional per-submit throttle policy handed to every document's
        #: sequencer: callable(client_id) -> retry-after seconds | None.
        self.throttle = throttle
        #: summary-node digest -> {tenant ids allowed to read it}.  Lives on
        #: the SHARED service (not a front-door instance) so multi-instance
        #: deployments agree; content-addressed nodes can be owned by many
        #: tenants at once.  A production store would prune these with
        #: summary eviction; entries are per-node and tiny.
        self.handle_tenants: Dict[str, set] = {}  # guarded-by: state_lock
        self._orderers: Dict[str, DocumentOrderer] = {}  # guarded-by: state_lock
        #: doc_id -> in-flight recovery; a herd of connects to a document
        #: present only in the durable log costs ONE replay (the same
        #: single-flight discipline as the catch-up cache).
        self._recoveries: Dict[str, _RecoveryFlight] = {}  # guarded-by: state_lock
        #: shard-level fence (set by ShardedOrderingService.kill_shard via
        #: fence_all): once set, no NEW orderer can be created or
        #: published unfenced — closes the window where a single-flight
        #: recovery in flight at kill time would install a live orderer
        #: on a dead-routed shard after the per-orderer fence sweep ran.
        self._fenced = False  # guarded-by: state_lock
        #: guards handle_tenants and lazy orderer creation: the network
        #: front door offloads catchup/upload_summary to executor THREADS
        #: that mutate these maps concurrently with event-loop dispatches
        #: (ADVICE r3) — GIL atomicity alone is not a contract.
        self.state_lock = threading.RLock()
        #: optional ``fn(doc_id, head_seq)`` fired after every committed
        #: stamp/segment on any document (the streaming fold's dirty-doc
        #: feed).  Installed via :meth:`set_commit_hook`; rides the
        #: sequencer's WATCHER list, never its subscriber list, so it
        #: cannot knock documents off the columnar fast path.
        self.commit_hook = None  # guarded-by: state_lock (installation)

    def fence_all(self) -> List[str]:
        """Shard failover: refuse new orderers, then fence every live one.
        The flag flips under state_lock FIRST, so a racing recovery either
        published before this (its orderer is in the sweep snapshot) or
        publishes after (and is born fenced in _recover_publish) — there
        is no interleaving that leaves a live orderer on a dead shard.
        Returns the fenced doc ids."""
        with self.state_lock:
            self._fenced = True
            orderers = sorted(self._orderers.items())
        for _doc_id, orderer in orderers:
            orderer.fence()
        return [doc_id for doc_id, _ in orderers]

    def set_commit_hook(self, fn) -> None:
        """Install (or clear) the service-wide commit hook and wire it
        onto every LIVE orderer; later-created/recovered/adopted orderers
        are wired at install time.  One hook at a time — the streaming
        fold is the intended single consumer."""
        with self.state_lock:
            self.commit_hook = fn
            orderers = sorted(self._orderers.items())
        if fn is not None:
            for doc_id, orderer in orderers:
                self._wire_commit_hook(doc_id, orderer)

    def _wire_commit_hook(self, doc_id: str,
                          orderer: DocumentOrderer) -> None:
        # The watcher reads ``self.commit_hook`` at FIRE time (not wire
        # time) so clearing the hook actually detaches delivery, and it
        # is wired at most once per orderer so attach/detach/attach does
        # not fan a single commit out twice.
        with self.state_lock:
            armed = self.commit_hook is not None
        if not armed:
            return
        if getattr(orderer, "_commit_hook_wired", False):
            return
        orderer._commit_hook_wired = True
        orderer.sequencer.watch_commits(
            lambda head, _d=doc_id: self._fire_commit_hook(_d, head))

    def _fire_commit_hook(self, doc_id: str, head: int) -> None:
        with self.state_lock:  # snapshot only; fn runs lock-free
            fn = self.commit_hook
        if fn is not None:
            fn(doc_id, head)

    def create_document(self, doc_id: str) -> DocumentEndpoint:
        with self.state_lock:
            if self._fenced:
                raise ShardFencedError(doc_id)
            if doc_id in self._orderers:
                raise ValueError(f"document {doc_id!r} already exists")
            self._orderers[doc_id] = DocumentOrderer(
                doc_id, self.oplog, self.storage, throttle=self.throttle
            )
            self._wire_commit_hook(doc_id, self._orderers[doc_id])
            return DocumentEndpoint(self._orderers[doc_id])

    def has_document(self, doc_id: str) -> bool:
        with self.state_lock:  # executor threads mutate the map (ADVICE r4)
            known = doc_id in self._orderers
        return known or self.oplog.head(doc_id) > 0

    # -- single-flight recovery (begin/publish/abandon, catchup_cache shape) ---

    def _recover_begin(self, doc_id: str):
        """One atomic claim: ``("have", orderer)`` when live,
        ``("lead", flight)`` when this caller must replay the log, or
        ``("wait", flight)`` when another caller already is."""
        with self.state_lock:
            orderer = self._orderers.get(doc_id)
            if orderer is not None:
                return "have", orderer
            flight = self._recoveries.get(doc_id)
            if flight is not None:
                return "wait", flight
            flight = _RecoveryFlight()
            self._recoveries[doc_id] = flight
            return "lead", flight

    def _recover_publish(self, doc_id: str,
                         orderer: DocumentOrderer) -> DocumentOrderer:
        """Leader succeeded: install the orderer, wake every waiter.  The
        install re-validates via setdefault — if create_document landed in
        the replay window, its orderer wins and the replay is discarded.
        A shard fenced mid-replay installs the orderer FENCED: waiters get
        clean ShardFencedErrors and re-resolve through the router instead
        of sequencing on a dead shard."""
        with self.state_lock:
            fenced = self._fenced
            installed = self._orderers.setdefault(doc_id, orderer)
        if installed is orderer:
            self._wire_commit_hook(doc_id, installed)
        with self.state_lock:
            flight = self._recoveries.pop(doc_id, None)
        if fenced:
            installed.fence()
        if flight is not None:
            flight.done.set()
        return installed

    def _recover_abandon(self, doc_id: str) -> None:
        """Leader failed: wake waiters empty-handed (one re-claims and
        replays itself).  Safe on an already-published key."""
        with self.state_lock:
            flight = self._recoveries.pop(doc_id, None)
        if flight is not None:
            flight.done.set()

    def _recover_reap(self, doc_id: str, flight: _RecoveryFlight) -> None:
        """A waiter timed out: presume the leader crashed without reaching
        its finally-abandon and remove the flight — only if it is still
        the identical object this waiter waited on, so a fresh leader's
        flight is never popped (the identity-guard discipline of
        CatchupResultCache.join)."""
        with self.state_lock:
            if self._recoveries.get(doc_id) is flight:
                self._recoveries.pop(doc_id)
                flight.done.set()

    def endpoint(self, doc_id: str) -> DocumentEndpoint:
        """Connect-or-recover: an existing orderer is reused; a document
        present only in the durable log (service restart, shard failover)
        is recovered by replaying the log into a fresh orderer.  A herd of
        concurrent connects costs ONE replay: the first caller leads and
        replays outside the lock (seconds of work; state_lock stays a
        dict-operations-only lock), everyone else waits on the flight and
        re-claims once it resolves."""
        while True:
            state, val = self._recover_begin(doc_id)
            if state == "have":
                return DocumentEndpoint(val)
            if state == "lead":
                try:
                    if self.oplog.head(doc_id) == 0:
                        raise KeyError(f"document {doc_id!r} does not exist")
                    recovered = DocumentOrderer.recover(
                        doc_id, self.oplog, self.storage
                    )
                except BaseException:
                    self._recover_abandon(doc_id)
                    raise
                return DocumentEndpoint(
                    self._recover_publish(doc_id, recovered)
                )
            # wait: bounded — a leader that died without its
            # finally-abandon must not hang followers forever; on timeout
            # reap the dead flight (identity-guarded) and re-claim.
            if not val.done.wait(RECOVERY_JOIN_TIMEOUT):
                self._recover_reap(doc_id, val)

    def adopt_orderer(self, doc_id: str,
                      orderer: DocumentOrderer) -> DocumentOrderer:
        """Install an orderer built elsewhere (fluidproc migration: the
        target shard restores the source's frozen checkpoint so quorum
        state and dedup floors continue exactly).  Loses to an existing
        orderer (``setdefault`` — a concurrent lazy recovery's result is
        equivalent: both continue the same durable log); born fenced when
        the shard itself is."""
        with self.state_lock:
            fenced = self._fenced
            installed = self._orderers.setdefault(doc_id, orderer)
        if installed is orderer:
            self._wire_commit_hook(doc_id, installed)
        if fenced:
            installed.fence()
        return installed

    def drop_orderer(self, doc_id: str) -> None:
        """Forget a document's in-memory orderer (migration-abort thaw:
        a frozen/fenced orderer is discarded so the next ``endpoint()``
        lazily recovers a LIVE one from this shard's own durable log —
        quorum and dedup floors rebuild from the replay)."""
        with self.state_lock:
            self._orderers.pop(doc_id, None)

    def submit_many(self, batches: Dict[str, List[RawOperation]]
                    ) -> Dict[str, SubmitOutcome]:
        """Batched ingress — see :func:`submit_batches` (the swarm-scale
        submit surface: per-document batch stamping, one durable flush,
        per-document failure isolation)."""
        return submit_batches(self, batches)

    def submit_columns(self, batch: ColumnBatch,
                       doc_rows: Dict[str, np.ndarray]
                       ) -> Dict[str, SubmitOutcome]:
        """Columnar batched ingress — see :func:`submit_column_batches`."""
        return submit_column_batches(self, batch, doc_rows)

    def submit_mixed(self, batches: Optional[Dict[str, List[RawOperation]]],
                     batch: Optional[ColumnBatch],
                     doc_rows: Optional[Dict[str, np.ndarray]]
                     ) -> Dict[str, SubmitOutcome]:
        """Both ingress shapes in one sorted pass — see
        :func:`submit_mixed_batches`."""
        return submit_mixed_batches(self, batches, batch, doc_rows)

    def doc_ids(self) -> List[str]:
        with self.state_lock:
            known = set(self._orderers)
        return sorted(known | set(self.oplog.doc_ids()))

    def checkpoint(self) -> dict:
        with self.state_lock:
            snapshot = sorted(self._orderers.items())
        return {doc_id: orderer.checkpoint() for doc_id, orderer in snapshot}

    @staticmethod
    def restore(
        oplog: OpLog, storage: SummaryStorage, checkpoint: dict
    ) -> "LocalOrderingService":
        service = LocalOrderingService(oplog, storage)
        # Replay OUTSIDE the lock — state_lock is a dict-operations-only
        # lock (see endpoint()), and per-document restore is seconds of
        # work — then publish everything in one locked dict update.
        restored = {
            doc_id: DocumentOrderer.restore(
                doc_id, oplog, storage, doc_checkpoint
            )
            for doc_id, doc_checkpoint in checkpoint.items()
        }
        with service.state_lock:
            service._orderers.update(restored)
        return service
