"""Sequencer-attached streaming fold (ISSUE 16): incremental
summarization that rides the COMMIT stream instead of waiting for
catch-up traffic.

The bulk catch-up path (ISSUE 3/6/13) is demand-driven: the first client
asking for a document pays pack → fold → extract for the whole tail
since the last summary.  Under a catch-up storm that demand arrives all
at once — PR 15 bounds the damage with admission, but the cold folds are
still there (22 of them in ``BENCH_catchup_storm_cpu_r15.json``).  This
service removes the demand spike at its source: every committed
micro-batch is folded SHORTLY AFTER it commits, the folded device state
stays PINNED in the tier-2.5 resident-state tier (suffix packs splice
onto device-resident base chunks — no re-upload, no re-fold of history),
and the resulting summaries are continuously published through the
store's idempotent ``upload_absent`` election.  A catch-up then finds a
summary at most one fold cadence behind the durable head and serves it
from the STREAMING HEAD lane — ``(handle, ref_seq)`` plus a bounded tail
the client replays itself (the summary + tail reference contract) — with
no fold, no admission, no device work.

Attachment — watchers, not subscribers: the sequencer's commit feed for
this service is the :meth:`~..protocol.sequencer.Sequencer.watch_commits`
list, which is deliberately INVISIBLE to ``has_subscribers_besides`` —
riding the ordinary subscriber list would force every columnar submit
through the boxing path (``columnar_ready`` would see a third
subscriber) and quietly destroy the zero-boxing pipeline this repo
exists to measure.  The hook itself only RECORDS the new head under the
service lock; all folding happens in :meth:`poll`, which the owner calls
at its own cadence (the server after each submit batch, the swarm once
per virtual tick).  Nothing here reads a wall clock: cadence is measured
in sequence numbers, so replay runs fold at identical points.

Summary-anchored truncation: once a summary at ``ref_seq`` is durable,
oplog records at or below ``min(ref_seq, MSN, head − retention_floor)``
can never be needed again — catch-up serves the summary, gap repair
starts strictly above the summary's ref_seq (``from_seq == floor`` is
the legal boundary), and in-flight submits referencing below MSN are
already nacked ``staleView``.  :meth:`poll` advances the oplog floor to
that cut after each publish, carrying the orderer checkpoint in the
truncation marker so a crashed process can still
:meth:`~.orderer.DocumentOrderer.recover` a log whose prefix is gone.

Degradation contract (chaos seam): a stalled streaming fold
(``stream.stall``) skips whole poll rounds — summaries age past
``stream_lag``, and catch-ups simply fall back to the existing cold-fold
path, byte-identical, with the downgrade visible in the counters.  A
``stream.crash`` aborts one poll round mid-selection; the unprocessed
documents stay pending and fold on the next round.  Streaming on vs. off
must converge byte-identically — the fold path is the SAME
``CatchupService`` fold either way, just invoked earlier and with
``pin_resident=True``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from .catchup_cache import StreamHeadIndex

__all__ = ["StreamFoldService", "DEFAULT_CADENCE_OPS",
           "DEFAULT_RETENTION_FLOOR"]

#: fold once a document has this many committed-but-unfolded ops.  Small
#: enough that the streaming-head lane (`head - ref_seq <= cadence`)
#: covers a herd join, large enough that the per-fold fixed cost (dispatch
#: + extract) amortizes over a real micro-batch.
DEFAULT_CADENCE_OPS = 8

#: never truncate the newest N ops even when a summary covers them: a
#: live client repairing a gap close to the head must find the records,
#: and keeping a bounded hot tail makes the truncated log self-serving
#: for every `deltas()` read pattern the tests exercise.
DEFAULT_RETENTION_FLOOR = 64


class StreamFoldService:
    """Commit-driven incremental summarizer over one ordering service.

    Owns no device state itself: folding is delegated to the existing
    :class:`~.catchup.CatchupService` (same kernels, same caches, same
    byte-identical results), with ``pin_resident=True`` so each fold's
    device chunks stay pinned in the tier-2.5 resident-state tier for
    the NEXT micro-batch to splice onto.

    Counters (all under ``_lock``): ``polls`` (rounds entered),
    ``folds`` (rounds that folded at least one doc), ``docs_folded``,
    ``ops_folded`` (sequence numbers advanced past), ``publishes``
    (index publications), ``stalls`` (rounds skipped by
    ``stream.stall``), ``crashes`` (rounds aborted mid-selection by
    ``stream.crash``), ``truncations`` (oplog cuts that dropped
    records), ``truncated_msgs`` (records those cuts dropped).
    """

    def __init__(self, service, catchup, *,
                 cadence_ops: int = DEFAULT_CADENCE_OPS,
                 retention_floor: int = DEFAULT_RETENTION_FLOOR,
                 truncate: bool = True,
                 faults=None,
                 head_index: Optional[StreamHeadIndex] = None) -> None:
        if cadence_ops < 1:
            raise ValueError("cadence_ops must be >= 1")
        if retention_floor < 0:
            raise ValueError("retention_floor must be >= 0")
        self.service = service
        self.catchup = catchup
        self.cadence_ops = int(cadence_ops)
        self.retention_floor = int(retention_floor)
        self.truncate_enabled = bool(truncate)
        self._faults = faults
        self.head_index = head_index if head_index is not None \
            else StreamHeadIndex()
        self._lock = threading.Lock()
        self._pending: Dict[str, int] = {}  # doc -> committed head  guarded-by: _lock
        self._folded: Dict[str, int] = {}  # doc -> head at last fold  guarded-by: _lock
        self._attached = False  # guarded-by: _lock
        self.counters: Dict[str, int] = {
            "polls": 0, "folds": 0, "docs_folded": 0, "ops_folded": 0,
            "publishes": 0, "stalls": 0, "crashes": 0,
            "truncations": 0, "truncated_msgs": 0,
        }  # guarded-by: _lock

    # -- attachment ------------------------------------------------------------

    def attach(self) -> "StreamFoldService":
        """Install the commit hook on the ordering service (idempotent).
        Every already-live orderer and every later-created one feeds
        :meth:`_on_commit` from its sequencer's watcher list."""
        with self._lock:
            if self._attached:
                return self
            self._attached = True
        self.service.set_commit_hook(self._on_commit)
        return self

    def detach(self) -> None:
        with self._lock:
            if not self._attached:
                return
            self._attached = False
        self.service.set_commit_hook(None)

    def _on_commit(self, doc_id: str, head_seq: int) -> None:
        """Sequencer commit watcher: RECORD ONLY.  Runs inside the
        stamping path (possibly inside an open ``oplog.batch()``), so it
        must not fold, flush, or touch the device — it just remembers
        the newest committed head for :meth:`poll` to pick up."""
        with self._lock:
            prev = self._pending.get(doc_id, 0)
            if head_seq > prev:
                self._pending[doc_id] = int(head_seq)

    # -- the poll loop ---------------------------------------------------------

    def note_doc(self, doc_id: str) -> None:
        """Seed a document into the pending map from its durable head
        (used when attaching to a service with pre-existing history —
        the commit hook only sees commits made AFTER attachment)."""
        head = self.service.oplog.head(doc_id)
        if head > 0:
            self._on_commit(doc_id, head)

    def due(self, force: bool = False) -> List[str]:
        """Documents whose unfolded span reached the cadence (all
        pending docs when ``force``), in sorted order (determinism)."""
        with self._lock:
            return sorted(
                d for d, head in self._pending.items()
                if head > self._folded.get(d, 0)
                and (force
                     or head - self._folded.get(d, 0) >= self.cadence_ops)
            )

    def poll(self, force: bool = False) -> Dict[str, Tuple[str, int]]:
        """One streaming round: fold every due document's committed
        micro-batch, publish the summaries, advance the truncation
        floor.  Returns ``{doc_id: (handle, ref_seq)}`` for the folded
        documents.  MUST run outside any open ``oplog.batch()`` — the
        truncation marker's durability commit point is a flush.
        """
        with self._lock:
            self.counters["polls"] += 1
        fault = (self._faults.fire("stream.stall")
                 if self._faults is not None else None)
        if fault is not None:
            # Stalled round: fold nothing.  Lag grows past stream_lag
            # and catch-ups degrade to the cold-fold path — the
            # downgrade the counters (and the chaos verdict) look for.
            with self._lock:
                self.counters["stalls"] += 1
            return {}
        due = self.due(force=force)
        batch: List[str] = []
        crashed = False
        for doc_id in due:
            fault = (self._faults.fire("stream.crash", doc=doc_id)
                     if self._faults is not None else None)
            if fault is not None:
                # The round dies mid-selection: docs already selected
                # fold below; this doc and the rest stay pending and
                # fold next round.  The service survives (swallow +
                # count) — only the ROUND crashed, not the process.
                crashed = True
                break
            batch.append(doc_id)
        if crashed:
            with self._lock:
                self.counters["crashes"] += 1
        if not batch:
            return {}
        # Observe lag BEFORE folding: the honest "how far behind is the
        # newest durable summary" number the lag gate bounds by cadence.
        with self._lock:
            heads = {d: self._pending[d] for d in batch}
        for doc_id, head in heads.items():
            self.head_index.observe_lag(doc_id, head)
        # The SAME fold the demand path runs — byte-identical by
        # construction — pinned device-resident for the next splice.
        results = self.catchup.catch_up(batch, upload=True,
                                        pin_resident=True)
        epoch = self.service.storage.epoch
        folded_docs = 0
        folded_ops = 0
        with self._lock:
            for doc_id, (_handle, ref_seq) in results.items():
                prev = self._folded.get(doc_id, 0)
                if ref_seq > prev:
                    folded_ops += ref_seq - prev
                    self._folded[doc_id] = int(ref_seq)
                folded_docs += 1
            self.counters["docs_folded"] += folded_docs
            self.counters["ops_folded"] += folded_ops
            if folded_docs:
                self.counters["folds"] += 1
        for doc_id, (handle, ref_seq) in sorted(results.items()):
            if self.head_index.publish(doc_id, handle, ref_seq, epoch):
                with self._lock:
                    self.counters["publishes"] += 1
            if self.truncate_enabled:
                self._truncate_below_summary(doc_id, ref_seq)
        return results

    # -- summary-anchored truncation -------------------------------------------

    def _truncate_below_summary(self, doc_id: str, ref_seq: int) -> int:
        """Advance the oplog floor to ``min(newest durable summary
        ref_seq, MSN, head − retention_floor)``.  Every term is a
        CANNOT-BE-NEEDED bound: the summary serves everything at or
        below its ref_seq; a submit referencing below MSN is already
        nacked ``staleView`` (so no live client can gap-repair below
        it); the retention floor keeps a hot tail for near-head repairs
        regardless.  The orderer checkpoint rides the truncation marker
        so crash recovery never needs the dropped prefix."""
        oplog = self.service.oplog
        head = oplog.head(doc_id)
        # A sharded service keeps orderers per shard — the MSN/checkpoint
        # source is the owning shard's LocalOrderingService either way.
        owner = getattr(self.service, "_owner", None)
        svc = owner(doc_id) if callable(owner) else self.service
        with svc.state_lock:
            orderer = svc._orderers.get(doc_id)
            if orderer is None:
                # No live orderer → no checkpoint to anchor recovery on;
                # leave the log whole (the next poll after recovery cuts).
                return 0
            msn = orderer.sequencer.min_seq
            cut = min(int(ref_seq), int(msn),
                      int(head) - self.retention_floor)
            if cut <= oplog.floor(doc_id):
                return 0
            checkpoint = orderer.checkpoint()
        dropped = oplog.truncate(doc_id, cut, checkpoint=checkpoint)
        if dropped:
            with self._lock:
                self.counters["truncations"] += 1
                self.counters["truncated_msgs"] += dropped
        return dropped

    # -- introspection ---------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counters)
            out["pending_docs"] = sum(
                1 for d, head in self._pending.items()
                if head > self._folded.get(d, 0))
        for key, value in self.head_index.stats().items():
            out[f"head_{key}"] = value
        # The log's own compaction counter: bytes physically dropped by
        # this service's truncations (the honest before/after-truncation
        # size delta — markers and rewrites already netted out).
        out["oplog_bytes_reclaimed"] = int(
            getattr(self.service.oplog, "bytes_reclaimed", 0))
        return out
