"""fluidproc front door: routing, supervision, failover, live migration.

The Alfred-shaped entry point of the out-of-process tier (ISSUE 12): one
process that owns the :class:`~.sharding.ShardRouter`, supervises a fleet
of :mod:`~.shardhost` **processes** (spawn, heartbeat, death detection),
and speaks the existing client frame protocol — so
``NetworkDocumentServiceFactory`` and the Loader run against it
unchanged.  Every document-scoped request is proxied to the owning shard
over a per-shard RPC connection; broadcast events are relayed
serialize-once (one ``frame_bytes`` per event for all subscribed client
sessions).

Connection layer (ISSUE 18): a single-threaded :mod:`~.framepump`
event loop owns every client socket — accept, reads, and budget-aware
writes — and decoded frames dispatch to a small worker pool (responses
match by ``re`` id, so per-connection pipelining is safe).  Connection
count is a benchmarked axis (``tools/loadgen.py --connections``), not a
thread-count ceiling.  N doors can front one shard fleet: replicas run
``spawn="attach"`` against the primary's ``shard_addrs()`` and agree on
placement purely through the deterministic rendezvous router — shared
assignment state is ZERO, and each replica taps shard broadcasts over
its own RPC connection.

Control plane (all topology mutations run on ONE supervisor thread — the
actor discipline that keeps failover and migration serialized without
holding a lock across an RPC round-trip):

- **Failover** (``proc.kill`` faults, heartbeat death detection, or a
  transport error observed by a proxy thread): the victim process is
  SIGKILLed first — *process death is the fence*; a merely-hung process
  must not wake up and extend a log whose documents were re-owned — then
  the router marks it dead, every surviving shard adopts the
  deterministically-derived fence epoch, and the dead shard's documents
  re-own by **adoption**: the new owner imports the document's span from
  the dead shard's on-disk log (read-only view) into its OWN log and
  recovers the orderer by replay.  Documents with live subscriptions
  adopt eagerly (broadcast channels re-wired, ``fence`` events pushed);
  the rest adopt lazily on next touch — failover is O(live
  subscriptions), exactly the in-proc tier's bar.
- **Live migration** (``add_shard``): per document — ``freeze`` on the
  source (fence + seal + checkpoint at the frozen head), ``transfer``
  (export the log span; the summary store is shared and content-
  addressed, so only the handle is named), ``import`` on the target
  (idempotent span append + checkpoint restore, so quorum state and
  dedup floors continue exactly), ``flip`` (the front door's per-doc
  override — rendezvous takes over when the shard finally joins the
  router), ``resume`` (re-wire broadcast, retire the source copy).  A
  crash at ANY step converges: source death falls back to failover +
  re-try, target death aborts with a ``thaw`` (the document never left),
  and the import's idempotence absorbs unknown-outcome retries.

See SEMANTICS.md "Deployment & migration" for the exact guarantees (and
non-guarantees: heartbeat detection cannot distinguish slow from dead —
the SIGKILL-before-adopt rule is what makes the distinction irrelevant).
"""

from __future__ import annotations

import os
import queue
import signal as _signal
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from ..drivers.network_driver import (RpcError, RpcTimeoutError,
                                      RpcTransportError, _RpcClient)
from ..protocol.messages import (DocRelocatedError, NackError,
                                 ShardFencedError)
from ..protocol.wire import (WIRE_VERSION, decode_column_batch,
                             encode_column_batch, frame_bytes)
from ..utils.telemetry import LockedCounterSet, MonitoringContext
from .framepump import FramePump, PumpConnection
from .sharding import ShardRouter, fence_token, rendezvous_score

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: transport-shaped failures from a shard RPC: the shard may be dead
#: (check it), the request may or may not have landed (retries dedup).
_TRANSPORT_ERRORS = (RpcTransportError, RpcTimeoutError, OSError)


class MigrationAborted(RuntimeError):
    """``add_shard`` could not complete (the target died mid-migration):
    every frozen document was thawed back to its source — the tier is
    exactly as it was, minus the dead would-be shard."""


class _Job:
    """One unit of supervisor work (the control-plane actor queue).
    ``fire_and_forget`` marks jobs with no waiter (heartbeat posts): their
    failure must surface through telemetry, or it vanishes entirely."""

    def __init__(self, fn: Callable[[], object],
                 fire_and_forget: bool = False) -> None:
        self.fn = fn
        self.fire_and_forget = fire_and_forget
        self.done = threading.Event()
        self.result: object = None
        self.error: Optional[BaseException] = None


class ShardHandle:
    """Supervision view of one shard server: RPC + liveness + signals."""

    def __init__(self, shard_id: str) -> None:
        self.shard_id = shard_id
        self.addr: Tuple[str, int] = ("", 0)
        self.rpc: Optional[_RpcClient] = None
        #: the shard process pid (from ``shard_info``): what lets a
        #: NON-owning front door (a replica attached to another door's
        #: shards) still honor SIGKILL-is-the-fence on failover.
        self.pid: Optional[int] = None

    def connect(self, mc=None, timeout: float = 30.0) -> None:
        self.rpc = _RpcClient(self.addr[0], self.addr[1], timeout=timeout,
                              mc=mc)

    def ping(self, timeout: float = 2.0) -> bool:
        if self.rpc is None:
            return False
        try:
            return self.rpc.request("ping", {}, timeout=timeout) == "pong"
        except (RpcError, OSError, ConnectionError):
            return False

    def request(self, method: str, params: dict,
                timeout: Optional[float] = None):
        if self.rpc is None:
            raise RpcTransportError(
                f"shard {self.shard_id} has no connection")
        return self.rpc.request(method, params, timeout=timeout)

    # backend-specific ---------------------------------------------------------

    def alive(self) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def kill(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def hang(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def terminate(self, timeout: float = 15.0) -> None:  # pragma: no cover
        raise NotImplementedError

    def close(self) -> None:
        if self.rpc is not None:
            self.rpc.close()


class ProcShard(ShardHandle):
    """A real ``python -m fluidframework_tpu.service.shardhost`` process."""

    def __init__(self, shard_id: str, base_dir: str,
                 fault_plan_path: Optional[str] = None,
                 extra_args: Tuple[str, ...] = ()) -> None:
        super().__init__(shard_id)
        cmd = [sys.executable, "-m", "fluidframework_tpu.service.shardhost",
               "--shard-id", shard_id, "--dir", base_dir, "--port", "0"]
        if fault_plan_path:
            cmd += ["--fault-plan", fault_plan_path]
        cmd += list(extra_args)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        self.proc = subprocess.Popen(
            cmd, cwd=_REPO_ROOT, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        self.log_tail: List[str] = []
        self._await_ready()
        self._drain = threading.Thread(target=self._drain_stdout,
                                       daemon=True)
        self._drain.start()

    def _await_ready(self, timeout: float = 60.0) -> None:
        import select

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready, _, _ = select.select([self.proc.stdout], [], [], 0.5)
            if not ready:
                if self.proc.poll() is not None:
                    break
                continue
            line = self.proc.stdout.readline()
            if line == "" and self.proc.poll() is not None:
                break
            self.log_tail.append(line.rstrip())
            if "listening on" in line:
                addr = line.split("listening on", 1)[1].split()[0]
                host, port = addr.rsplit(":", 1)
                self.addr = (host, int(port))
                return
        self.proc.kill()
        raise RuntimeError(
            f"shardhost {self.shard_id} never reported listening: "
            f"{self.log_tail[-5:]}")

    def _drain_stdout(self) -> None:
        # Keep the pipe from filling; remember a bounded tail for
        # post-mortems (the SIGTERM seal line rides this).
        for line in self.proc.stdout:
            self.log_tail.append(line.rstrip())
            del self.log_tail[:-200]

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()  # SIGKILL: no drain, no seal — the real test
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass

    def hang(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(_signal.SIGSTOP)

    def terminate(self, timeout: float = 15.0) -> None:
        """Graceful stop: SIGTERM → drain-and-seal → exit; escalates to
        SIGKILL only if the drain never completes."""
        if self.proc.poll() is None:
            self.proc.terminate()
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.kill()


class ThreadShard(ShardHandle):
    """An in-process shard server (same on-disk layout, same RPC) for
    cheap harness runs: "kill" abandons the server instead of SIGKILLing
    a process — equivalent to a kill landing between dispatches, which is
    the only difference the deterministic harnesses can observe.  The
    REAL signal semantics (mid-anything SIGKILL, SIGSTOP hangs, SIGTERM
    seal) are exercised by the ``ProcShard`` tests and benches."""

    def __init__(self, shard_id: str, base_dir: str,
                 extra_args: Tuple[str, ...] = ()) -> None:
        from .shardhost import ShardHost, ShardHostServer, apply_shard_flags

        super().__init__(shard_id)
        self.host_obj = ShardHost(shard_id, base_dir)
        self.server = ShardHostServer(self.host_obj, port=0)
        # Same tuning vocabulary as the process CLI (and re-applied the
        # same way on a failover respawn).
        apply_shard_flags(self.server, extra_args)
        self.server.start_in_thread()
        self.addr = ("127.0.0.1", self.server.port)
        self._dead = False
        self._hung = False

    def ping(self, timeout: float = 2.0) -> bool:
        if self._hung or self._dead:
            return False
        return super().ping(timeout=timeout)

    def alive(self) -> bool:
        return not self._dead

    def _stop_listener(self) -> None:
        """Close the in-thread server's listening socket so abandoned
        shards do not accumulate live listeners/loops for the process
        lifetime (long harness sessions kill many of these)."""
        loop, server = self.server.loop, self.server._server
        if loop is not None and server is not None:
            try:
                loop.call_soon_threadsafe(server.close)
            except RuntimeError:
                pass  # loop already closed

    def kill(self) -> None:
        self._dead = True
        # Process-death semantics without a process: a SIGKILLed shard
        # stamps NOTHING ever again — fence every orderer BEFORE closing
        # the connection, or the server-side session teardown would
        # gracefully stamp LEAVEs into the "dead" log (messages a real
        # kill -9 could never produce, and the adopted owner would then
        # replay a quorum the oracle never saw).
        self.host_obj.service.fence_all()
        self.close()
        self._stop_listener()

    def hang(self) -> None:
        self._hung = True

    def terminate(self, timeout: float = 15.0) -> None:
        self._dead = True
        # Order matters: fence before closing the connection — the
        # server-side session teardown would otherwise stamp LEAVEs
        # into a log the seal below is about to close.
        self.host_obj.service.fence_all()
        self.close()
        self._stop_listener()
        self.host_obj.seal()


class ExternShard(ShardHandle):
    """Attach-mode handle (ISSUE 18): a shard-host process OWNED BY
    ANOTHER front door (the primary), addressed over TCP.  N shared-
    nothing replicas supervise the same shard fleet through these —
    they agree on doc→shard placement purely through the deterministic
    rendezvous router, with zero shared assignment state.

    Ownership split: ``terminate`` is a NO-OP (a replica closing must
    never tear down shards the primary still serves), but ``kill`` is
    REAL — it SIGKILLs by pid (``shard_info`` reports it; same-machine
    deployment).  SIGKILL-is-the-fence must hold no matter which
    replica runs a failover: adopting a merely-unreachable shard's
    documents without killing it would let the old process wake up and
    extend a re-owned log."""

    def __init__(self, shard_id: str, addr: Tuple[str, int]) -> None:
        super().__init__(shard_id)
        self.addr = (addr[0], int(addr[1]))

    def alive(self) -> bool:
        # No child handle to poll: liveness is observable only over the
        # wire.  The heartbeat model already accepts that ambiguity —
        # kill-before-adopt is what makes slow-vs-dead irrelevant.
        return self.ping()

    def kill(self) -> None:
        if self.pid is None:
            return
        try:
            os.kill(self.pid, _signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass  # already gone (the owner may have reaped it)

    def hang(self) -> None:
        if self.pid is None:
            return
        try:
            os.kill(self.pid, _signal.SIGSTOP)
        except (ProcessLookupError, PermissionError):
            pass

    def terminate(self, timeout: float = 15.0) -> None:
        """Not ours to stop: the owning front door drains-and-seals its
        own children on ITS close."""


class FrontDoor:
    """The routing front door + shard supervisor of the fluidproc tier.

    Public API (thread-safe; topology mutations serialize on the
    supervisor thread): :meth:`start`, :meth:`close`, :meth:`add_shard`,
    :meth:`fail_shard`, :meth:`tick` (fault-plan driver), :meth:`stats`,
    :meth:`poll_shards` (synchronous death-detection sweep).
    """

    def __init__(self, base_dir: str, n_shards: int = 4,
                 shard_ids: Optional[List[str]] = None,
                 spawn: str = "proc", host: str = "127.0.0.1",
                 port: int = 0, faults=None,
                 heartbeat_interval: Optional[float] = None,
                 hang_detect_ticks: int = 2, mc=None,
                 shard_fault_plan_path: Optional[str] = None,
                 request_timeout: float = 30.0,
                 relay_budget: int = 4 << 20,
                 attach_addrs: Optional[Dict[str, Tuple[str, int]]] = None,
                 shard_args: Optional[List[str]] = None,
                 dispatch_workers: int = 8) -> None:
        if spawn not in ("proc", "thread", "attach"):
            raise ValueError(f"unknown spawn backend {spawn!r}")
        if spawn == "attach":
            if not attach_addrs:
                raise ValueError("attach spawn requires attach_addrs")
            ids = (list(shard_ids) if shard_ids is not None
                   else sorted(attach_addrs))
        else:
            ids = (list(shard_ids) if shard_ids is not None
                   else [f"shard{i:02d}" for i in range(n_shards)])
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)
        self.spawn_mode = spawn
        self._attach_addrs = dict(attach_addrs or {})
        #: extra tuning args applied to every spawned shard — CLI args
        #: for proc spawns, the same vocabulary via
        #: ``shardhost.apply_shard_flags`` for thread spawns (e.g. the
        #: wire-clock admission flags a deterministic out-of-proc storm
        #: needs); ignored for attach spawns (not ours to configure).
        self.shard_args: Tuple[str, ...] = tuple(shard_args or ())
        self.host = host
        self.port = port
        self.router = ShardRouter(ids)
        self.epoch: Optional[str] = None
        self.fences = 0
        self._mc = (mc or MonitoringContext()).child("frontdoor")
        self._faults = faults
        self._shard_fault_plan_path = shard_fault_plan_path
        self.hang_detect_ticks = int(hang_detect_ticks)
        self._heartbeat_interval = heartbeat_interval
        #: per shard-RPC timeout: a SIGSTOPped (hung-not-dead) shard is
        #: only discovered when a request against it expires — harnesses
        #: drop this so hang windows cost seconds, not the 30 s default.
        self.request_timeout = float(request_timeout)
        #: per-client broadcast-relay byte budget (ISSUE 15): queued +
        #: in-flight relay bytes above this demote the session for the
        #: saturating document — bounded memory per laggard, no relay
        #: stall for anyone else.
        self.relay_budget = int(relay_budget)
        self.counters = LockedCounterSet(
            "fd.requests", "fd.failovers", "fd.adoptions", "fd.migrations",
            "fd.retries", "fd.events", "fd.hangs", "fd.heartbeat_failures",
            "fd.relay_demotions",
        )
        #: routing state — every map below is dict-operations-only under
        #: the route lock; RPC never happens while it is held.
        self._route_lock = threading.Lock()
        self._shards: Dict[str, ShardHandle] = {}  # guarded-by: _route_lock
        self._overrides: Dict[str, str] = {}  # guarded-by: _route_lock
        self._orphans: Dict[str, str] = {}  # guarded-by: _route_lock
        self._docs: Set[str] = set()  # guarded-by: _route_lock
        self._subs: Dict[str, List[PumpConnection]] = {}  # guarded-by: _route_lock
        self._tap_registered: Set[Tuple[str, str]] = set()  # guarded-by: _route_lock
        #: migration audit trail: (doc, source shard, target shard)
        self.migrations: List[Tuple[str, str, str]] = []  # guarded-by: _route_lock
        #: proc.hang detections pending their virtual-tick deadline
        self._hang_pending: Dict[str, int] = {}
        self._next_ordinal = len(ids)
        self._crash_hook: Optional[Callable[[str, str], None]] = None
        self._stopping = threading.Event()
        self._jobs: "queue.Queue[Optional[_Job]]" = queue.Queue()
        self._supervisor: Optional[threading.Thread] = None
        self._heartbeat_thread: Optional[threading.Thread] = None
        #: the event-loop connection layer (ISSUE 18): ONE thread owns
        #: accept + reads + budget-aware writes for every client socket;
        #: decoded frames dispatch to the worker pool below (a shard RPC
        #: must never run on the loop — it would stall every connection).
        self._pump: Optional[FramePump] = None
        self._dispatch: Optional[ThreadPoolExecutor] = None
        self.dispatch_workers = int(dispatch_workers)
        #: set by :meth:`kill` (replica-death drills): this door went
        #: down ABRUPTLY — no drain, no seal, shards left running.
        self.killed = False

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "FrontDoor":
        try:
            for sid in self.router.shard_ids():
                handle = self._spawn(sid)
                with self._route_lock:
                    self._shards[sid] = handle
            self._seed_registry()
        except BaseException:
            # A later spawn (port exhaustion, child import error) or the
            # registry seed failed: reap every shard already running, or
            # each failed start() leaks live processes.
            with self._route_lock:
                spawned = list(self._shards.values())
            for handle in spawned:
                handle.close()
                try:
                    handle.terminate()
                except (OSError, RuntimeError):
                    pass
            raise
        self._dispatch = ThreadPoolExecutor(
            max_workers=self.dispatch_workers,
            thread_name_prefix="fd-dispatch")
        self._pump = FramePump(self.host, self.port, self._on_frame,
                               on_close=self._drop_session,
                               relay_budget=self.relay_budget,
                               mc=self._mc)
        self._pump.start()
        self.port = self._pump.port
        self._supervisor = threading.Thread(target=self._supervisor_loop,
                                            daemon=True)
        self._supervisor.start()
        if self._heartbeat_interval is not None:
            self._heartbeat_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True)
            self._heartbeat_thread.start()
        return self

    def _spawn(self, shard_id: str) -> ShardHandle:
        if self.spawn_mode == "proc":
            handle: ShardHandle = ProcShard(
                shard_id, self.base_dir,
                fault_plan_path=self._shard_fault_plan_path,
                extra_args=self.shard_args)
        elif self.spawn_mode == "attach":
            if shard_id not in self._attach_addrs:
                raise RpcTransportError(
                    f"attach replica has no address for {shard_id!r}")
            handle = ExternShard(shard_id, self._attach_addrs[shard_id])
        else:
            handle = ThreadShard(shard_id, self.base_dir,
                                 extra_args=self.shard_args)
        handle.connect(mc=self._mc, timeout=self.request_timeout)
        info = handle.request("shard_info", {})
        handle.pid = info.get("pid")
        if self.epoch is None:
            self.epoch = info["epoch"]
        return handle

    def _seed_registry(self) -> None:
        """Restart over an existing deployment: the doc registry rebuilds
        from every shard's durable log heads."""
        with self._route_lock:
            handles = list(self._shards.values())
        seen: Set[str] = set()
        for handle in handles:
            stats = handle.request("stats", {})
            seen.update(stats.get("heads", {}))
        with self._route_lock:
            self._docs.update(seen)

    def close(self) -> None:
        """Graceful stop: connections down, workers drained, every OWNED
        shard drain-and-sealed (``ExternShard.terminate`` is a no-op —
        attach replicas never tear down the primary's fleet)."""
        self._stopping.set()
        if self._pump is not None:
            self._pump.close()
        if self._dispatch is not None:
            self._dispatch.shutdown(wait=False)
        self._jobs.put(None)
        with self._route_lock:
            handles = list(self._shards.values())
        for handle in handles:
            handle.close()
            try:
                handle.terminate()
            except (OSError, RuntimeError) as exc:
                self._mc.logger.send({
                    "eventName": "shardTerminateError",
                    "shard": handle.shard_id, "error": str(exc)})
        if self._supervisor is not None:
            self._supervisor.join(timeout=10)
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=10)

    def kill(self) -> None:
        """Abrupt death (replica drills): every client socket drops with
        NOTHING flushed — from the wire this is indistinguishable from a
        SIGKILLed replica process, which is the point.  Shard processes
        are NOT touched (a replica does not own them; for a primary this
        models the supervisor dying while its children keep serving —
        callers that own shards must still reap them)."""
        self.killed = True
        self._stopping.set()
        if self._pump is not None:
            self._pump.close()
        if self._dispatch is not None:
            self._dispatch.shutdown(wait=False, cancel_futures=True)
        self._jobs.put(None)
        with self._route_lock:
            handles = list(self._shards.values())
        for handle in handles:
            handle.close()  # the RPC socket only, never the process
        if self._supervisor is not None:
            self._supervisor.join(timeout=10)
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=10)

    # -- the supervisor actor (ALL topology mutations run here) ----------------

    def _supervisor_loop(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            try:
                job.result = job.fn()
            except BaseException as exc:
                # Delivered to the waiter; a waiterless (fire-and-forget)
                # job's failure surfaces through telemetry instead of
                # vanishing with the Job object.
                job.error = exc
                if job.fire_and_forget:
                    self._mc.logger.send({
                        "eventName": "supervisorJobFailed",
                        "error": str(exc),
                        "errorType": type(exc).__name__,
                    })
            finally:
                job.done.set()

    def _control(self, fn: Callable[[], object], wait: bool = True,
                 timeout: float = 600.0):
        """Run ``fn`` on the supervisor thread.  ``wait=False`` posts and
        returns (heartbeat detections; failures land in telemetry);
        otherwise the caller blocks — bounded — and the job's exception
        re-raises here."""
        job = _Job(fn, fire_and_forget=not wait)
        self._jobs.put(job)
        if not wait:
            return None
        if not job.done.wait(timeout):
            raise RuntimeError("front-door supervisor stalled")
        if job.error is not None:
            raise job.error
        return job.result

    # -- routing ---------------------------------------------------------------

    def _owner_for(self, doc_id: str, candidates: List[str]) -> str:
        return max(candidates,
                   key=lambda sid: (rendezvous_score(doc_id, sid), sid))

    def _route_probe(self, doc_id: str) -> Tuple[str, Optional[str]]:
        """(current owner, orphan source or None) in one critical
        section."""
        with self._route_lock:
            sid = self._overrides.get(doc_id)
            if sid is None:
                sid = self.router.owner(doc_id)
            return sid, self._orphans.get(doc_id)

    def _route_ready(self, doc_id: str) -> str:
        """The owner shard id, with lazy failover adoption done: a
        document orphaned by a dead shard is imported into its new owner
        before any request is forwarded there."""
        sid, orphan_src = self._route_probe(doc_id)
        if orphan_src is None:
            return sid
        if threading.current_thread() is self._supervisor:
            # Already on the control plane (failover/migration re-wiring
            # resolving its own routes): posting a job to ourselves and
            # waiting would deadlock — run the adoption directly.
            self._adopt(doc_id)
        else:
            self._control(lambda: self._adopt(doc_id))
        sid, _ = self._route_probe(doc_id)
        return sid

    def _shard(self, shard_id: str) -> ShardHandle:
        with self._route_lock:
            handle = self._shards.get(shard_id)
        if handle is None:
            raise RpcTransportError(f"no live shard {shard_id!r}")
        return handle

    def _forward_doc(self, method: str, params: dict):
        """Proxy one doc-scoped request to the owning shard, riding
        through at most two topology changes (failover / migration flip)
        by re-resolving and retrying — submits are safe to resend because
        the sequencer dedups by (client, client_seq)."""
        doc_id = params["doc"]
        last: Optional[BaseException] = None
        for _attempt in range(3):
            sid = self._route_ready(doc_id)
            handle = self._shard(sid)
            try:
                return handle.request(method, params)
            except DocRelocatedError as exc:
                last = exc  # stale route: re-resolve through the maps
                self.counters.bump("fd.retries")
            except ShardFencedError as exc:
                last = exc
                self.counters.bump("fd.retries")
                self._control(lambda s=sid: self._check_shard(s))
            except _TRANSPORT_ERRORS as exc:
                last = exc
                self.counters.bump("fd.retries")
                self._control(lambda s=sid: self._check_shard(s))
        raise last

    # -- client-facing server (the pump feeds these) ---------------------------

    def _on_frame(self, session: PumpConnection, frame: dict) -> None:
        # on-loop: runs on the pump thread for EVERY decoded frame — the
        # only permissible work here is handing off to the worker pool
        # (a shard RPC on the loop would stall every connection).
        dispatch = self._dispatch
        if dispatch is None or self._stopping.is_set():
            return
        try:
            dispatch.submit(self._serve_frame, session, frame)
        except RuntimeError:
            pass  # pool shut down mid-teardown: the socket is dying too

    def _serve_frame(self, session: PumpConnection, frame: dict) -> None:
        """Worker-pool entry: serve one request, write the response back
        through the pump.  Responses may interleave across requests of
        one connection — the wire contract matches replies by ``re`` id,
        so per-connection pipelining is free concurrency, not a bug."""
        try:
            session.send_obj(self._respond(session, frame))
        except Exception as exc:  # a response writer must never die mute
            self._mc.logger.send({"eventName": "clientSessionError",
                                  "error": str(exc)})

    def _drop_session(self, session: PumpConnection) -> None:
        with self._route_lock:
            for doc_id in session.subscribed:
                subs = self._subs.get(doc_id)
                if subs and session in subs:
                    subs.remove(session)

    def _respond(self, session: PumpConnection, frame: dict) -> dict:
        rid = frame.get("id")
        if frame.get("v", 1) > WIRE_VERSION:
            return {"v": WIRE_VERSION, "re": rid, "ok": False,
                    "error": f"unsupported wire version {frame.get('v')}"}
        self.counters.bump("fd.requests")
        try:
            result = self._handle_method(session, frame.get("method"),
                                         frame.get("params", {}))
            return {"v": WIRE_VERSION, "re": rid, "ok": True,
                    "result": result}
        except NackError as nack:
            body = {"retryAfter": nack.retry_after,
                    "reason": nack.reason, "code": nack.code}
            if nack.admission is not None:
                body["admission"] = nack.admission
            return {"v": WIRE_VERSION, "re": rid, "ok": False,
                    "error": nack.reason, "nack": body}
        except DocRelocatedError as dr:
            return {"v": WIRE_VERSION, "re": rid, "ok": False,
                    "error": str(dr), "code": "wrongShard",
                    "doc": dr.doc_id}
        except ShardFencedError as sf:
            return {"v": WIRE_VERSION, "re": rid, "ok": False,
                    "error": str(sf), "code": "shardFenced",
                    "doc": sf.doc_id}
        except RpcError as exc:
            out = {"v": WIRE_VERSION, "re": rid, "ok": False,
                   "error": str(exc)}
            epoch = getattr(exc, "server_epoch", None)
            if epoch is not None:
                out["code"] = "epochMismatch"
                out["epoch"] = epoch
            return out
        except Exception as exc:  # surfaced to the client, like the server
            return {"v": WIRE_VERSION, "re": rid, "ok": False,
                    "error": str(exc), "code": "internal"}

    def _handle_method(self, session: PumpConnection, method: str,
                       params: dict):
        if method == "ping":
            return "pong"
        if method == "auth":
            return True  # tenancy lives on the single-server shape
        if method == "stats":
            return self.stats()
        if method == "locate":
            sid = self._route_ready(params["doc"])
            handle = self._shard(sid)
            return {"shard": sid, "host": handle.addr[0],
                    "port": handle.addr[1]}
        if method == "heads":
            return self.heads(list(params.get("docs") or ()))
        if method == "log_contiguous" and "docs" in params:
            return self.contiguous(list(params["docs"]))
        if method == "submit_mixed":
            return self._submit_mixed(params)
        if method == "catchup":
            return self._catchup(params)
        if method == "read_summary":
            # content-addressed + shared store: any live shard serves it
            return self._shard(self.router.alive()[0]).request(
                "read_summary", params)
        if method == "subscribe_doc":
            return self._subscribe(session, params)
        if method == "create_document":
            result = self._forward_doc(method, params)
            with self._route_lock:
                self._docs.add(params["doc"])
            return result
        if "doc" in params:
            return self._forward_doc(method, params)
        raise ValueError(f"unknown method {method!r}")

    # -- bulk routes -----------------------------------------------------------

    def _group_by_owner(self, doc_ids) -> Dict[str, List[str]]:
        """THE bulk-route fan-out grouping: documents by their
        (adoption-resolved) owning shard — one definition point so every
        bulk route routes, and lazily adopts, identically."""
        groups: Dict[str, List[str]] = {}
        for doc_id in doc_ids:
            groups.setdefault(self._route_ready(doc_id), []).append(doc_id)
        return groups

    def heads(self, doc_ids: List[str]) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for sid, docs in sorted(self._group_by_owner(doc_ids).items()):
            out.update(self._shard(sid).request("heads", {"docs": docs}))
        return out

    def contiguous(self, doc_ids: List[str]) -> Dict[str, bool]:
        """Bulk per-doc seq-contiguity, grouped by owning shard."""
        out: Dict[str, bool] = {}
        for sid, docs in sorted(self._group_by_owner(doc_ids).items()):
            out.update(self._shard(sid).request("log_contiguous",
                                                {"docs": docs}))
        return out

    def _catchup(self, params: dict) -> dict:
        doc_ids = params.get("docs")
        if doc_ids is None:
            with self._route_lock:
                doc_ids = sorted(self._docs)
        groups = self._group_by_owner(doc_ids)
        merged = {"docs": {}, "skipped": [], "deviceDocs": 0, "cpuDocs": 0,
                  "cache": None, "deltaCache": None, "lane": None,
                  "lanes": {}, "degraded": []}
        for sid, docs in sorted(groups.items()):
            part = self._shard(sid).request(
                "catchup", dict(params, docs=docs))
            merged["docs"].update(part.get("docs", {}))
            merged["skipped"].extend(part.get("skipped", ()))
            merged["deviceDocs"] += part.get("deviceDocs", 0)
            merged["cpuDocs"] += part.get("cpuDocs", 0)
            merged["degraded"].extend(part.get("degraded", ()))
            merged["lanes"][sid] = part.get("lane")
        merged["skipped"] = sorted(merged["skipped"])
        merged["degraded"] = sorted(merged["degraded"])
        # One summary lane for single-shard callers; the per-shard split
        # stays in "lanes".  Worst lane wins: any degraded answer makes
        # the merged answer degraded (a stale doc is in there somewhere).
        lanes = set(merged["lanes"].values())
        merged["lane"] = ("degraded" if "degraded" in lanes
                          else "fold" if "fold" in lanes
                          else "warm" if lanes else None)
        return merged

    def _submit_mixed(self, params: dict) -> Dict[str, dict]:
        """Fan one client batch out to the owning shards: boxed op lists
        forward as-is, the columnar batch is row-sliced per shard
        (``ColumnBatch.take``) so each shard stamps exactly its
        documents' rows under ONE group commit of ITS log.  A shard dying
        mid-call reports its documents with ``consumed=-1`` ("unknown —
        re-read the durable head"); the whole-batch resubmit contract
        plus seq dedup make the retry safe."""
        batches = params.get("batches") or {}
        doc_rows = params.get("doc_rows") or {}
        batch = (decode_column_batch(params["columns"])
                 if params.get("columns") is not None else None)
        groups = self._group_by_owner(sorted(set(batches) | set(doc_rows)))
        out: Dict[str, dict] = {}
        for sid in sorted(groups):
            boxed = [d for d in groups[sid] if d in batches]
            row_docs = [d for d in groups[sid] if d in doc_rows]
            payload: dict = {
                "batches": {d: batches[d] for d in boxed}}
            if row_docs:
                ranges = sorted(
                    (int(doc_rows[d][0]), int(doc_rows[d][1]), d)
                    for d in row_docs)
                rows = np.concatenate([
                    np.arange(s, e, dtype=np.int64) for s, e, _d in ranges])
                sub_rows: Dict[str, list] = {}
                at = 0
                for s, e, d in ranges:
                    sub_rows[d] = [at, at + (e - s)]
                    at += e - s
                payload["columns"] = encode_column_batch(batch.take(rows))
                payload["doc_rows"] = sub_rows
            handle = self._shard(sid)
            try:
                out.update(handle.request("submit_mixed", payload))
            except _TRANSPORT_ERRORS as exc:
                self._control(lambda s=sid: self._check_shard(s))
                for d in groups[sid]:
                    out[d] = {"stamped": 0, "consumed": -1,
                              "error": f"shard died mid-batch: {exc}",
                              "code": "shardDead"}
        return out

    # -- broadcast relay -------------------------------------------------------

    def _subscribe(self, session: PumpConnection, params: dict) -> int:
        doc_id = params["doc"]
        head = self._ensure_tap(doc_id)
        with self._route_lock:
            subs = self._subs.setdefault(doc_id, [])
            if session not in subs:
                subs.append(session)
            # Under the lock: _drop_session and _demote_relay iterate /
            # mutate this set cross-thread, and pool dispatch means even
            # one connection's own subscribes run on arbitrary workers.
            session.subscribed.add(doc_id)
        return head

    def _ensure_tap(self, doc_id: str) -> int:
        """Subscribe the FRONT DOOR on the owning shard (once per
        (shard, doc)): op/signal events relay serialize-once to every
        subscribed client session."""
        sid = self._route_ready(doc_id)
        handle = self._shard(sid)
        with self._route_lock:
            register = (sid, doc_id) not in self._tap_registered
            if register:
                self._tap_registered.add((sid, doc_id))
        if register and handle.rpc is not None:
            handle.rpc.on("op", doc_id, self._relay_event)
            handle.rpc.on("signal", doc_id, self._relay_event)
            handle.rpc.on("demoted", doc_id, self._relay_demoted)
        return handle.request("subscribe_doc", {"doc": doc_id})

    def _relay_event(self, frame: dict) -> None:
        doc_id = frame.get("doc", "")
        with self._route_lock:
            sessions = list(self._subs.get(doc_id, ()))
        if not sessions:
            return
        self.counters.bump("fd.events")
        data = frame_bytes(frame)  # ONE encode for every client session
        for session in sessions:
            if not session.relay(data):
                self._demote_relay(session, doc_id)

    def _demote_relay(self, session: PumpConnection, doc_id: str) -> None:
        """Per-client relay flow control tripped (ISSUE 15): remove the
        laggard session from this document's fan-out and tell it once —
        the client driver re-subscribes and gap-repairs from durable
        deltas, the exact broadcaster demotion contract (SEMANTICS.md
        "Delivery and backpressure") applied at the front-door hop.
        The session's OTHER documents are untouched (it may be current
        on them), and no other session ever waits on the laggard."""
        with self._route_lock:
            subs = self._subs.get(doc_id)
            if subs is None or session not in subs:
                return  # already demoted by a racing relay fan-out
            subs.remove(session)
            # Under the lock, like every touch of session.subscribed
            # (_subscribe adds, _drop_session iterates — all
            # cross-thread once frames dispatch to a pool).
            session.subscribed.discard(doc_id)
        self.counters.bump("fd.relay_demotions")
        session.relay_priority(frame_bytes(
            {"v": WIRE_VERSION, "event": "demoted", "doc": doc_id,
             "head": 0}))

    def _relay_demoted(self, frame: dict) -> None:
        """The shard's broadcaster demoted the FRONT DOOR (we lagged):
        forward the demotion — each client's driver re-subscribes
        (re-requesting our upstream subscribe_doc) and gap-repairs from
        durable deltas, the exact single-server recovery path.  Handler
        registrations stay (``_tap_registered``): they belong to the
        connection, and re-adding them on re-subscribe would
        double-deliver every later event.  Rides the priority relay
        path: a demotion notice must reach even a budget-saturated
        client."""
        doc_id = frame.get("doc", "")
        with self._route_lock:
            sessions = list(self._subs.get(doc_id, ()))
        data = frame_bytes(frame)
        for session in sessions:
            session.relay_priority(data)

    def _retap(self, doc_id: str, head: int) -> None:
        """Failover/migration re-wiring: move the upstream tap to the
        document's current owner and push a ``fence`` event so pinned
        clients unpin proactively (byte-compatible with the in-proc
        tier's fence push)."""
        self._ensure_tap(doc_id)
        with self._route_lock:
            sessions = list(self._subs.get(doc_id, ()))
        frame = {"v": WIRE_VERSION, "event": "fence", "doc": doc_id,
                 "epoch": self.epoch, "head": head}
        data = frame_bytes(frame)
        for session in sessions:
            # Control frame: budget-exempt — a fenced client must learn
            # the new epoch even when its relay queue is saturated.
            session.relay_priority(data)

    # -- supervision: death detection + failover -------------------------------

    def poll_shards(self) -> List[str]:
        """Synchronous death-detection sweep (tests, tick harnesses):
        every unresponsive live shard fails over NOW.  Returns the shard
        ids that were failed over."""
        with self._route_lock:
            candidates = [(sid, h) for sid, h in self._shards.items()
                          if sid not in self.router.dead()]
        failed = []
        for sid, handle in candidates:
            if not handle.alive() or not handle.ping():
                self.counters.bump("fd.heartbeat_failures")
                self._control(lambda s=sid: self._failover(s))
                failed.append(sid)
        return failed

    def _heartbeat_loop(self) -> None:
        while not self._stopping.wait(self._heartbeat_interval):
            with self._route_lock:
                candidates = [(sid, h) for sid, h in self._shards.items()
                              if sid not in self.router.dead()]
            for sid, handle in candidates:
                if self._stopping.is_set():
                    return
                if not handle.alive() or not handle.ping():
                    self.counters.bump("fd.heartbeat_failures")
                    self._control(lambda s=sid: self._failover(s),
                                  wait=False)

    def fail_shard(self, shard_id: str) -> List[str]:
        """Kill one shard process and fail it over (test/chaos API)."""
        return self._control(lambda: self._kill_and_failover(shard_id))

    def fence_token(self, shard_id: str) -> str:
        """Deterministic fence epoch — the SAME derivation as the
        in-proc tier's (shared helper: cross-tier byte parity)."""
        return fence_token(self.epoch or "", shard_id)

    def _check_shard(self, shard_id: str) -> None:
        """Supervisor-side trouble report: transient errors are ignored;
        a dead/unresponsive shard fails over exactly once."""
        with self._route_lock:
            handle = self._shards.get(shard_id)
            already_dead = shard_id in self.router.dead()
        if handle is None or already_dead:
            return
        if handle.alive() and handle.ping():
            return
        self._failover(shard_id)

    def _kill_and_failover(self, shard_id: str) -> List[str]:
        alive = self.router.alive()
        if shard_id in alive and len(alive) <= 1:
            # Same contract as the in-proc tier's kill_shard: the last
            # live shard is unkillable — refuse BEFORE the SIGKILL, or
            # the refusal would come from mark_dead with the process
            # already dead and the tier unroutable.
            raise RuntimeError("cannot kill the last live shard")
        with self._route_lock:
            handle = self._shards.get(shard_id)
        if handle is not None:
            handle.kill()
        return self._failover(shard_id)

    def _routes_of(self, shard_id: str) -> List[str]:
        with self._route_lock:
            return sorted(
                d for d in self._docs
                if (self._overrides.get(d) or self.router.owner(d))
                == shard_id)

    def _apply_failover_routes(self, shard_id: str,
                               affected: List[str]) -> List[str]:
        """One critical section: orphan every affected doc (keeping an
        EARLIER orphan source — its log still holds the history), drop
        overrides pointing at the corpse, and snapshot the subscribed
        docs that need eager adoption."""
        with self._route_lock:
            for doc_id in affected:
                self._orphans.setdefault(doc_id, shard_id)
            for doc_id, sid in list(self._overrides.items()):
                if sid == shard_id:
                    self._overrides.pop(doc_id)
            for key in list(self._tap_registered):
                if key[0] == shard_id:
                    self._tap_registered.discard(key)
            self.fences += 1
            return [d for d in affected if self._subs.get(d)]

    def _failover(self, shard_id: str) -> List[str]:
        """Supervisor-only.  The epoch-fenced failover: SIGKILL the
        victim (process death IS the fence — a hung process must never
        wake up and extend a re-owned document's log), flip the router,
        orphan the dead shard's documents FIRST (the step everything
        else can heal from — it must never be skipped by a later
        failure), then bump the fence epoch on every survivor and
        eagerly adopt + re-wire the live-subscribed documents.  Every
        post-orphaning step is individually fault-isolated: a survivor
        that fails its epoch bump gets its own trouble check, a doc
        whose eager adoption fails keeps its orphan mark (the next
        touch retries) — a SECOND fault mid-failover degrades, never
        silently loses durable history."""
        with self._route_lock:
            handle = self._shards.get(shard_id)
            already_dead = shard_id in self.router.dead()
            routed = shard_id in self.router.shard_ids()
        if handle is None or already_dead:
            return []
        if not routed:
            # A pending migration target (spawned, not yet joined to the
            # router) died: nothing rendezvous-routes to it, but flipped
            # docs may override to it — re-orphan those from ITS log.
            self._abort_pending_shard(shard_id)
            return []
        alive = self.router.alive()
        if shard_id in alive and len(alive) <= 1:
            # The LAST live shard missed a probe (GC pause, disk stall):
            # SIGKILLing it would turn a stall into a total outage with
            # no adoption target.  Refuse BEFORE the kill — mark_dead
            # would refuse anyway, but only after the process was gone.
            self._mc.logger.send({
                "eventName": "lastShardUnfailable", "shard": shard_id})
            return []
        handle.kill()
        handle.close()
        affected = self._routes_of(shard_id)
        self.router.mark_dead(shard_id)  # raises on the last live shard
        subscribed = self._apply_failover_routes(shard_id, affected)
        self.counters.bump("fd.failovers")
        token = self.fence_token(shard_id)
        with self._route_lock:
            survivors = [(sid, h) for sid, h in self._shards.items()
                         if sid != shard_id
                         and sid not in self.router.dead()]
        new_epoch = self.epoch
        for sid, survivor in survivors:
            try:
                new_epoch = survivor.request("bump_epoch",
                                             {"token": token})
            except (RpcError, OSError, ConnectionError) as exc:
                # The survivor may itself be dying: its own failover will
                # re-route its documents; the missed (deterministic)
                # bump only widens the stale-pin window, never forks.
                self._mc.logger.send({
                    "eventName": "epochBumpFailed", "shard": sid,
                    "error": str(exc)})
        self.epoch = new_epoch
        for doc_id in subscribed:
            try:
                head = self._adopt(doc_id)
                self._retap(doc_id, head)
            except (RpcError, OSError, ConnectionError) as exc:
                # Orphan mark survives (only cleared on adopt success):
                # the next touch re-runs the adoption.
                self._mc.logger.send({
                    "eventName": "eagerAdoptFailed", "doc": doc_id,
                    "error": str(exc)})
        return affected

    def _abort_pending_shard(self, shard_id: str) -> None:
        """A shard that never joined the router died (migration target):
        kill the handle and re-orphan every doc flipped to it — its log
        holds their live spans."""
        with self._route_lock:
            handle = self._shards.pop(shard_id, None)
            flipped = [d for d, s in self._overrides.items()
                       if s == shard_id]
            for doc_id in flipped:
                self._overrides.pop(doc_id)
                self._orphans.setdefault(doc_id, shard_id)
            for key in list(self._tap_registered):
                if key[0] == shard_id:
                    self._tap_registered.discard(key)
        if handle is not None:
            handle.kill()
            handle.close()

    def _adopt(self, doc_id: str) -> int:
        """Supervisor-only: import an orphaned document's span from the
        dead source's log into its new owner.  Idempotent; returns the
        owner's durable head."""
        with self._route_lock:
            source = self._orphans.get(doc_id)
            sid = self._overrides.get(doc_id) or self.router.owner(doc_id)
        handle = self._shard(sid)
        if source is None:
            return handle.request("heads", {"docs": [doc_id]})[doc_id]
        # Any FAILURE keeps the orphan mark (a later touch retries) —
        # only an explicit verdict may clear it: either a successful
        # import, or the shard's structured "nothing durable existed"
        # answer (created-but-empty doc died with its shard; in-proc
        # parity is that the document simply no longer exists).  A
        # corrupt-object or replay error must NEVER be mistaken for
        # nothing-durable: that would silently abandon real history.
        result = handle.request("adopt_doc",
                                {"doc": doc_id, "from_shard": source})
        self._orphan_adopted(doc_id, source)
        if result.get("nothing"):
            self._mc.logger.send({
                "eventName": "adoptNothingDurable", "doc": doc_id,
                "from": source})
            return 0
        self.counters.bump("fd.adoptions")
        return result["head"]

    def _orphan_adopted(self, doc_id: str, source: str) -> None:
        """Clear the orphan mark — re-validated under the lock: only the
        exact source the adoption imported from is cleared, so a
        concurrent re-orphaning (the adopter itself died mid-call) is
        never wiped by a stale success."""
        with self._route_lock:
            if self._orphans.get(doc_id) == source:
                self._orphans.pop(doc_id)

    # -- fault-plan driver (deterministic harnesses) ---------------------------

    def _victim_of(self, point) -> Optional[str]:
        if point.shard is not None:
            victim = point.shard
        elif point.doc is not None:
            victim = self._route_probe(point.doc)[0]
        else:
            alive = self.router.alive()
            victim = alive[0] if alive else None
        if (victim is None or victim in self.router.dead()
                or len(self.router.alive()) <= 1):
            return None
        return victim

    def tick(self, now: int) -> List[str]:
        """Execute every scheduled ``proc.kill`` / ``proc.hang`` /
        ``shard.kill`` fault point whose virtual tick arrived (the
        harness step driver — same surface as the in-proc sharded tier).
        A hang SIGSTOPs the victim now; its death is only DETECTED
        ``hang_detect_ticks`` later (the heartbeat model), at which point
        the front door SIGKILLs the stopped process and fails over."""
        if self._faults is None:
            return []
        affected: List[str] = []
        for point in self._faults.due("proc.hang", now):
            victim = self._victim_of(point)
            if victim is None or victim in self._hang_pending:
                self._faults.mark_unfired(point)
                continue
            self._control(lambda v=victim: self._shard(v).hang())
            self.counters.bump("fd.hangs")
            self._hang_pending[victim] = now + self.hang_detect_ticks
        for site in ("proc.kill", "shard.kill"):
            for point in self._faults.due(site, now):
                victim = self._victim_of(point)
                if victim is None:
                    self._faults.mark_unfired(point)
                    continue
                affected.extend(self._control(
                    lambda v=victim: self._kill_and_failover(v)))
        for sid, deadline in sorted(self._hang_pending.items()):
            if deadline > now:
                continue
            alive = self.router.alive()
            if sid in alive and len(alive) <= 1:
                # The hung shard is the last one alive: failing it over
                # is impossible — KEEP the entry pending so a later tick
                # (after capacity returns via add_shard) still shoots it.
                continue
            self._hang_pending.pop(sid)
            affected.extend(self._control(
                lambda v=sid: self._kill_and_failover(v)))
        return affected

    # -- live migration (add_shard) --------------------------------------------

    def set_crash_hook(self, fn: Optional[Callable[[str, str], None]]
                       ) -> None:
        """Test instrument: ``fn(step, doc)`` runs immediately before
        every migration step (steps: freeze, transfer, import, flip,
        resume) — crash-point suites kill a shard there and assert the
        protocol converges."""
        self._crash_hook = fn

    def _crash_point(self, step: str, doc_id: str) -> None:
        if self._crash_hook is not None:
            self._crash_hook(step, doc_id)

    def add_shard(self, shard_id: Optional[str] = None) -> dict:
        """Spawn a new shard process and LIVE-migrate the ~1/N documents
        rendezvous assigns it — freeze → transfer → import → flip →
        resume per document — then join it to the router.  Raises
        :class:`MigrationAborted` (with every frozen doc thawed) if the
        new shard dies mid-migration."""
        return self._control(lambda: self._add_shard_job(shard_id))

    def _new_shard_id(self) -> str:
        existing = set(self.router.shard_ids())
        while True:
            sid = f"shard{self._next_ordinal:02d}"
            self._next_ordinal += 1
            if sid not in existing:
                return sid

    def _add_shard_job(self, shard_id: Optional[str]) -> dict:
        sid = shard_id if shard_id is not None else self._new_shard_id()
        handle = self._spawn(sid)
        with self._route_lock:
            self._shards[sid] = handle
            docs = sorted(self._docs)
        future = self.router.alive() + [sid]
        movers = [d for d in docs if self._owner_for(d, future) == sid]
        moved: List[str] = []
        try:
            for doc_id in movers:
                if self._migrate_doc(doc_id, sid):
                    moved.append(doc_id)
        except MigrationAborted:
            self._abort_add_shard(sid, moved)
            raise
        self.router.add_shard(sid)
        with self._route_lock:
            # rendezvous now agrees with every override pointing at the
            # new shard — the overrides are redundant, not load-bearing.
            for doc_id in moved:
                self._overrides.pop(doc_id, None)
        return {"shard": sid, "moved": moved,
                "docs": len(docs), "movers": len(movers)}

    def _abort_add_shard(self, sid: str, moved: List[str]) -> None:
        """The new shard died mid-migration.  Docs already flipped to it
        are orphaned from ITS log (their live span is there); the rest
        never left their sources.  The would-be shard never joins the
        router."""
        with self._route_lock:
            handle = self._shards.pop(sid, None)
            for doc_id in moved:
                self._overrides.pop(doc_id, None)
                self._orphans.setdefault(doc_id, sid)
            subscribed = [d for d in moved if self._subs.get(d)]
        if handle is not None:
            handle.kill()
            handle.close()
        for doc_id in subscribed:
            try:
                head = self._adopt(doc_id)
                self._retap(doc_id, head)
            except (RpcError, OSError, ConnectionError) as exc:
                # Same per-doc isolation as _failover's eager loop: the
                # orphan mark survives, the next touch retries.
                self._mc.logger.send({
                    "eventName": "abortAdoptFailed", "doc": doc_id,
                    "error": str(exc)})

    def _migrate_doc(self, doc_id: str, target_sid: str) -> bool:
        """One document's live migration; supervisor-only.  Returns True
        when the doc ended up on the target.  Source death at any step
        degrades to the failover path (+ one retry from the adopted
        owner); target death raises :class:`MigrationAborted` after
        thawing the frozen source."""
        for _attempt in range(2):
            with self._route_lock:
                src_sid = (self._overrides.get(doc_id)
                           or self.router.owner(doc_id))
            if src_sid == target_sid:
                return True
            src = self._shard(src_sid)
            dst = self._shard(target_sid)
            frozen = None
            try:
                self._crash_point("freeze", doc_id)
                frozen = src.request("freeze_doc", {"doc": doc_id})
                self._crash_point("transfer", doc_id)
                span = src.request("export_doc", {"doc": doc_id})
                self._crash_point("import", doc_id)
                dst.request("import_doc", {
                    "doc": doc_id, "records": span["records"],
                    "checkpoint": frozen["checkpoint"]})
                self._crash_point("flip", doc_id)
            except _TRANSPORT_ERRORS as exc:
                if not (dst.alive() and dst.ping()):
                    # Target died: thaw the source (the doc never left)
                    # and abort the whole expansion.
                    if frozen is not None and src.alive():
                        src.request("thaw_doc", {"doc": doc_id})
                    raise MigrationAborted(
                        f"target shard {target_sid} died migrating "
                        f"{doc_id!r}: {exc}") from exc
                # Source died pre-flip: ordinary failover re-owns the
                # doc from the dead log; retry the migration from there.
                self._check_shard(src_sid)
                self._adopt(doc_id)
                continue
            subscribed = self._flip_doc(doc_id, src_sid, target_sid)
            self.counters.bump("fd.migrations")
            self._crash_point("resume", doc_id)
            try:
                if subscribed:
                    self._retap_migrated(doc_id)
            except (RpcError, OSError, ConnectionError) as exc:
                if not (dst.alive() and dst.ping()):
                    # Target died AFTER the flip: its log already holds
                    # the doc's live span — re-orphan it from there
                    # (exactly what _abort_add_shard does for earlier
                    # movers) and abort the expansion.
                    self._unflip_to_orphan(doc_id, target_sid)
                    raise MigrationAborted(
                        f"target shard {target_sid} died resuming "
                        f"{doc_id!r}: {exc}") from exc
                # Transient re-tap failure on a live target: the client
                # drivers' own demote/re-subscribe path self-heals.
                self._mc.logger.send({
                    "eventName": "migrationRetapFailed", "doc": doc_id,
                    "error": str(exc)})
            try:
                src.request("retire_doc", {"doc": doc_id})
                self._purge_tap(src_sid, doc_id, src)
            except _TRANSPORT_ERRORS as exc:
                # Post-flip source death: its OTHER docs fail over
                # normally; this doc already lives on the target.
                self._mc.logger.send({
                    "eventName": "retireAfterFlipFailed", "doc": doc_id,
                    "shard": src_sid, "error": str(exc)})
                self._check_shard(src_sid)
            return True
        raise MigrationAborted(
            f"could not migrate {doc_id!r} to {target_sid}: source kept "
            "dying")

    def _unflip_to_orphan(self, doc_id: str, dead_target: str) -> None:
        """Undo a flip whose target died: route falls back to rendezvous
        and the doc adopts from the dead target's log (the live span is
        there — the import landed before the flip)."""
        with self._route_lock:
            self._overrides.pop(doc_id, None)
            self._orphans.setdefault(doc_id, dead_target)

    def _purge_tap(self, shard_id: str, doc_id: str,
                   handle: ShardHandle) -> None:
        """Migration hygiene: drop the source-side tap bookkeeping and
        event handlers for a doc that moved away — only failover's
        by-shard purge cleaned these before, so long-lived tiers rotted
        a registration per migrated subscribed doc."""
        with self._route_lock:
            self._tap_registered.discard((shard_id, doc_id))
        if handle.rpc is not None:
            handle.rpc.off("op", doc_id, self._relay_event)
            handle.rpc.off("signal", doc_id, self._relay_event)
            handle.rpc.off("demoted", doc_id, self._relay_demoted)

    def _flip_doc(self, doc_id: str, src_sid: str,
                  target_sid: str) -> bool:
        """The migration commit point, one critical section: route the
        document to the target and record the move.  Returns whether the
        doc has live subscriptions (the caller re-wires broadcast)."""
        with self._route_lock:
            self._overrides[doc_id] = target_sid
            self.migrations.append((doc_id, src_sid, target_sid))
            return bool(self._subs.get(doc_id))

    def _retap_migrated(self, doc_id: str) -> None:
        """Migration resume for a live-subscribed doc: move the tap; no
        fence event — migration does not change the storage generation
        (summaries are content-addressed and shared), so clients keep
        every cache."""
        self._ensure_tap(doc_id)

    # -- introspection ---------------------------------------------------------

    def doc_ids(self) -> List[str]:
        with self._route_lock:
            return sorted(self._docs)

    def shard_addrs(self) -> Dict[str, Tuple[str, int]]:
        """(host, port) per live shard — what an attach replica needs to
        supervise the same fleet (``FrontDoor(spawn="attach",
        attach_addrs=primary.shard_addrs())``)."""
        with self._route_lock:
            dead = set(self.router.dead())
            return {sid: handle.addr
                    for sid, handle in sorted(self._shards.items())
                    if sid not in dead}

    def stats(self) -> dict:
        with self._route_lock:
            handles = sorted(self._shards.items())
            migrations = list(self.migrations)
            fences = self.fences
        pump = self._pump
        sessions = pump.connections() if pump is not None else []
        shards = {}
        for sid, handle in handles:
            if sid in self.router.dead() or not handle.alive():
                shards[sid] = {"dead": True}
                continue
            try:
                # Bounded like a probe: an undetected-hung (SIGSTOPped)
                # shard must not stall the whole stats call for the full
                # request timeout.
                shards[sid] = handle.request(
                    "stats", {}, timeout=min(self.request_timeout, 5.0))
            except (RpcError, OSError, ConnectionError) as exc:
                shards[sid] = {"error": str(exc)}
        # Supervisor-view rollup (ISSUE 15 satellite): each shard host
        # snapshots its catchup admission counters locally, but an
        # operator watching a storm needs the TIER's overload picture in
        # one place — sum every live shard's admission counters here.
        admission: Dict[str, int] = {}
        for per_shard in shards.values():
            for key, value in (per_shard.get("admission") or {}).items():
                admission[key] = admission.get(key, 0) + int(value)
        return {
            "shards": shards,
            "alive": self.router.alive(),
            "dead": self.router.dead(),
            "router_version": self.router.version,
            "epoch": self.epoch,
            "fences": fences,
            "migrations": [list(m) for m in migrations],
            "counters": self.counters.snapshot(),
            "admission": admission,
            # per-client relay flow control health: live client
            # sessions, bytes currently queued across them, the
            # per-session budget (demotions are in counters).
            "relay": {
                "sessions": len(sessions),
                "pending_bytes": sum(s.relay_pending()
                                     for s in sessions),
                "budget_per_session": self.relay_budget,
            },
            # connection-layer health (the event-loop pump)
            "pump": {
                "accepted": pump.accepted if pump is not None else 0,
                "dropped": pump.dropped if pump is not None else 0,
                "open": len(sessions),
            },
        }


def _raise_nofile_limit() -> None:
    """Best-effort: lift the soft fd limit to the hard cap.  The
    connection-scale gate (tools/loadgen.py --connections) needs every
    fd the container will give one process; the HARD cap is a kernel/
    container fact this process cannot raise, so the bench records it
    honestly instead."""
    try:
        import resource
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < hard:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    except (ImportError, ValueError, OSError):
        pass


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        description="fluidproc front door: routing + shard supervision "
                    "over real shard-host processes")
    parser.add_argument("--dir", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7070)
    parser.add_argument("--shards", type=int, default=4)
    parser.add_argument("--heartbeat", type=float, default=1.0,
                        help="heartbeat interval in seconds (death "
                             "detection); 0 disables")
    parser.add_argument("--spawn", choices=("proc", "thread"),
                        default="proc",
                        help="shard backend: real processes, or "
                             "in-process servers (connection-scale "
                             "benches measure ONE process this way)")
    parser.add_argument("--relay-budget", type=int, default=4 << 20,
                        help="per-client broadcast relay byte budget")
    parser.add_argument("--shard-arg", action="append", default=[],
                        help="extra CLI arg forwarded to every spawned "
                             "shard-host process (repeatable)")
    args = parser.parse_args(argv)
    _raise_nofile_limit()
    door = FrontDoor(
        args.dir, n_shards=args.shards, spawn=args.spawn, host=args.host,
        port=args.port,
        heartbeat_interval=args.heartbeat if args.heartbeat > 0 else None,
        relay_budget=args.relay_budget,
        shard_args=args.shard_arg,
    )
    door.start()
    print(f"frontdoor listening on {door.host}:{door.port} "
          f"shards={door.router.alive()} pid={os.getpid()}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        door.close()


if __name__ == "__main__":
    main()
