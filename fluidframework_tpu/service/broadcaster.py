"""Single-serialization broadcast fan-out for the ordering tier (ISSUE 7;
the Redis-pub/sub Broadcaster capability of SURVEY §2.3, collapsed into
the process).

The TCP front door used to register one closure pair per (session,
document): every sequenced message was re-encoded once per subscriber —
N clients on a hot document cost N ``json.dumps`` of the same payload on
the sequencing hot path.  This module subscribes ONCE per document
channel, encodes each :class:`SequencedMessage` exactly once through
``protocol/wire.py``, and hands the identical frame bytes to every
subscribed sink (the counter-pinned serialize-once contract: M clients ×
K ops → exactly K encodes).

Backpressure: a sink accepts a frame or reports saturation
(``write_frame`` → False).  A saturated sink is **demoted** — removed
from the channel, told once via ``on_demoted`` — instead of stalling the
shard or buffering unboundedly: the client backfills from the durable
op log (its delta storage) and re-subscribes.  One laggard can never
hold back the other subscribers of its document.

Sink protocol (duck-typed; ``service/server.py``'s ``_ClientSession`` is
the production implementation):

- ``write_frame(data: bytes) -> bool`` — enqueue one encoded frame;
  False = would exceed the sink's buffer budget (demote me).
- ``write_signal(data: bytes, signal: dict) -> bool`` — same, for signal
  frames; the sink applies its per-client target filter (targeted
  signals must not reach other clients) and returns True for frames it
  filters out.
- ``on_demoted(doc_id: str, head_seq: int) -> None`` — called once,
  after removal, outside the broadcaster lock.
- ``on_fence(doc_id: str, epoch: str, head_seq: int) -> None`` — shard
  failover notification (see :meth:`refence`).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..protocol.messages import SequencedMessage
from ..protocol.wire import (WIRE_VERSION, encode_sequenced_message,
                             frame_bytes)
from ..utils.telemetry import CounterSet


class _Channel:
    """One (document, wire name) broadcast channel: a single endpoint
    subscription fanning encoded frames to every sink."""

    def __init__(self, doc_id: str, out_doc: str, endpoint) -> None:
        self.doc_id = doc_id
        self.out_doc = out_doc
        self.endpoint = endpoint
        self.sinks: List[object] = []  # guarded-by: Broadcaster._lock
        # Bound per-channel callbacks: subscribe/unsubscribe need stable
        # function identity across refence().
        self.on_op = None
        self.on_signal = None

    def wire(self, broadcaster: "Broadcaster") -> None:
        self.on_op = lambda msg: broadcaster._publish_op(self, msg)
        self.on_signal = lambda signal: broadcaster._publish_signal(
            self, signal)
        self.endpoint.subscribe(self.on_op)
        self.endpoint.subscribe_signals(self.on_signal)

    def unwire(self) -> None:
        self.endpoint.unsubscribe(self.on_op)
        self.endpoint.unsubscribe_signals(self.on_signal)


class Broadcaster:
    """Per-document fan-out with exactly-once serialization, laggard
    demotion, and failover re-attach.

    Counters (all under the one lock): ``encodes`` (op messages encoded —
    the serialize-once pin), ``writes`` (frames accepted by sinks),
    ``demotions`` (laggards removed), ``signal_encodes``, ``fences``
    (channels re-attached across a shard failover).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._channels: Dict[Tuple[str, str], _Channel] = {}  # guarded-by: _lock
        self.counters = CounterSet(
            "encodes", "writes", "demotions", "signal_encodes", "fences",
        )  # guarded-by: _lock

    # -- subscription management -----------------------------------------------

    def attach(self, doc_id: str, endpoint, sink,
               out_doc: Optional[str] = None) -> None:
        """Subscribe ``sink`` to ``doc_id``'s broadcast under the wire
        name ``out_doc`` (tenant-visible id; defaults to ``doc_id``).
        The first sink of a channel wires the single endpoint
        subscription; later sinks share it."""
        key = (doc_id, out_doc if out_doc is not None else doc_id)
        # Wire/unwire transitions happen UNDER the lock: an attach racing
        # a detach/demote/refence must never leave an orphaned-but-wired
        # channel (encoding forever for nobody) or a doubly-wired one
        # (every op delivered twice).  The subscription calls are plain
        # list operations on the sequencer — nothing blocking rides the
        # critical section.
        with self._lock:
            channel = self._channels.get(key)
            if channel is None:
                channel = _Channel(key[0], key[1], endpoint)
                self._channels[key] = channel
                channel.wire(self)
            if sink not in channel.sinks:
                channel.sinks.append(sink)

    def detach(self, doc_id: str, sink,
               out_doc: Optional[str] = None) -> None:
        key = (doc_id, out_doc if out_doc is not None else doc_id)
        with self._lock:
            channel = self._channels.get(key)
            if channel is None or sink not in channel.sinks:
                return
            channel.sinks.remove(sink)
            if not channel.sinks:
                del self._channels[key]
                channel.unwire()

    def detach_all(self, sink) -> None:
        """Remove a sink from every channel (session teardown)."""
        with self._lock:
            for key in [k for k, ch in self._channels.items()
                        if sink in ch.sinks]:
                channel = self._channels[key]
                channel.sinks.remove(sink)
                if not channel.sinks:
                    del self._channels[key]
                    channel.unwire()

    def docs_with_channels(self) -> List[str]:
        """Internal doc ids that currently have live broadcast channels
        — the set a shard-fence handler must re-attach (everything else
        recovers lazily on next touch)."""
        with self._lock:
            return sorted({d for d, _o in self._channels})

    def subscriber_count(self, doc_id: str,
                         out_doc: Optional[str] = None) -> int:
        key = (doc_id, out_doc if out_doc is not None else doc_id)
        with self._lock:
            channel = self._channels.get(key)
            return len(channel.sinks) if channel is not None else 0

    # -- publish (called from the sequencer broadcast chain) -------------------

    def _publish_op(self, channel: _Channel, msg: SequencedMessage) -> None:
        # ONE encode regardless of subscriber count — the whole point.
        frame = frame_bytes({
            "v": WIRE_VERSION, "event": "op", "doc": channel.out_doc,
            "msg": encode_sequenced_message(msg),
        })
        with self._lock:
            self.counters.bump("encodes")
            sinks = list(channel.sinks)
        laggards = []
        accepted = 0
        for sink in sinks:
            if sink.write_frame(frame):
                accepted += 1
            else:
                laggards.append(sink)
        with self._lock:
            self.counters.bump("writes", accepted)
        for sink in laggards:
            self._demote(channel, sink, msg.seq)

    def _publish_signal(self, channel: _Channel, signal: dict) -> None:
        frame = frame_bytes({
            "v": WIRE_VERSION, "event": "signal", "doc": channel.out_doc,
            "signal": signal,
        })
        with self._lock:
            self.counters.bump("signal_encodes")
            sinks = list(channel.sinks)
        laggards = []
        for sink in sinks:
            if not sink.write_signal(frame, signal):
                laggards.append(sink)
        # Saturated on a signal = saturated, same demotion (signals are
        # lossy-by-contract, but a full buffer means the op stream behind
        # it is stalled too).
        for sink in laggards:
            self._demote(channel, sink, -1)

    def _demote(self, channel: _Channel, sink, head_seq: int) -> None:
        """Remove a saturated sink from ONE channel and notify it once.
        Other channels the sink subscribes to are untouched (it may be
        current on them); an empty channel unwires its subscription."""
        with self._lock:
            if sink not in channel.sinks:
                return  # already demoted/detached by a racing publisher
            channel.sinks.remove(sink)
            self.counters.bump("demotions")
            if not channel.sinks:
                # Only drop the channel if this object is still the live
                # registration (a racing detach+attach may have replaced
                # it); unwire under the lock either way.
                if self._channels.get(
                        (channel.doc_id, channel.out_doc)) is channel:
                    del self._channels[(channel.doc_id, channel.out_doc)]
                channel.unwire()
        sink.on_demoted(channel.out_doc, head_seq)

    # -- failover --------------------------------------------------------------

    def refence(self, doc_id: str, endpoint, epoch: str) -> int:
        """Shard failover for ``doc_id``: move every channel of the
        document onto the recovered owner's ``endpoint`` and tell each
        sink the storage generation changed (clients unpin and drop
        pre-fence caches instead of waiting to trip over epochMismatch).
        Returns the number of sinks notified."""
        to_notify: List[Tuple[_Channel, List[object]]] = []
        with self._lock:
            moved = [ch for (d, _o), ch in self._channels.items()
                     if d == doc_id]
            if moved:
                self.counters.bump("fences")
            for channel in moved:
                # The old endpoint's orderer is fenced — unsubscribing
                # from it is a plain list removal and always safe; the
                # whole swap stays under the lock so a racing attach can
                # neither double-wire nor observe a half-moved channel.
                channel.unwire()
                channel.endpoint = endpoint
                channel.wire(self)
                to_notify.append((channel, list(channel.sinks)))
        notified = 0
        head = endpoint.head_seq if to_notify else 0
        for channel, sinks in to_notify:
            for sink in sinks:
                sink.on_fence(channel.out_doc, epoch, head)
                notified += 1
        return notified

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = self.counters.snapshot()
            out["channels"] = len(self._channels)
            out["subscriptions"] = sum(
                len(ch.sinks) for ch in self._channels.values()
            )
        return out
