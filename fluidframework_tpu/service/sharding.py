"""Sharded ordering tier: document-partitioned orderers with epoch-fenced
failover (ISSUE 7; SURVEY §2.3's Kafka-partitioned Deli sequencers,
re-shaped for the in-process/single-host deployment).

The fold tier already runs on a multi-slice mesh while every op flowed
through ONE ``LocalOrderingService`` — sequencing was the scaling wall
for heavy live traffic.  This module partitions documents across N
orderer shards behind the same ``DocumentEndpoint`` contract:

- :class:`ShardRouter` — deterministic rendezvous (highest-random-weight)
  hashing of ``doc_id`` → shard.  Every router instance over the same
  shard list computes the same owner (no coordination state to
  replicate), adding a shard moves only ~1/N documents, and removing a
  dead shard moves ONLY the dead shard's documents.
- :class:`ShardedOrderingService` — owns N :class:`LocalOrderingService`
  shards over ONE shared durable :class:`OpLog` + summary store (the
  scriptorium/historian tier the reference likewise shares behind its
  partitioned sequencers) and routes every document operation through
  the router.

Failover rides machinery that already exists.  ``kill_shard``:

1. marks the shard dead in the router (new requests route elsewhere),
2. **fences** every orderer the dead shard owned — the fence aborts any
   stamp before the durable append, so the log-append-before-broadcast
   invariant guarantees sequencing never forks: nothing a fenced orderer
   stamps becomes durable or visible,
3. bumps the **storage epoch** (deterministically derived from the old
   epoch + shard id), so every client/cache pinned to the pre-failover
   generation hits the existing ``epochMismatch`` reconnect path instead
   of silently mixing state across the fence,
4. notifies fence listeners (the network front door re-taps live
   broadcast subscriptions and pushes fence events to clients).

The re-owned documents are rebuilt lazily: the first ``endpoint()`` on
the new owner replays the durable log via ``DocumentOrderer.recover``
(single-flight — a reconnect herd costs one replay per document), and
the recovered sequencer continues the sequence exactly where the log
ends — seq numbers stay strictly contiguous per document.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..protocol.messages import RawOperation
from ..protocol.summary import SummaryStorage
from ..protocol.wire import ColumnBatch
from .oplog import OpLog
from .orderer import (DocumentEndpoint, DocumentOrderer,
                      LocalOrderingService, SubmitOutcome, submit_batches,
                      submit_column_batches, submit_mixed_batches)

#: fence listener: (dead shard id, affected doc ids, new storage epoch)
FenceListener = Callable[[str, List[str], str], None]


def fence_token(epoch: str, shard_id: str) -> str:
    """Deterministic next storage epoch for fencing ``shard_id`` out of a
    tier whose current epoch is ``epoch`` — ONE derivation shared by the
    in-proc tier and the fluidproc front door, because byte-identical
    fence epochs across tiers are part of the failover parity bar."""
    return hashlib.sha256(
        b"fence\x00" + epoch.encode("utf-8")
        + b"\x00" + shard_id.encode("utf-8")
    ).hexdigest()


def rendezvous_score(doc_id: str, shard_id: str) -> int:
    """Deterministic 64-bit weight of (document, shard) — sha256-based so
    every process/run agrees without shared state, and uncorrelated
    across shards so each document's preference list is an independent
    permutation (what makes reassignment move only ~1/N docs)."""
    h = hashlib.sha256(
        doc_id.encode("utf-8") + b"\x00" + shard_id.encode("utf-8")
    )
    return int.from_bytes(h.digest()[:8], "big")


class ShardRouter:
    """Rendezvous-hashing document → shard ownership with liveness.

    Thread-safe; owners are computed, never stored, so there is no
    assignment table to migrate or corrupt — liveness (the dead set) is
    the only mutable state.
    """

    def __init__(self, shard_ids: List[str]) -> None:
        if not shard_ids:
            raise ValueError("router needs at least one shard")
        if len(set(shard_ids)) != len(shard_ids):
            raise ValueError(f"duplicate shard ids: {shard_ids}")
        self._lock = threading.Lock()
        self._shard_ids: List[str] = list(shard_ids)  # guarded-by: _lock
        self._dead: set = set()  # guarded-by: _lock
        #: bumped on every liveness/topology change — the invalidation
        #: token for cached doc→shard assignments
        self._version = 0  # guarded-by: _lock

    @property
    def version(self) -> int:
        """Monotone topology version: changes exactly when ``owner``
        results may change (shard death, shard add)."""
        with self._lock:
            return self._version

    def shard_ids(self) -> List[str]:
        with self._lock:
            return list(self._shard_ids)

    def alive(self) -> List[str]:
        with self._lock:
            return [s for s in self._shard_ids if s not in self._dead]

    def dead(self) -> List[str]:
        with self._lock:
            return sorted(self._dead)

    def owner(self, doc_id: str) -> str:
        """The live shard owning ``doc_id`` — highest rendezvous weight
        over the alive set (shard id tie-break for total determinism)."""
        candidates = self.alive()
        if not candidates:
            raise RuntimeError("no live shards")
        return max(
            candidates, key=lambda sid: (rendezvous_score(doc_id, sid), sid)
        )

    def mark_dead(self, shard_id: str) -> bool:
        """Remove a shard from the live set; its documents re-route on
        the next ``owner`` call.  Returns False if already dead."""
        with self._lock:
            if shard_id not in self._shard_ids:
                raise KeyError(shard_id)
            if shard_id in self._dead:
                return False
            self._dead.add(shard_id)
            if len(self._dead) == len(self._shard_ids):
                self._dead.discard(shard_id)
                raise RuntimeError("cannot kill the last live shard")
            self._version += 1
            return True

    def add_shard(self, shard_id: str) -> None:
        with self._lock:
            if shard_id in self._shard_ids:
                raise ValueError(f"shard {shard_id!r} already exists")
            self._shard_ids.append(shard_id)
            self._version += 1


class ShardedOrderingService:
    """Document-partitioned ordering tier behind the single-service
    surface: ``create_document`` / ``has_document`` / ``endpoint`` /
    ``doc_ids`` / ``storage`` / ``oplog`` — everything the front door,
    the drivers, and the catch-up service already consume — so it drops
    into ``OrderingServer``/``LocalDocumentServiceFactory`` unchanged.

    All shards share ONE durable op log and ONE summary store (the
    durable tier); each shard owns only in-memory sequencing state, which
    is exactly what makes failover a log replay instead of a data
    migration.
    """

    def __init__(
        self,
        n_shards: int = 4,
        oplog: Optional[OpLog] = None,
        storage: Optional[SummaryStorage] = None,
        throttle=None,
        shard_ids: Optional[List[str]] = None,
        faults=None,
    ) -> None:
        ids = (list(shard_ids) if shard_ids is not None
               else [f"shard{i:02d}" for i in range(n_shards)])
        self.oplog = oplog if oplog is not None else OpLog()
        self.storage = storage if storage is not None else SummaryStorage()
        self.throttle = throttle
        self.router = ShardRouter(ids)
        self._shards: Dict[str, LocalOrderingService] = {
            sid: LocalOrderingService(
                oplog=self.oplog, storage=self.storage, throttle=throttle
            )
            for sid in ids
        }
        #: same contract as LocalOrderingService.handle_tenants: the
        #: tenant grant map is service-global (content-addressed nodes are
        #: shared across shards), mutated by executor threads.
        self.handle_tenants: Dict[str, set] = {}  # guarded-by: state_lock
        #: doc_id -> owning shard id, valid while the router topology is
        #: unchanged; refreshed wholesale on fence/epoch events (shard
        #: kill, shard add) via the router version token — the columnar
        #: ingress consults this instead of rendezvous-hashing every
        #: document on every tick.
        self._owner_cache: Dict[str, str] = {}  # guarded-by: state_lock
        #: doc_id -> resolved endpoint on the cached owner, same
        #: invalidation discipline (one endpoint construction per doc
        #: per topology epoch instead of one per tick)
        self._endpoint_cache: Dict[str, DocumentEndpoint] = {}  # guarded-by: state_lock
        self._owner_cache_version = -1  # guarded-by: state_lock
        self.state_lock = threading.RLock()
        self._fence_listeners: List[FenceListener] = []  # guarded-by: state_lock
        #: monotone count of completed failovers (introspection/benches)
        self.fences = 0  # guarded-by: state_lock
        #: faultline hook: ``tick()`` consults this injector's scheduled
        #: ``shard.kill`` points (testing/faults.py) — failovers fire at
        #: deterministic virtual ticks instead of hand-placed test calls.
        self._faults = faults
        # Serializes kill_shard end-to-end: the fence-then-flip sequence
        # must not interleave with another kill (two racing kills could
        # both pass the last-live-shard check, fence their orderers, and
        # leave one fenced-but-still-routed shard behind).  Kills are
        # rare; holding one lock across the whole failover is the simple
        # correct shape.
        self._kill_lock = threading.Lock()

    # -- routing ---------------------------------------------------------------

    def shard_of(self, doc_id: str) -> str:
        """The live shard currently owning ``doc_id``."""
        return self.router.owner(doc_id)

    def shard_service(self, shard_id: str) -> LocalOrderingService:
        return self._shards[shard_id]

    def set_commit_hook(self, fn) -> None:
        """Fan the commit watcher out to every shard (streaming fold,
        ISSUE 16): whichever shard owns a document — now or after a
        failover re-own — its sequencer feeds the same hook."""
        for sid in sorted(self._shards):
            self._shards[sid].set_commit_hook(fn)

    def _owner(self, doc_id: str) -> LocalOrderingService:
        return self._shards[self.router.owner(doc_id)]

    # -- the LocalOrderingService surface --------------------------------------

    def create_document(self, doc_id: str) -> DocumentEndpoint:
        return self._owner(doc_id).create_document(doc_id)

    def has_document(self, doc_id: str) -> bool:
        # The shared oplog makes any shard's view authoritative for logged
        # docs; the storage probe additionally covers a summary-only doc
        # whose creating shard died before its first op.
        return (self._owner(doc_id).has_document(doc_id)
                or self.storage.head(doc_id) is not None)

    def _cached_owner(self, doc_id: str) -> str:
        """Owner lookup through the fence-refreshed assignment cache: a
        topology change (kill/add — the same events that bump the storage
        epoch) invalidates the whole cache via the router version, so a
        stale entry can survive at most until the next call."""
        version = self.router.version
        with self.state_lock:
            if self._owner_cache_version != version:
                self._owner_cache = {}
                self._endpoint_cache = {}
                self._owner_cache_version = version
            owner = self._owner_cache.get(doc_id)
            if owner is None:
                owner = self.router.owner(doc_id)
                self._owner_cache[doc_id] = owner
        return owner

    def shard_assignment(self, doc_ids: Sequence[str]) -> np.ndarray:
        """Vectorized doc→shard assignment: for each document, the
        ordinal of its owning shard in ``router.shard_ids()`` order —
        int32, aligned with ``doc_ids``.  Backed by the same
        fence-refreshed cache the columnar ingress routes through."""
        order = {sid: i for i, sid in enumerate(self.router.shard_ids())}
        return np.fromiter(
            (order[self._cached_owner(d)] for d in doc_ids),
            np.int32, count=len(doc_ids))

    def _endpoint_on(self, owner: LocalOrderingService,
                     doc_id: str) -> DocumentEndpoint:
        try:
            return owner.endpoint(doc_id)
        except KeyError:
            # Unknown to the owner AND absent from the log: a summary-only
            # document (created + summarized, zero ops) re-owned after a
            # failover.  Re-create its (empty) orderer on the new owner —
            # the summary store, shared and content-addressed, still holds
            # its state.
            if self.storage.head(doc_id) is None:
                raise
            try:
                return owner.create_document(doc_id)
            except ValueError:
                return owner.endpoint(doc_id)  # lost a benign create race

    def endpoint(self, doc_id: str) -> DocumentEndpoint:
        return self._endpoint_on(self._owner(doc_id), doc_id)

    def _endpoint_probe(self, doc_id: str) -> Optional[DocumentEndpoint]:
        with self.state_lock:
            return self._endpoint_cache.get(doc_id)

    def _endpoint_install(self, doc_id: str,
                          endpoint: DocumentEndpoint) -> DocumentEndpoint:
        # Re-validate the topology under the lock before caching: an
        # endpoint resolved against a pre-kill owner must not be
        # installed into a cache already refreshed to the post-kill
        # version (it would serve ShardFencedError until the NEXT
        # topology change).  On a version mismatch the endpoint is
        # returned uncached — worst case one fenced submit, and the
        # resubmit re-resolves freshly.  setdefault additionally lets a
        # concurrent resolver's endpoint win (both are stateless
        # facades).
        version = self.router.version
        with self.state_lock:
            if self._owner_cache_version != version:
                return endpoint
            return self._endpoint_cache.setdefault(doc_id, endpoint)

    def _cached_endpoint(self, doc_id: str) -> DocumentEndpoint:
        owner = self._cached_owner(doc_id)  # refreshes both caches
        endpoint = self._endpoint_probe(doc_id)
        if endpoint is not None:
            return endpoint
        # Resolve OUTSIDE the lock (endpoint() may replay a log on
        # failover recovery; state_lock stays dict-operations-only).
        return self._endpoint_install(
            doc_id, self._endpoint_on(self._shards[owner], doc_id))

    def submit_many(self, batches: Dict[str, List[RawOperation]]
                    ) -> Dict[str, SubmitOutcome]:
        """Batched ingress across the shard tier — see
        :func:`~fluidframework_tpu.service.orderer.submit_batches`: the
        per-document ``endpoint()`` route lands each batch on its
        rendezvous owner (one MSN recomputation per doc batch) and the
        whole call pays ONE flush of the shared durable log.  A document
        whose owner died re-routes and recovers lazily inside
        ``endpoint()``, so the NEXT submit after a failover lands on the
        recovered owner with no caller-side special case."""
        return submit_batches(self, batches)

    def submit_columns(self, batch: ColumnBatch,
                       doc_rows: Dict[str, np.ndarray]
                       ) -> Dict[str, SubmitOutcome]:
        """Columnar batched ingress across the shard tier — the boxed
        ``submit_many`` contract (sorted per-doc order, ONE shared-log
        flush, per-doc :class:`SubmitOutcome` isolation,
        whole-batch-resubmit on ``BatchAbortedError``) over
        :class:`ColumnBatch` row slices, routed through the
        fence-refreshed doc→shard assignment cache (array form:
        :meth:`shard_assignment`) instead of per-call rendezvous
        hashing.  A kill between cache refreshes surfaces as
        a fenced per-doc outcome; the resubmit re-resolves through the
        bumped router version."""
        return submit_column_batches(self, batch, doc_rows,
                                     endpoint_of=self._cached_endpoint)

    def submit_mixed(self, batches: Optional[Dict[str, List[RawOperation]]],
                     batch: Optional[ColumnBatch],
                     doc_rows: Optional[Dict[str, np.ndarray]]
                     ) -> Dict[str, SubmitOutcome]:
        """Both ingress shapes in ONE sorted per-doc pass (the parity
        requirement under occurrence-indexed fault schedules) — see
        :func:`~fluidframework_tpu.service.orderer.submit_mixed_batches`;
        routed through the fence-refreshed assignment cache."""
        return submit_mixed_batches(self, batches, batch, doc_rows,
                                    endpoint_of=self._cached_endpoint)

    def doc_ids(self) -> List[str]:
        ids = set(self.oplog.doc_ids())
        for shard in self._shards.values():
            ids.update(shard.doc_ids())
        return sorted(ids)

    def checkpoint(self) -> dict:
        """Flat {doc_id: orderer checkpoint} over every live shard —
        ownership is derivable (rendezvous), so it is not serialized."""
        out: dict = {}
        for sid in self.router.alive():
            out.update(self._shards[sid].checkpoint())
        return out

    @staticmethod
    def restore(
        oplog: OpLog,
        storage: SummaryStorage,
        checkpoint: dict,
        shard_ids: List[str],
    ) -> "ShardedOrderingService":
        """Rebuild a sharded service: each document's checkpoint replays
        into the shard the router assigns it to (the checkpoint may have
        been taken under a different shard list — rendezvous re-routes)."""
        service = ShardedOrderingService(
            oplog=oplog, storage=storage, shard_ids=shard_ids
        )
        routed: Dict[str, Dict[str, DocumentOrderer]] = {}
        for doc_id, doc_checkpoint in checkpoint.items():
            routed.setdefault(service.router.owner(doc_id), {})[doc_id] = \
                DocumentOrderer.restore(doc_id, oplog, storage,
                                        doc_checkpoint)
        for sid, orderers in routed.items():
            shard = service._shards[sid]
            with shard.state_lock:
                shard._orderers.update(orderers)
        return service

    # -- failover --------------------------------------------------------------

    def add_fence_listener(self, fn: FenceListener) -> None:
        with self.state_lock:
            self._fence_listeners.append(fn)

    def fence_token(self, shard_id: str) -> str:
        """Deterministic next storage epoch for killing ``shard_id``:
        derived from the current epoch so replay harnesses produce the
        same fence token on every run (no wall clock, no PRNG)."""
        return fence_token(self.storage.epoch, shard_id)

    def kill_shard(self, shard_id: str) -> List[str]:
        """Fail one shard: fence its orderers, re-route its documents,
        bump the storage epoch, notify listeners.  Returns the affected
        doc ids (documents the shard held live orderers for).  Idempotent
        — a second kill of the same shard returns [].

        Ordering matters: each orderer is fenced FIRST — fence() shares a
        lock with the durable-append subscriber, so when the sweep
        finishes every in-flight stamp has either landed (part of what
        the new owner will replay) or aborted, and the log is quiescent
        for the dead shard's documents — and only THEN does the router
        flip, so a recovery on the new owner can never replay a prefix a
        not-yet-fenced orderer still extends.  (Between fence and flip a
        submit routed to the dead shard fails fenced; clients retry
        through the re-resolved owner.)  The epoch bump then invalidates
        every pre-failover pin.
        """
        with self._kill_lock:
            dead = self._shards[shard_id]  # KeyError on unknown shard
            if shard_id in self.router.dead():
                return []
            if len(self.router.alive()) <= 1:
                raise RuntimeError("cannot kill the last live shard")
            with self.state_lock:
                listeners = list(self._fence_listeners)
            # Shard-level fence: flips the shard's refuse-new-orderers
            # flag BEFORE sweeping, so a single-flight recovery in flight
            # at kill time publishes its orderer fenced instead of live —
            # no interleaving leaves a sequencing orderer on this shard.
            affected = dead.fence_all()
            self.router.mark_dead(shard_id)
            with self.state_lock:
                self.fences += 1
            new_epoch = self.storage.bump_epoch(self.fence_token(shard_id))
            for fn in listeners:
                fn(shard_id, affected, new_epoch)
            return affected

    def tick(self, now: int) -> List[str]:
        """Fault-plan driver: execute every scheduled ``shard.kill``
        whose virtual tick has arrived (the chaos harness calls this once
        per step).  The victim is the point's named shard, else the
        current owner of its named document, else the first live shard —
        all deterministic under rendezvous routing.  Returns the affected
        doc ids across all kills this tick."""
        if self._faults is None:
            return []
        affected: List[str] = []
        for point in self._faults.due("shard.kill", now):
            if point.shard is not None:
                victim = point.shard
            elif point.doc is not None:
                victim = self.router.owner(point.doc)
            else:
                victim = self.router.alive()[0]
            if (victim in self.router.dead()
                    or len(self.router.alive()) <= 1):
                # Unexecutable kill: the victim already died, or it is
                # the last live shard (unkillable by contract).  Roll the
                # point's fired mark back so the coverage oracle REPORTS
                # it unfired, instead of crashing the harness step loop
                # or silently claiming a failover that never happened.
                self._faults.mark_unfired(point)
                continue
            affected.extend(self.kill_shard(victim))
        return affected

    # -- introspection ---------------------------------------------------------

    def shard_load(self) -> Dict[str, Tuple[int, int]]:
        """{shard_id: (live documents owned, ops sequenced across them)}
        — the balance surface the shard bench reports."""
        out: Dict[str, Tuple[int, int]] = {}
        for sid in self.router.alive():
            shard = self._shards[sid]
            with shard.state_lock:
                docs = sorted(shard._orderers)
            out[sid] = (
                len(docs), sum(self.oplog.head(d) for d in docs)
            )
        return out
