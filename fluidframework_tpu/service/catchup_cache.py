"""Seq-anchored catch-up result cache: the memory tier of the two-tier
catch-up cache (ISSUE 3; the snapshot-cache/EpochTracker capability of
SURVEY §3.2 applied to the SERVICE's own fold work).

Round-5 hardware truth: the device fold is ~free while the host pack +
extract busy time caps e2e throughput.  But the serving workload is
heavily repeated reads — thousands of loading clients catching up to the
same ``(document, seq)`` point — so the second and every later request
for an identical fold should cost a dict lookup, not a pack → fold →
extract pass.

Keying and correctness:

- Entries are keyed ``(storage epoch, doc id, base summary digest,
  base ref_seq, tail head seq)``.  The op log is append-only and the
  summary store content-addressed, so within one storage generation that
  tuple pins the exact ``(base bytes, tail bytes)`` input of the fold —
  a cached tree is byte-identical to a fresh fold by construction
  (asserted by golden + fuzz tests, cache-on vs cache-off).
- The epoch component is the EpochTracker parity: a recreated store gets
  a fresh epoch, so entries from a dead generation can never be served;
  :meth:`invalidate_epoch` additionally drops them eagerly.
- No wall-clock anywhere (fluidlint FL-DET-CLOCK applies to this path):
  recency is an LRU over dict insertion order, not timestamps, so replay
  runs are deterministic.

Concurrency — single-flight: concurrent requests for the same key are
collapsed to one fold.  The first caller ``begin()``s the key and becomes
the LEADER (it computes the fold and ``finish()``es); every other caller
``join()``s and blocks until the leader publishes — a thundering herd of
N loading clients costs exactly one device pass and N-1 waits.
"""

from __future__ import annotations

import threading
from typing import Dict, NamedTuple, Optional, Tuple

from ..protocol.summary import SummaryBlob, SummaryTree
from ..utils.telemetry import CounterSet

#: default bound for join(): a crashed leader must never hang a follower
#: forever, even at call sites that never thought about timeouts
#: (CatchupService.JOIN_TIMEOUT carries the same value; the
#: Catchup.JoinTimeout gate overrides it per service).  Pass
#: timeout=None explicitly to wait unbounded.
DEFAULT_JOIN_TIMEOUT = 60.0

#: accounting overhead charged per summary node (name + dict slot + object
#: headers) so byte budgets track real memory, not just blob payloads.
NODE_OVERHEAD = 96


def tree_nbytes(node) -> int:
    """Approximate retained bytes of a summary tree: blob payloads plus a
    flat per-node overhead.  Deterministic (no sys.getsizeof walks)."""
    if isinstance(node, SummaryBlob):
        return NODE_OVERHEAD + len(node.content)
    total = NODE_OVERHEAD
    if isinstance(node, SummaryTree):
        for name, child in node.children.items():
            total += len(name) + tree_nbytes(child)
    return total


class CachedFold(NamedTuple):
    """A served cache entry: the folded tree plus its handle, digested
    ONCE at publish time — a hit is a dict lookup, never a Merkle walk."""

    tree: SummaryTree
    handle: str


class _Flight:
    """One in-flight fold: the leader publishes, waiters block on the
    event and read the result (None = leader abandoned; waiters retry)."""

    def __init__(self) -> None:
        self.done = threading.Event()
        self.result: Optional[CachedFold] = None


class CatchupResultCache:
    """Byte-bounded LRU of folded catch-up summaries with single-flight.

    All mutation happens under one lock; ``join()`` waits outside it.
    Counters: ``hits`` / ``misses`` (lookup outcomes), ``inserts`` /
    ``evictions`` (LRU churn), ``waits`` (single-flight joins that
    blocked on a leader), ``invalidations`` (epoch drops).
    """

    def __init__(self, max_bytes: int = 256 << 20) -> None:
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        # dict insertion order IS the LRU order (touch = delete+reinsert).
        self._entries: Dict[tuple, Tuple[CachedFold, int]] = {}  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        self._flights: Dict[tuple, _Flight] = {}  # guarded-by: _lock
        self._last_epoch: Optional[str] = None  # guarded-by: _lock (invalidate fast path)
        self.counters = CounterSet(
            "hits", "misses", "inserts", "evictions", "waits",
            "invalidations",
        )  # guarded-by: _lock (CounterSet is not internally synchronized)

    # -- introspection ---------------------------------------------------------

    @property
    def current_bytes(self) -> int:
        # Under the lock (fluidrace FL-RACE-GUARD): `_bytes` is adjusted
        # in multi-step insert/evict sequences — an unlocked read could
        # observe a torn mid-eviction value.
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = self.counters.snapshot()
            out["entries"] = len(self._entries)
            out["bytes"] = self._bytes
        return out

    # -- plain lookup/insert ---------------------------------------------------

    def lookup(self, key: tuple) -> Optional[CachedFold]:
        """Cached (tree, handle) for ``key`` (LRU-touched), or None."""
        with self._lock:
            found = self._get_locked(key)
            self.counters.bump("hits" if found is not None else "misses")
            return found

    def _get_locked(self, key: tuple) -> Optional[CachedFold]:
        """Uncounted fetch + LRU touch.  Counting discipline: ``hits``
        bump wherever an entry is served; ``misses`` bump ONLY at the
        authoritative claim point (``begin``/``lookup``) — ``join`` is a
        probe and counting its empty result too would double-count every
        doc that probes first and claims right after."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        # Touch: move to the back of the insertion order.
        del self._entries[key]
        self._entries[key] = entry
        return entry[0]

    def insert(self, key: tuple, tree: SummaryTree) -> CachedFold:
        with self._lock:
            return self._insert_locked(key, tree)

    def _insert_locked(self, key: tuple, tree: SummaryTree) -> CachedFold:
        # Digest ONCE here, at publish time — every later hit serves the
        # stored handle instead of re-walking the tree.
        fold = CachedFold(tree, tree.digest())
        nbytes = tree_nbytes(tree)
        old = self._entries.pop(key, None)
        if old is not None:
            self._bytes -= old[1]
        if nbytes > self.max_bytes:
            # Never admit an entry the budget cannot hold: admitting it
            # would evict the whole cache for a single un-keepable tree.
            self.counters.bump("evictions")
            return fold
        self._entries[key] = (fold, nbytes)
        self._bytes += nbytes
        self.counters.bump("inserts")
        while self._bytes > self.max_bytes and self._entries:
            oldest = next(iter(self._entries))
            _fold, n = self._entries.pop(oldest)
            self._bytes -= n
            self.counters.bump("evictions")
        return fold

    # -- single-flight ---------------------------------------------------------

    def begin(self, key: tuple):
        """Claim a key: ``("hit", CachedFold)`` when cached, else
        ``("lead", None)`` — the caller is now the leader and MUST
        ``finish`` or ``abandon`` the key (use try/finally).  A second
        ``begin`` for a key already in flight also leads (callers
        serialized by the catch-up lock re-claim after an abandon);
        waiters use :meth:`join`."""
        with self._lock:
            found = self._get_locked(key)
            if found is not None:
                self.counters.bump("hits")
                return "hit", found
            self.counters.bump("misses")
            self._flights.setdefault(key, _Flight())
            return "lead", None

    def finish(self, key: tuple, tree: SummaryTree) -> CachedFold:
        """Leader publishes: insert into the LRU and wake every waiter.
        Returns the (tree, handle) pair so the leader reuses the one
        digest computed at insert."""
        with self._lock:
            fold = self._insert_locked(key, tree)
            flight = self._flights.pop(key, None)
        if flight is not None:
            flight.result = fold
            flight.done.set()
        return fold

    def abandon(self, key: tuple) -> None:
        """Leader failed: wake waiters empty-handed (they retry or fold
        themselves).  Safe on a key that was already finished."""
        with self._lock:
            flight = self._flights.pop(key, None)
        if flight is not None:
            flight.done.set()

    def join(self, key: tuple,
             timeout: Optional[float] = DEFAULT_JOIN_TIMEOUT,
             reap_on_timeout: bool = True) -> Optional[CachedFold]:
        """Wait-or-read: the cached (tree, handle); else, when a leader
        is in flight, block until it publishes and return its result
        (None if it abandoned or ``timeout`` elapsed); else None
        immediately.

        With ``reap_on_timeout`` (the default), a timeout presumes the
        leader crashed without reaching its finally-abandon: the flight
        is removed — only if it is still THE flight this caller waited
        on, so a fresh leader's flight is never popped — and its event
        set, waking every other waiter stuck on the dead leader (they
        retry or fold themselves).  A merely-slow leader losing its
        flight is benign: ``finish`` on a popped flight still publishes
        to the LRU.  Callers waiting a DELIBERATELY short bound (the
        server's warm priority lane giving up and taking the admission
        fold lane instead) pass ``reap_on_timeout=False`` — an impatient
        reader must not tear down a live leader's flight."""
        with self._lock:
            found = self._get_locked(key)
            if found is not None:
                self.counters.bump("hits")
                return found
            flight = self._flights.get(key)
            if flight is None:
                return None  # probe only: begin() counts the miss
            self.counters.bump("waits")
        if not flight.done.wait(timeout):
            if reap_on_timeout:
                self._reap_flight(key, flight)
            return None
        return flight.result

    def _reap_flight(self, key: tuple, flight: _Flight) -> None:
        """A waiter timed out: presume the leader crashed without its
        finally-abandon and remove the flight — one critical section,
        identity-guarded: the re-validation pops the flight only if it is
        still THE object this waiter waited on, so a fresh leader's
        flight is never reaped (pinned by
        test_join_timeout_pop_is_identity_guarded)."""
        with self._lock:
            if self._flights.get(key) is flight:
                self._flights.pop(key)
                # set() only for the flight this caller reaped: when the
                # guard fails, whoever popped it (finish/abandon/another
                # reaper) sets the event once the result is in place —
                # setting it here would wake the other waiters to
                # result=None on a COMPLETED fold.
                flight.done.set()

    # -- epoch invalidation ----------------------------------------------------

    def invalidate_epoch(self, current_epoch: str) -> int:
        """Drop every entry pinned to a DIFFERENT storage generation.
        The epoch is key component 0, so stale generations can never be
        served even without this call — eager dropping just frees the
        budget the moment the store is recreated.  Returns entries
        dropped.  O(1) while the epoch is unchanged (the hot serving
        loop calls this per request; the full scan runs only on an
        actual generation change).  Callers sharing one cache must all
        serve the SAME store: this treats every other epoch as dead, so
        two live stores alternating here would evict each other."""
        with self._lock:
            if current_epoch == self._last_epoch:
                return 0
            self._last_epoch = current_epoch
            stale = [k for k in self._entries if k[0] != current_epoch]
            for key in stale:
                _tree, n = self._entries.pop(key)
                self._bytes -= n
                self.counters.bump("invalidations")
        return len(stale)


# ---------------------------------------------------------------------------
# Tier 0: digest-gated delta-download cache (ISSUE 6)
# ---------------------------------------------------------------------------


class _DeltaEntry(NamedTuple):
    """One document's previous fold, as the delta path needs it: the
    host-side anchor that pins the fold's INPUT under the token contract,
    the device-computed state digest, and the extracted summary."""

    anchor: tuple           # (n_ops, final_seq, final_msn, attribution)
    digest: Tuple[int, int]
    tree: SummaryTree
    nbytes: int


class DeltaExportCache:
    """Tier 0 of the catch-up cache: per-document state digests + the
    previously extracted summaries, keyed by the pipeline's
    ``MergeTreeDocInput.cache_token`` (``(epoch, channel, base ref_seq,
    base summary digest)`` — the same append-only anchor tier 2 packs
    under).  The fold stays device-resident: a warm catch-up over a
    grown tail re-folds (cheaply, through the pack cache), fetches only
    the tiny digest plane, and downloads + extracts ONLY the documents
    whose digest changed — unchanged documents serve their cached
    summaries byte-identically.

    Correctness is structural, belt and braces:

    - a served summary requires the TOKEN (append-only op stream over a
      pinned base within one storage generation), the HOST ANCHOR
      (op-stream length — under append-only ops, equal length means the
      identical op list — plus ``final_seq``/``final_msn``/attribution,
      the extraction inputs that live outside device state), AND the
      64-bit device digest to all match;
    - any missing entry, anchor drift, or digest mismatch falls back to
      the full download — the delta path can lose a win, never bytes;
    - binary-stream and token-less documents bypass entirely.

    No wall-clock (LRU over insertion order); all mutation under one
    lock.  Counters: ``served`` (documents whose download+extract was
    skipped), ``changed`` (candidates whose digest moved), ``misses``
    (no candidate entry), ``inserts``/``evictions``, ``invalidations``
    (epoch drops), ``bytes_saved`` (d2h bytes the gather avoided).
    """

    def __init__(self, max_bytes: int = 256 << 20) -> None:
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        # dict insertion order IS the LRU order (touch = delete+reinsert)
        self._entries: Dict[tuple, _DeltaEntry] = {}  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        self._last_epoch: Optional[str] = None  # guarded-by: _lock
        self.counters = CounterSet(
            "served", "changed", "misses", "inserts", "evictions",
            "invalidations", "bytes_saved",
        )  # guarded-by: _lock (CounterSet is not internally synchronized)

    @staticmethod
    def _anchor(doc) -> tuple:
        return (len(doc.ops), doc.final_seq, doc.final_msn,
                bool(doc.attribution))

    @staticmethod
    def _eligible(doc) -> bool:
        # Binary-stream docs carry their ops opaquely (len(doc.ops) == 0
        # would alias every window): bypass, like tier 2 does.  Families
        # without a binary form (tree) simply lack the attribute — the
        # tier is family-generic (round 14), so probe, don't assume.
        return doc.cache_token is not None \
            and getattr(doc, "binary_ops", None) is None

    # -- introspection ---------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = self.counters.snapshot()
            out["entries"] = len(self._entries)
            out["bytes"] = self._bytes
        return out

    def note_bytes_saved(self, nbytes: int) -> None:
        """The pipeline reports the d2h bytes its gather skipped."""
        with self._lock:
            self.counters.bump("bytes_saved", int(nbytes))

    # -- the delta handshake ---------------------------------------------------

    def _candidate_locked(self, doc) -> bool:
        entry = self._entries.get(doc.cache_token)
        return entry is not None and entry.anchor == self._anchor(doc)

    def candidate(self, doc) -> bool:
        """Dispatch-time pre-check (no digest yet): could this document
        possibly be served?"""
        if not self._eligible(doc):
            return False
        with self._lock:
            return self._candidate_locked(doc)

    def any_candidate(self, docs) -> bool:
        """Chunk-level :meth:`candidate` under ONE lock acquisition (the
        dispatch hot path runs this per chunk, not per doc).  A chunk
        with zero candidates keeps the plain full-fetch pipeline —
        including its dispatch-time async host copy — so cold runs pay
        nothing for the gate."""
        with self._lock:
            for doc in docs:
                if self._eligible(doc) and self._candidate_locked(doc):
                    return True
        return False

    def _serve_one_locked(self, doc, anchor: tuple,
                          digest: Tuple[int, int]):
        entry = self._entries.get(doc.cache_token)
        if entry is None or entry.anchor != anchor:
            self.counters.bump("misses")
            return None
        if entry.digest != digest:
            self.counters.bump("changed")
            return None
        # Touch: move to the back of the insertion order.
        del self._entries[doc.cache_token]
        self._entries[doc.cache_token] = entry
        self.counters.bump("served")
        return entry.tree

    def serve(self, doc, digest: Tuple[int, int]):
        """The fetched digest arrived: the cached summary iff token +
        anchor + digest all match (LRU-touched), else None (the caller
        downloads this document's rows)."""
        if not self._eligible(doc):
            return None
        anchor = self._anchor(doc)
        with self._lock:
            return self._serve_one_locked(doc, anchor, tuple(digest))

    def serve_many(self, docs, digests) -> Dict[int, SummaryTree]:
        """Batched :meth:`serve` over a chunk's fetched ``[D, 2]`` digest
        plane: ``{doc position: cached tree}`` for every servable doc,
        ONE lock acquisition for the whole chunk (the fetch hot path
        would otherwise serialize D acquire/release cycles against the
        extract threads' ``put`` calls)."""
        out: Dict[int, SummaryTree] = {}
        with self._lock:
            for d, doc in enumerate(docs):
                if not self._eligible(doc):
                    continue
                tree = self._serve_one_locked(
                    doc, self._anchor(doc),
                    (int(digests[d, 0]), int(digests[d, 1])))
                if tree is not None:
                    out[d] = tree
        return out

    def _put_locked(self, token: tuple, entry: _DeltaEntry) -> None:
        old = self._entries.pop(token, None)
        if old is not None:
            self._bytes -= old.nbytes
        if entry.nbytes > self.max_bytes:
            self.counters.bump("evictions")
            return
        self._entries[token] = entry
        self._bytes += entry.nbytes
        self.counters.bump("inserts")
        while self._bytes > self.max_bytes and self._entries:
            oldest = next(iter(self._entries))
            dropped = self._entries.pop(oldest)
            self._bytes -= dropped.nbytes
            self.counters.bump("evictions")

    def put(self, doc, digest: Tuple[int, int], tree: SummaryTree) -> None:
        """Publish/refresh a document's entry after extraction."""
        if not self._eligible(doc):
            return
        entry = _DeltaEntry(self._anchor(doc), tuple(digest), tree,
                            tree_nbytes(tree))
        with self._lock:
            self._put_locked(doc.cache_token, entry)

    def put_many(self, items) -> None:
        """Batched :meth:`put` over ``(doc, digest, tree)`` triples: the
        entries (including the ``tree_nbytes`` walks) are built OUTSIDE
        the lock, then one acquisition publishes the whole chunk —
        symmetric with :meth:`serve_many` on the read side."""
        entries = [
            (doc.cache_token,
             _DeltaEntry(self._anchor(doc), tuple(digest), tree,
                         tree_nbytes(tree)))
            for doc, digest, tree in items if self._eligible(doc)
        ]
        if not entries:
            return
        with self._lock:
            for token, entry in entries:
                self._put_locked(token, entry)

    # -- epoch invalidation ----------------------------------------------------

    def invalidate_epoch(self, current_epoch: str) -> int:
        """Drop entries pinned to a DIFFERENT storage generation.  The
        epoch is token component 0, so a dead generation can never be
        served even without this call — eager dropping frees the budget
        (same contract as :meth:`CatchupResultCache.invalidate_epoch`,
        including the one-live-store-per-cache caveat)."""
        with self._lock:
            if current_epoch == self._last_epoch:
                return 0
            self._last_epoch = current_epoch
            stale = [k for k in self._entries if k[0] != current_epoch]
            for key in stale:
                dropped = self._entries.pop(key)
                self._bytes -= dropped.nbytes
                self.counters.bump("invalidations")
        return len(stale)


# ---------------------------------------------------------------------------
# Streaming-head publication index (ISSUE 16)
# ---------------------------------------------------------------------------


class StreamHeadIndex:
    """The streaming fold's published-summary index: per document, the
    NEWEST summary the streaming fold has durably published — ``(handle,
    ref_seq)``, pinned to a storage epoch.  Unlike the byte-bounded
    tiers, this is a tiny unbounded map (one tuple per live document):
    its job is bookkeeping, not caching — the server's streaming-head
    serve lane and the truncation cut both read it, and the lag gates
    (``stream_lag_max``) are computed against it.

    All mutation under one lock; no wall-clock anywhere (lag is measured
    in SEQUENCE NUMBERS — head seq minus published ref_seq — so replay
    runs report identical lag).  ``publish`` is monotone per document
    within an epoch: a stale ref_seq (an out-of-order worker) never
    regresses the index.  Counters: ``publishes`` (accepted),
    ``regressions`` (stale publishes ignored), ``invalidations``
    (epoch drops)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, Tuple[str, int]] = {}  # guarded-by: _lock
        self._epoch: Optional[str] = None  # guarded-by: _lock
        self._lag_max = 0  # guarded-by: _lock (high-water, seqs)
        self.counters = CounterSet(
            "publishes", "regressions", "invalidations",
        )  # guarded-by: _lock

    def publish(self, doc_id: str, handle: str, ref_seq: int,
                epoch: str) -> bool:
        """Record a durably published summary.  A first publish in a new
        epoch sweeps the old generation (same one-live-store contract as
        the cache tiers).  Returns False for a non-advancing ref_seq."""
        with self._lock:
            if epoch != self._epoch:
                if self._entries:
                    self.counters.bump("invalidations", len(self._entries))
                    self._entries.clear()
                self._epoch = epoch
                self._lag_max = 0
            old = self._entries.get(doc_id)
            if old is not None and old[1] >= ref_seq:
                self.counters.bump("regressions")
                return False
            self._entries[doc_id] = (handle, ref_seq)
            self.counters.bump("publishes")
            return True

    def get(self, doc_id: str, epoch: str) -> Optional[Tuple[str, int]]:
        """The published ``(handle, ref_seq)`` for ``doc_id`` in the
        CURRENT epoch, else None (a dead generation is never served)."""
        with self._lock:
            if epoch != self._epoch:
                return None
            return self._entries.get(doc_id)

    def published_ref_seq(self, doc_id: str) -> int:
        """The newest published ref_seq (0 when never published) — the
        truncation cut's summary anchor."""
        with self._lock:
            entry = self._entries.get(doc_id)
            return entry[1] if entry is not None else 0

    def observe_lag(self, doc_id: str, head_seq: int) -> int:
        """Record (and return) this document's current lag in sequence
        numbers: committed head minus newest published ref_seq.  Feeds
        the ``stream_lag_max`` high-water gate."""
        with self._lock:
            entry = self._entries.get(doc_id)
            lag = max(0, int(head_seq) - (entry[1] if entry else 0))
            if lag > self._lag_max:
                self._lag_max = lag
            return lag

    def invalidate_epoch(self, current_epoch: str) -> int:
        """Eager sweep on a storage generation change (parity with the
        cache tiers' contract)."""
        with self._lock:
            if current_epoch == self._epoch:
                return 0
            dropped = len(self._entries)
            self._entries.clear()
            self._epoch = current_epoch
            self._lag_max = 0
            if dropped:
                self.counters.bump("invalidations", dropped)
        return dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = self.counters.snapshot()
            out["entries"] = len(self._entries)
            out["lag_max"] = self._lag_max
        return out
