"""Canonical serving-gate registry: every ``Catchup.*`` / ``Server.*``
configuration gate the serving tier reads, with its canonical default.

Before this module, each gate's default lived at its read site — a
renamed gate or a drifted default was invisible until an operator's
config silently stopped doing anything.  Now the table below is the
single source of defaults; call sites read through the typed helpers
(which raise ``KeyError`` on an unregistered gate), and fluidlint's
``FL-DUR-GATE`` project rule statically cross-checks every
``Catchup.*``/``Server.*`` string literal in the package against this
table in both directions (unregistered read / registered-but-never-read).

Helpers take the :class:`~..utils.telemetry.ConfigProvider` explicitly —
this module holds no state and imports nothing from the serving tier, so
it can never participate in an import cycle.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

_OFF = ("off", "false", "0")
_ON = ("on", "true", "1")

#: gate key -> canonical default.  Grouped by subsystem; every entry is
#: read somewhere in the package (FL-DUR-GATE enforces it).
GATES: Dict[str, Any] = {
    # -- catch-up cache tiers (service/catchup.py) ------------------------
    "Catchup.Cache": "on",             # tier-1 folded-result cache
    "Catchup.CacheBytes": 256 << 20,
    "Catchup.PackCache": "on",         # tier-2 packed-chunk reuse
    "Catchup.PackCacheBytes": 192 << 20,
    "Catchup.DeltaDownload": "on",     # tier-0 digest-gated delta export
    "Catchup.DeltaCacheBytes": 256 << 20,
    "Catchup.DeviceResident": "on",    # tier-2.5 device-resident packs
    "Catchup.DeviceCacheBytes": 192 << 20,
    # -- fold orchestration (service/catchup.py) --------------------------
    "Catchup.JoinTimeout": 60.0,       # single-flight follower wait; 0 = never
    "Catchup.Mesh": "auto",            # multi-device fold mesh detection
    "Catchup.ProfileDir": None,        # JAX profiler trace dir (off when unset)
    # -- admission / overload (service/server.py) -------------------------
    "Catchup.MaxInflight": 4,          # ctor arg overrides per-server
    "Catchup.ShedRetryFloor": 0.05,
    "Catchup.ShedRetryCap": 5.0,
    "Catchup.DegradeAfter": 2,         # consecutive-shed window -> degraded
    "Catchup.DegradedServe": "on",     # stale-summary serving under overload
    "Catchup.WarmJoinTimeout": 5.0,    # warm-lane single-flight bound
    # -- streaming fold (service/server.py, round 16) ---------------------
    "Catchup.Stream": "off",           # opt-in: sequencer-attached fold
    "Catchup.StreamCadence": 8,
    "Catchup.StreamRetention": 64,
    # -- server lifecycle (service/server.py) -----------------------------
    "Server.DrainRetryAfter": 0.5,     # shuttingDown nack retry_after
}


def default(key: str) -> Any:
    """The canonical default for ``key``; KeyError on an unregistered
    gate (registration here IS the contract FL-DUR-GATE checks)."""
    if key not in GATES:
        raise KeyError(f"gate {key!r} is not registered in GATES")
    return GATES[key]


def raw(config, key: str) -> Any:
    """The configured raw value, or the registry default when unset."""
    value = config.raw(key)
    return default(key) if value is None else value


def get_int(config, key: str, fallback: Optional[int] = None) -> int:
    """Int gate read; ``fallback`` (a constructor argument) overrides
    the registry default, never the operator's configured value."""
    base = int(default(key) if fallback is None else fallback)
    return config.get_int(key, base)


def get_float(config, key: str, fallback: Optional[float] = None) -> float:
    """Float gate read with the tolerant-parse semantics the serving
    tier always used: unset OR unparsable -> default."""
    base = float(default(key) if fallback is None else fallback)
    value = config.raw(key)
    try:
        return base if value is None else float(value)
    except (TypeError, ValueError):
        return base


def is_on(config, key: str) -> bool:
    """Boolean gate read honoring the default's polarity: an opt-out
    gate (default on) is on unless the value says off; an opt-in gate
    (default off) is off unless the value says on.  Unrecognized text
    therefore always resolves to the default."""
    base = str(default(key)).strip().lower()
    text = str(config.raw(key) or base).strip().lower()
    if base in _OFF:
        return text in _ON
    return text not in _OFF
