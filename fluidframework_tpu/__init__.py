"""fluidframework_tpu — a TPU-native real-time collaboration framework.

A ground-up rebuild of the capabilities of Fluid Framework (reference:
wizmea/FluidFramework; see /root/repo/SURVEY.md for the structural analysis and
its provenance caveats): operation-based optimistic replication of Distributed
Data Structures under a total-order sequencing service, with summarization and
catch-up replay.

The architecture is TPU-first, not a port:

- ``protocol/``  — the op/sequence-number model (seq, clientSeq, refSeq, MSN),
  the in-process total-order sequencer, and the canonical summary-tree model.
  Pure Python, zero JAX.  (Reference capability: protocol-definitions,
  protocol-base, memory-orderer — SURVEY.md §1 layers 2–4.)
- ``dds/``       — CPU oracle implementations of the merge engines
  (SharedMap/Directory, merge-tree/SharedString, IntervalCollection,
  SharedMatrix, SharedTree).  These define the merge semantics, serve as the
  correctness oracles for the device kernels, and are the 1× CPU baseline.
  (Reference capability: packages/dds/* — SURVEY.md §2.2.)
- ``runtime/``   — the ChannelFactory plugin boundary, datastore/container
  runtime (op routing, batching, summarization).  (SURVEY.md §2.1.)
- ``ops/``       — the TPU batch-merge path: op streams packed into ragged
  tensors, JAX-traced op-fold kernels vmapped over thousands of documents.
  (The BASELINE.json north star.)
- ``parallel/``  — device mesh / sharding: pjit over a document-sharded Mesh,
  merged state assembled with XLA collectives over ICI.
- ``service/``   — ordering-service capabilities (sequencer service, durable op
  log, summary storage, catch-up service).  (SURVEY.md §2.3.)
- ``testing/``   — mock runtimes (MockContainerRuntimeFactory pattern) and the
  seeded fuzz harness with convergence asserts.  (SURVEY.md §4.)
"""

__version__ = "0.1.0"
