"""DeltaManager — the loader's op pipeline and connection state machine.

Capability-equivalent of the reference's ``DeltaManager`` +
``ConnectionManager`` + ``ConnectionStateHandler`` (SURVEY.md §2.1
container-loader; upstream paths UNVERIFIED — empty reference mount):

- presents the ordering-service surface the container runtime expects
  (``submit`` / ``subscribe`` / ``connect`` / ``log``) while owning the
  *transport* concerns beneath it;
- delivers strictly **gap-free, in-order** messages: a live message that
  skips ahead parks in a buffer while the missing range is fetched from
  delta storage (the reference's fetchMissingDeltas path);
- tracks connection state (disconnected → connecting → catching_up →
  connected) and supports explicit disconnect/reconnect against the same
  or a new document service;
- read-only mode rejects local submits at the edge (the reference's
  forced-readonly capability).
"""

from __future__ import annotations

import enum
import itertools
from typing import Callable, List, Optional

import time

from ..protocol.messages import (NackError, RawOperation, SequencedMessage,
                                 ShardFencedError)

_session_counter = itertools.count(1)


class ConnectionState(enum.Enum):
    DISCONNECTED = "disconnected"
    CONNECTING = "connecting"
    CATCHING_UP = "catching_up"
    CONNECTED = "connected"
    CLOSED = "closed"


class DeltaManager:
    """Gap-free ordered delivery + connection lifecycle over a driver.

    ``clock`` is the manager's only time source (nack retryAfter holds are
    schedule decisions).  It defaults to the wall clock for live sessions;
    replay/test harnesses inject a virtual clock so a catch-up run is
    reproducible byte-for-byte regardless of when it executes.
    """

    def __init__(self, document_service,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self._service = document_service
        self._clock = clock or time.time
        self.state = ConnectionState.DISCONNECTED
        self.client_id: Optional[str] = None
        self.read_only = False
        self.last_delivered_seq = 0
        self.gaps_repaired = 0
        self.nacks = 0
        # An op-level NACK with retryAfter holds outbound sends (can_send
        # False) until this wall-clock moment; optimistic local state stays
        # intact and everything rides out on the next writable flush.
        self.nacked_until = 0.0
        # A staleView nack means the queued wire bytes reference a view
        # below the collaboration window: resending identical bytes would
        # livelock.  The container's pump sees this flag and reconnects,
        # which discards the stale encodings and REBASES pending ops to a
        # fresh view (the existing reconnect machinery).
        self.rebase_required = False
        # The document's orderer shard was fenced (failover): retrying
        # the same connection can never succeed — the host must
        # re-resolve the document service (the router now hands out the
        # recovered owner) and reconnect with it.  Mirrors
        # rebase_required: a flag the pump reads, because the error
        # itself is a ConnectionError the wire-drain rightly swallows.
        self.fence_required = False
        self._subscribers: List[Callable[[SequencedMessage], None]] = []
        self._ahead: dict = {}  # seq -> parked out-of-order message
        self._live_fn = None
        # Connection epoch: reconnects from THIS manager resume the same
        # sequencer-side record (dedup floor preserved); a different
        # manager reusing the client id gets a fresh record.
        self._session = f"dm-{id(self)}-{next(_session_counter)}"

    # -- the service surface handed to ContainerRuntime ------------------------

    @property
    def log(self) -> List[SequencedMessage]:
        """Durable backfill feed — the tail this manager has not already
        delivered/accounted.  Reading it *consumes* the tail: its one
        consumer (``ContainerRuntime.connect``) enqueues everything
        returned, so delivery accounting advances here — otherwise the
        next live message would misread the backfilled span as a gap and
        re-fetch it all."""
        tail = self._service.delta_storage.get(
            from_seq=self.last_delivered_seq
        )
        if tail:
            self.last_delivered_seq = tail[-1].seq
        return tail

    def subscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        self._subscribers.append(fn)

    def connect(self, client_id: str) -> None:
        if self.state is ConnectionState.CLOSED:
            raise RuntimeError("delta manager is closed")
        self.state = ConnectionState.CONNECTING
        self.client_id = client_id
        conn = self._service.connection()
        self._live_fn = self._on_live
        conn.subscribe(self._live_fn)
        conn.connect(client_id, self._session)
        self.state = ConnectionState.CONNECTED

    @property
    def can_send(self) -> bool:
        """False holds ops in the runtime outbox (optimistic local state
        stays intact, everything rides out on the next writable flush) —
        both offline and read-only work this way, because rejecting at
        submit time would fire *after* the DDS's optimistic apply and
        strand a diverged replica."""
        return (self.state is ConnectionState.CONNECTED
                and not self.read_only
                and self._clock() >= self.nacked_until)

    def submit(self, op: RawOperation):
        if self.read_only:
            raise PermissionError("container is in read-only mode")
        if self.state is not ConnectionState.CONNECTED:
            raise ConnectionError(f"not connected (state={self.state.value})")
        now = self._clock()
        if now < self.nacked_until:
            # Direct submitters honor the retryAfter hold too (the flush
            # path is already gated by can_send).
            raise NackError("held by retryAfter",
                            retry_after=self.nacked_until - now)
        try:
            return self._service.connection().submit(op)
        except ShardFencedError:
            # Dead shard: the op stays queued (ConnectionError contract),
            # but flag that only a reconnect against a re-resolved
            # service can drain it.
            self.fence_required = True
            raise
        except NackError as nack:
            # The service refused the op (throttle / stale view): hold
            # sends for retryAfter; the runtime keeps the encoded ops
            # queued (NackError IS a ConnectionError) and the next
            # writable flush resends them.
            self.nacks += 1
            self.nacked_until = max(
                self.nacked_until, self._clock() + nack.retry_after
            )
            if nack.code == "staleView":
                self.rebase_required = True
            raise

    # -- signals ---------------------------------------------------------------

    def submit_signal(self, content, target_client_id: Optional[str] = None):
        self._service.connection().submit_signal(
            self.client_id, content, target_client_id
        )

    def subscribe_signals(self, fn) -> None:
        self._service.connection().subscribe_signals(fn)

    # -- lifecycle -------------------------------------------------------------

    def disconnect(self) -> None:
        if self.state in (ConnectionState.DISCONNECTED, ConnectionState.CLOSED):
            return
        conn = self._service.connection()
        if self._live_fn is not None:
            conn.unsubscribe(self._live_fn)
            self._live_fn = None
        if self.client_id is not None:
            conn.disconnect(self.client_id)
        self.state = ConnectionState.DISCONNECTED

    def reconnect(self, client_id: Optional[str] = None,
                  document_service=None) -> None:
        """Drop the old connection (if any) and establish a fresh one,
        optionally against a new resolved service (new endpoint after a
        service restart)."""
        self.disconnect()
        if document_service is not None:
            self._service = document_service
        self.connect(client_id if client_id is not None else self.client_id)
        # A successful (re)connect clears the fence flag: either the host
        # handed us the re-resolved service, or the old one still works.
        self.fence_required = False

    def close(self) -> None:
        self.disconnect()
        self.state = ConnectionState.CLOSED

    # -- ordered, gap-free delivery --------------------------------------------

    def note_delivered(self, seq: int) -> None:
        """The container loaded a summary / replayed storage up to ``seq``
        outside the live path; future live delivery resumes after it."""
        self.last_delivered_seq = max(self.last_delivered_seq, seq)

    def _on_live(self, msg: SequencedMessage) -> None:
        if msg.seq <= self.last_delivered_seq:
            return  # duplicate of something storage already served
        if msg.seq > self.last_delivered_seq + 1:
            # A gap: park this message, repair from durable storage.
            self._ahead[msg.seq] = msg
            self.state = ConnectionState.CATCHING_UP
            missing = self._service.delta_storage.get(
                from_seq=self.last_delivered_seq, to_seq=msg.seq - 1
            )
            self.gaps_repaired += 1
            for m in missing:
                self._deliver(m)
        else:
            self._deliver(msg)
        # Drain any parked messages that are now contiguous.
        while self.last_delivered_seq + 1 in self._ahead:
            self._deliver(self._ahead.pop(self.last_delivered_seq + 1))
        if self.state is ConnectionState.CATCHING_UP and not self._ahead:
            self.state = ConnectionState.CONNECTED

    def _deliver(self, msg: SequencedMessage) -> None:
        if msg.seq <= self.last_delivered_seq:
            return
        self.last_delivered_seq = msg.seq
        for fn in list(self._subscribers):
            fn(msg)
