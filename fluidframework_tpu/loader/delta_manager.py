"""DeltaManager — the loader's op pipeline and connection state machine.

Capability-equivalent of the reference's ``DeltaManager`` +
``ConnectionManager`` + ``ConnectionStateHandler`` (SURVEY.md §2.1
container-loader; upstream paths UNVERIFIED — empty reference mount):

- presents the ordering-service surface the container runtime expects
  (``submit`` / ``subscribe`` / ``connect`` / ``log``) while owning the
  *transport* concerns beneath it;
- delivers strictly **gap-free, in-order** messages: a live message that
  skips ahead parks in a buffer while the missing range is fetched from
  delta storage (the reference's fetchMissingDeltas path);
- tracks connection state (disconnected → connecting → catching_up →
  connected) and supports explicit disconnect/reconnect against the same
  or a new document service;
- read-only mode rejects local submits at the edge (the reference's
  forced-readonly capability).
"""

from __future__ import annotations

import enum
import itertools
import threading
from typing import Callable, List, Optional

import time

from ..protocol.messages import (NackError, RawOperation, SequencedMessage,
                                 ShardFencedError)

_session_counter = itertools.count(1)


class ConnectionState(enum.Enum):
    DISCONNECTED = "disconnected"
    CONNECTING = "connecting"
    CATCHING_UP = "catching_up"
    CONNECTED = "connected"
    CLOSED = "closed"


class DeltaManager:
    """Gap-free ordered delivery + connection lifecycle over a driver.

    ``clock`` is the manager's only time source (nack retryAfter holds are
    schedule decisions).  It defaults to the wall clock for live sessions;
    replay/test harnesses inject a virtual clock so a catch-up run is
    reproducible byte-for-byte regardless of when it executes.
    """

    def __init__(self, document_service,
                 clock: Optional[Callable[[], float]] = None,
                 resolver: Optional[Callable[[], object]] = None,
                 retry=None, rng=None,
                 sleep: Optional[Callable[[float], None]] = None) -> None:
        import random as _random

        from ..utils.telemetry import LockedCounterSet

        self._service = document_service
        self._clock = clock or time.time
        #: re-resolves a fresh document service through the factory — the
        #: fence recovery path: after a shard failover the router hands
        #: out the recovered owner, and THIS manager re-resolves and
        #: replays its held outbound ops itself (no host polling of
        #: fence_required required; the Loader always wires this).
        self._resolver = resolver
        #: RetryPolicy for outbound submits/connects: transient transport
        #: or durability failures resend the same op (the sequencer
        #: dedups by client_seq); nacks and fences keep their own paths.
        self._retry = retry
        self._rng = rng if rng is not None else _random.Random(0)
        # Backoff actuator: a VirtualClock injects its own sleep (virtual
        # time advances, nothing blocks), live sessions really sleep.
        self._sleep = sleep if sleep is not None \
            else getattr(clock, "sleep", None) or time.sleep
        #: retry.* counters — the chaos oracle's budget-respected surface
        self.retry_counters = LockedCounterSet()
        self.state = ConnectionState.DISCONNECTED
        self.client_id: Optional[str] = None
        self.read_only = False
        self.last_delivered_seq = 0
        self.gaps_repaired = 0
        self.nacks = 0
        # An op-level NACK with retryAfter holds outbound sends (can_send
        # False) until this wall-clock moment; optimistic local state stays
        # intact and everything rides out on the next writable flush.
        self.nacked_until = 0.0
        # A staleView nack means the queued wire bytes reference a view
        # below the collaboration window: resending identical bytes would
        # livelock.  The container's pump sees this flag and reconnects,
        # which discards the stale encodings and REBASES pending ops to a
        # fresh view (the existing reconnect machinery).
        self.rebase_required = False
        # The document's orderer shard was fenced (failover): retrying
        # the same connection can never succeed — the host must
        # re-resolve the document service (the router now hands out the
        # recovered owner) and reconnect with it.  Mirrors
        # rebase_required: a flag the pump reads, because the error
        # itself is a ConnectionError the wire-drain rightly swallows.
        self.fence_required = False
        self._subscribers: List[Callable[[SequencedMessage], None]] = []
        # Delivery is serialized: live messages arrive on the driver's
        # dispatcher thread while backfills (reconnect catch-up, the log
        # property) run on the app thread — an unserialized interleave
        # can park a message in _ahead that the other thread's watermark
        # already passed, wedging the state at CATCHING_UP forever.
        # Re-entrant: gap repair delivers from inside a locked delivery.
        self._delivery_lock = threading.RLock()
        self._ahead: dict = {}  # guarded-by: _delivery_lock
        self._live_fn = None
        # Connection epoch: reconnects from THIS manager resume the same
        # sequencer-side record (dedup floor preserved); a different
        # manager reusing the client id gets a fresh record.
        self._session = f"dm-{id(self)}-{next(_session_counter)}"

    # -- the service surface handed to ContainerRuntime ------------------------

    @property
    def log(self) -> List[SequencedMessage]:
        """Durable backfill feed — the tail this manager has not already
        delivered/accounted.  Reading it *consumes* the tail: its one
        consumer (``ContainerRuntime.connect``) enqueues everything
        returned, so delivery accounting advances here — otherwise the
        next live message would misread the backfilled span as a gap and
        re-fetch it all."""
        with self._delivery_lock:
            tail = self._service.delta_storage.get(
                from_seq=self.last_delivered_seq
            )
            if tail:
                self.last_delivered_seq = tail[-1].seq
            return tail

    def subscribe(self, fn: Callable[[SequencedMessage], None]) -> None:
        self._subscribers.append(fn)

    def connect(self, client_id: str) -> None:
        if self.state is ConnectionState.CLOSED:
            raise RuntimeError("delta manager is closed")
        self.state = ConnectionState.CONNECTING
        self.client_id = client_id

        def _attach():
            conn = self._service.connection()
            self._live_fn = self._on_live
            conn.subscribe(self._live_fn)
            try:
                conn.connect(client_id, self._session)
            except BaseException:
                # Retry hygiene: a failed attach must not leave the live
                # subscription behind, or each retry would stack another
                # delivery path onto the same manager.
                conn.unsubscribe(self._live_fn)
                self._live_fn = None
                raise
        if self._retry is not None:
            self._retry.run(
                _attach, operation="connect",
                sleep=self._sleep, rng=self._rng,
                no_retry=(NackError,),
                # A fence DURING connect is retryable exactly when we can
                # re-resolve the recovered owner through the router.
                on_fence=(self._re_resolve if self._resolver is not None
                          else None),
                counters=self.retry_counters,
            )
        else:
            _attach()
        self.state = ConnectionState.CONNECTED

    def _re_resolve(self) -> None:
        """Swap in a freshly-resolved document service (the router's
        current owner for this document) — the ShardFencedError recovery
        the retry policy invokes between attempts."""
        self._service = self._resolver()

    @property
    def can_send(self) -> bool:
        """False holds ops in the runtime outbox (optimistic local state
        stays intact, everything rides out on the next writable flush) —
        both offline and read-only work this way, because rejecting at
        submit time would fire *after* the DDS's optimistic apply and
        strand a diverged replica."""
        return (self.state is ConnectionState.CONNECTED
                and not self.read_only
                and self._clock() >= self.nacked_until)

    def submit(self, op: RawOperation):
        if self.read_only:
            raise PermissionError("container is in read-only mode")
        if self.state is not ConnectionState.CONNECTED:
            raise ConnectionError(f"not connected (state={self.state.value})")
        now = self._clock()
        if now < self.nacked_until:
            # Direct submitters honor the retryAfter hold too (the flush
            # path is already gated by can_send).
            raise NackError("held by retryAfter",
                            retry_after=self.nacked_until - now)
        try:
            if self._retry is not None:
                # Bounded inline retry for transient transport/durability
                # failures (an injected oplog-append fault, a lost RPC
                # send): the same bytes resend and the sequencer's
                # client_seq dedup absorbs any duplicate.  Exhaustion
                # surfaces RetryBudgetExhaustedError — a ConnectionError,
                # so the runtime keeps the op queued for a later flush.
                # Nacks and fences fall through to the handlers below.
                return self._retry.run(
                    lambda: self._service.connection().submit(op),
                    operation="submit",
                    sleep=self._sleep, rng=self._rng,
                    no_retry=(NackError, ShardFencedError),
                    counters=self.retry_counters,
                )
            return self._service.connection().submit(op)
        except ShardFencedError:
            # Dead shard: the op stays queued (ConnectionError contract),
            # but flag that only a reconnect against a re-resolved
            # service can drain it.
            self.fence_required = True
            raise
        except NackError as nack:
            # The service refused the op (throttle / stale view): hold
            # sends for retryAfter; the runtime keeps the encoded ops
            # queued (NackError IS a ConnectionError) and the next
            # writable flush resends them.
            self.nacks += 1
            self.nacked_until = max(
                self.nacked_until, self._clock() + nack.retry_after
            )
            if nack.code == "staleView":
                self.rebase_required = True
            raise

    # -- signals ---------------------------------------------------------------

    def submit_signal(self, content, target_client_id: Optional[str] = None):
        self._service.connection().submit_signal(
            self.client_id, content, target_client_id
        )

    def subscribe_signals(self, fn) -> None:
        self._service.connection().subscribe_signals(fn)

    # -- lifecycle -------------------------------------------------------------

    def disconnect(self) -> None:
        if self.state in (ConnectionState.DISCONNECTED, ConnectionState.CLOSED):
            return
        conn = self._service.connection()
        try:
            if self._live_fn is not None:
                conn.unsubscribe(self._live_fn)
            if self.client_id is not None:
                conn.disconnect(self.client_id)
        except (ConnectionError, OSError, TimeoutError):
            # Tearing down a DEAD transport must not block moving to a
            # live one (reconnect after an RPC disconnect / fence): the
            # server reaps the dead session's quorum membership itself
            # when the socket closes.
            pass
        finally:
            self._live_fn = None
        self.state = ConnectionState.DISCONNECTED

    def reconnect(self, client_id: Optional[str] = None,
                  document_service=None) -> None:
        """Drop the old connection (if any) and establish a fresh one,
        optionally against a new resolved service (new endpoint after a
        service restart).  After a fence, no explicit service is needed:
        the manager re-resolves through its factory resolver itself —
        the router hands out the recovered owner."""
        self.disconnect()
        if document_service is not None:
            self._service = document_service
        elif self.fence_required and self._resolver is not None:
            self._re_resolve()
        self.connect(client_id if client_id is not None else self.client_id)
        # Deterministic catch-up: pull the span missed while disconnected
        # from durable storage NOW, instead of waiting for the next live
        # message to trigger gap repair.  Over an async transport (TCP)
        # the live tail lags the connect response — and the container's
        # reconnect protocol needs acks for already-sequenced pending ops
        # to land BEFORE it resubmits the rest, or the resubmit would
        # double-apply them.  The delivery watermark dedups any overlap
        # with the (sync or async) live feed.
        with self._delivery_lock:
            for msg in self._service.delta_storage.get(
                    from_seq=self.last_delivered_seq):
                self._deliver(msg)
        # A successful (re)connect clears the fence flag: either the host
        # handed us the re-resolved service, or the old one still works.
        self.fence_required = False

    def close(self) -> None:
        self.disconnect()
        self.state = ConnectionState.CLOSED

    # -- ordered, gap-free delivery --------------------------------------------

    def note_delivered(self, seq: int) -> None:
        """The container loaded a summary / replayed storage up to ``seq``
        outside the live path; future live delivery resumes after it."""
        with self._delivery_lock:
            self.last_delivered_seq = max(self.last_delivered_seq, seq)

    def _on_live(self, msg: SequencedMessage) -> None:
        with self._delivery_lock:
            if msg.seq <= self.last_delivered_seq:
                return  # duplicate of something storage already served
            if msg.seq > self.last_delivered_seq + 1:
                # A gap: park this message, repair from durable storage.
                self._ahead[msg.seq] = msg
                self.state = ConnectionState.CATCHING_UP
                missing = self._service.delta_storage.get(
                    from_seq=self.last_delivered_seq, to_seq=msg.seq - 1
                )
                self.gaps_repaired += 1
                for m in missing:
                    self._deliver(m)
            else:
                self._deliver(msg)
            # Drain parked messages that are now contiguous — and purge
            # stale parks a backfill already covered (a park below the
            # watermark would otherwise pin the state at CATCHING_UP
            # with no later message ever draining it).
            while self._ahead:
                nxt = min(self._ahead)
                if nxt <= self.last_delivered_seq:
                    self._ahead.pop(nxt)
                elif nxt == self.last_delivered_seq + 1:
                    self._deliver(self._ahead.pop(nxt))
                else:
                    break
            if self.state is ConnectionState.CATCHING_UP \
                    and not self._ahead:
                self.state = ConnectionState.CONNECTED

    def _deliver(self, msg: SequencedMessage) -> None:
        with self._delivery_lock:
            if msg.seq <= self.last_delivered_seq:
                return
            self.last_delivered_seq = msg.seq
            subscribers = list(self._subscribers)
        # Deliver outside any state mutation but still inside the outer
        # serialization (the lock is re-entrant): subscribers only append
        # to the runtime's inbound queue by contract.
        for fn in subscribers:
            fn(msg)
