"""Loader layer: container lifecycle, delta manager, audience, pending
state (SURVEY.md §1 layer 4 — the reference's container-loader package)."""

from .delta_manager import ConnectionState, DeltaManager
from .loader import Audience, Container, Loader

__all__ = [
    "Audience",
    "Container",
    "ConnectionState",
    "DeltaManager",
    "Loader",
]
