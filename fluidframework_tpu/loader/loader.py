"""Loader and Container — document lifecycle above the driver layer.

Capability-equivalent of the reference's ``Loader.resolve()`` /
``Container`` (SURVEY.md §2.1 container-loader, §3.2 load+catch-up path;
upstream paths UNVERIFIED — empty reference mount):

- **create**: build initial state, upload the attach summary, connect;
- **load**: latest summary → catch-up replay of the op tail from delta
  storage → live connection → connected (THE north-star client path);
- **audience**: who is in the collaboration, folded from join/leave;
- **pending state**: ``close_and_get_pending_state()`` captures unacked
  local ops; ``Loader.resolve(..., pending_state=...)`` rehydrates them
  (the reference's stashed-ops offline/crash-resume flow).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..protocol.messages import MessageType, SequencedMessage
from ..runtime.container import ContainerRuntime
from ..runtime.op_pipeline import decode_stream as _decode_stream
from ..runtime.registry import ChannelRegistry
from ..utils.telemetry import MonitoringContext, PerformanceEvent
from .delta_manager import ConnectionState, DeltaManager


class Audience:
    """Connected-client roster, folded from the sequenced join/leave stream
    (the reference's IAudience)."""

    def __init__(self) -> None:
        self._members: Dict[str, dict] = {}

    def observe(self, msg: SequencedMessage) -> None:
        if msg.type is MessageType.JOIN:
            cid = msg.contents["clientId"]
            self._members[cid] = {"clientId": cid, "joinedSeq": msg.seq}
        elif msg.type is MessageType.LEAVE:
            self._members.pop(msg.contents["clientId"], None)

    @property
    def members(self) -> List[str]:
        return sorted(self._members)

    def get(self, client_id: str) -> Optional[dict]:
        return self._members.get(client_id)


class Container:
    """One loaded document: runtime + delta manager + audience."""

    def __init__(
        self,
        doc_id: str,
        runtime: ContainerRuntime,
        delta_manager: DeltaManager,
    ) -> None:
        self.doc_id = doc_id
        self.runtime = runtime
        self.delta_manager = delta_manager
        self.audience = Audience()
        self.catchup_ops = 0  # ops replayed from delta storage at load
        # Members whose JOIN predates the loaded summary are only visible
        # in the summary's quorum — seed from it (joinedSeq unknowable).
        for cid in runtime.election.quorum:
            self.audience._members[cid] = {"clientId": cid,
                                           "joinedSeq": None}
        # Observe through the runtime so every processed message — backfill
        # and live alike — folds into the audience.
        runtime.message_observers.append(self.audience.observe)
        self.closed = False

    # -- state -----------------------------------------------------------------

    @property
    def connection_state(self) -> ConnectionState:
        return self.delta_manager.state

    @property
    def connected(self) -> bool:
        return self.delta_manager.state is ConnectionState.CONNECTED

    @property
    def client_id(self) -> Optional[str]:
        return self.delta_manager.client_id

    # -- op pumping ------------------------------------------------------------

    def drain(self) -> int:
        """Process everything queued inbound (tests/hosts drive delivery
        explicitly; a live host would pump this from its event loop).

        A staleView op-nack (queued wire bytes referencing a view below the
        collaboration window) is repaired here by reconnecting: the
        reconnect discards the stale encodings and rebases pending ops to
        a fresh view — resending identical bytes would livelock.

        A shard fence is repaired the same way, WITHOUT host polling: the
        DeltaManager flagged ``fence_required`` when a submit hit the
        dead shard, and ``reconnect()`` re-resolves the recovered owner
        through the manager's factory resolver and replays the held
        outbound ops (discard + resubmit) itself."""
        n = self.runtime.drain()
        if self.delta_manager.rebase_required:
            self.delta_manager.rebase_required = False
            self.reconnect()
            n += self.runtime.drain()
        if self.delta_manager.fence_required:
            self.reconnect()
            n += self.runtime.drain()
        return n

    # -- connection lifecycle --------------------------------------------------

    def disconnect(self) -> None:
        self.delta_manager.disconnect()

    def reconnect(self, client_id: Optional[str] = None,
                  document_service=None) -> None:
        """Reconnect and resubmit pending ops (catch-up first so acks for
        already-sequenced pending ops land, then resubmit the rest)."""
        self.delta_manager.reconnect(client_id, document_service)
        self.runtime.client_id = self.delta_manager.client_id
        self.runtime._client_ids.add(self.delta_manager.client_id)
        self.drain()
        self.discard_outbound()
        self.resubmit_pending()
        self.runtime.flush()

    def discard_outbound(self) -> None:
        """Drop the offline-held outbox and any half-sent wire messages:
        resubmit_pending re-issues every unacked op with fresh client_seqs
        under the new connection (keeping both would double-send; the old
        connection's partial chunk trains die with its LEAVE).  Unsent
        idRanges roll back into the compressor for re-attachment."""
        self.runtime.discard_outbound()

    def resubmit_pending(self, force_rebase: bool = False) -> None:
        """Re-issue every unacked op.  Meta-ops (ds/channel/blob attaches)
        first: their channels' ops must land on materialized targets."""
        self.runtime.resubmit_pending_runtime_ops()
        for ds in self.runtime.datastores.values():
            ds.resubmit_pending(force_rebase=force_rebase)

    def close(self) -> None:
        # Idempotent (fluidleak FL-LEAK-DOUBLE-CLOSE): close() is called
        # directly by hosts AND by close_and_get_pending_state(); the
        # second call must not re-run the disconnect protocol.
        if self.closed:
            return
        # Flag only after the disconnect protocol succeeds: an RpcError
        # mid-close must leave close() retryable (delta_manager.close is
        # re-entrant via its state check), not strand the subscription.
        self.delta_manager.close()
        self.closed = True

    # -- pending local state (stashed ops) -------------------------------------

    def get_pending_ops(self) -> List[dict]:
        """Unacked local channel ops in submission order.  Each op records
        the ``refSeq`` it was authored against: rehydrate re-applies it at
        exactly that point of the tail replay, because remote ops sequenced
        between authoring and the stash (e.g. removes shrinking a string)
        make stash-point positions unresolvable (load-harness-found)."""
        own_ids = sorted(
            self.runtime._client_ids - self.runtime._adopted_ids
        )
        pending = []
        for ds_id, ds in self.runtime.datastores.items():
            for channel_id, channel in ds.channels.items():
                for client_seq, contents, _meta, ref in channel._pending:
                    pending.append({
                        "clientSeq": client_seq,
                        "refSeq": ref,
                        # Every wire identity this op may sequence under:
                        # this session's own connection ids.  (Adopted
                        # prior-generation identities need no aliases
                        # here: transports submit synchronously, so a
                        # prior generation's copy either sequenced before
                        # our own rehydrate drained — acked then — or
                        # never will.  An async transport would need
                        # resubmit-time alias threading.)
                        "aliases": [[cid, client_seq] for cid in own_ids],
                        "ds": ds_id,
                        "channel": channel_id,
                        "contents": contents,
                    })
        pending.sort(key=lambda p: p["clientSeq"])
        return pending

    def close_and_get_pending_state(self) -> dict:
        """Capture everything needed to resume this session offline: the
        processed sequence point, unacked local ops, and the client ids
        they were submitted under (rehydrate uses those to drop stashed
        ops that *did* get sequenced — we just never saw the ack).
        Summary and op tail are re-fetched from the (durable) service at
        rehydrate time."""
        state = {
            "docId": self.doc_id,
            "refSeq": self.runtime.ref_seq,
            "clientIds": sorted(self.runtime._client_ids),
            "pending": self.get_pending_ops(),
        }
        self.close()
        return state


class Loader:
    """Resolves documents through a driver factory into Containers."""

    def __init__(self, factory,
                 registry: Optional[ChannelRegistry] = None,
                 mc: Optional[MonitoringContext] = None,
                 runtime_options=None,
                 clock: Optional[Callable[[], float]] = None,
                 retry=None) -> None:
        self.factory = factory
        self.registry = registry
        self.runtime_options = runtime_options
        self.mc = (mc or MonitoringContext()).child("loader")
        # Injected time source for every DeltaManager this loader wires
        # (None = wall clock).  Replay harnesses pass a virtual clock so
        # nack retryAfter holds resolve identically on every run.
        self.clock = clock
        #: RetryPolicy threaded into every DeltaManager (None = no
        #: inline retries; the runtime's flush-requeue contract still
        #: applies).  Backoff rides ``clock.sleep`` when the injected
        #: clock provides one (VirtualClock), so replay stays exact.
        self.retry = retry

    def _delta_manager(self, doc_id: str, service) -> DeltaManager:
        """One place wires every DeltaManager: the clock, the retry
        policy, and the fence resolver (re-resolving through the factory
        reaches the router's CURRENT owner — the self-healing reconnect
        after a shard failover)."""
        return DeltaManager(
            service, clock=self.clock,
            resolver=lambda: self.factory.resolve(doc_id),
            retry=self.retry,
        )

    def _new_runtime(self) -> ContainerRuntime:
        return ContainerRuntime(self.registry, options=self.runtime_options)

    # -- create (attach flow) --------------------------------------------------

    def create(
        self,
        doc_id: str,
        client_id: str,
        build: Callable[[ContainerRuntime], Any],
    ) -> Container:
        """Create a new document: ``build(runtime)`` seeds datastores and
        channels detached; their state rides the initial (attach) summary."""
        runtime = self._new_runtime()
        build(runtime)
        service = self.factory.create_document(
            doc_id, runtime.summarize(), ref_seq=0
        )
        return self._wire(doc_id, runtime, service, client_id)

    # -- load (catch-up flow) --------------------------------------------------

    def resolve(
        self,
        doc_id: str,
        client_id: Optional[str] = None,
        pending_state: Optional[dict] = None,
        stale_pending: str = "rebase",
    ) -> Container:
        """Load a document: summary + catch-up replay + live connection.
        ``client_id=None`` loads read-only-detached (e.g. replay driver).
        ``pending_state`` rehydrates a previous session's unacked ops.
        ``stale_pending``: when the stash's view has fallen below the
        collaboration window its ops cannot ship with their original view —
        ``"rebase"`` (default) regenerates them against the current view,
        ``"raise"`` surfaces StaleOpError (host decides), ``"drop"``
        discards the stashed ops and loads clean."""
        if pending_state is not None and client_id is None:
            raise ValueError("rehydrating pending state requires a live "
                             "client_id (stashed ops must be resubmitted)")
        with PerformanceEvent.timed_exec(
                self.mc.logger, "containerLoad", docId=doc_id) as perf:
            container = self._resolve(doc_id, client_id, pending_state,
                                      stale_pending)
            perf["extra"]["catchupOps"] = container.catchup_ops
        return container

    def _resolve(
        self,
        doc_id: str,
        client_id: Optional[str],
        pending_state: Optional[dict],
        stale_pending: str = "rebase",
    ) -> Container:
        if stale_pending not in ("rebase", "drop", "raise"):
            raise ValueError(
                f"stale_pending must be 'rebase', 'drop', or 'raise', "
                f"got {stale_pending!r}"
            )
        service = self.factory.resolve(doc_id)
        runtime = self._new_runtime()

        # Rehydrating: the summary must not be newer than ANY replayed op's
        # authoring view — each op re-applies at exactly its own refSeq
        # during the tail replay (remote ops sequenced between authoring
        # and the stash can shrink/shift position-carrying contents).  The
        # replay set is the stash's pending ops PLUS the crashed session's
        # own ops sequenced above the load point (their optimistic text
        # was part of later ops' views), so the load point is a fixpoint:
        # lowering it can expose own sequenced ops with still-earlier
        # authoring refs.
        stash_ref = pending_state["refSeq"] if pending_state else None
        load_ref = stash_ref
        if pending_state is not None:
            refs = [p["refSeq"] for p in pending_state["pending"]
                    if p.get("refSeq") is not None]
            if refs:
                load_ref = min([stash_ref] + refs)
        old_ids = set(pending_state.get("clientIds", [])) \
            if pending_state else set()
        summary = None
        converged = False
        # Loop to fixpoint: each refetch strictly lowers load_ref over a
        # finite tail, so termination is structural — no iteration cap
        # (ADVICE r3: a cap made legitimately deep convergent stashes fail).
        while not converged:
            summary, summary_seq = service.storage.latest(
                at_or_below=load_ref
            )
            if summary is None:
                raise KeyError(f"document {doc_id!r} has no summary "
                               f"(never attached)")
            tail = service.delta_storage.get(from_seq=summary_seq)
            if load_ref is None or not old_ids:
                converged = True
                break
            while True:
                own_refs = [
                    sub.get("refSeq", msg.ref_seq)
                    for msg, batch in _decode_stream(
                        m for m in tail
                        if m.client_id in old_ids
                        and load_ref < m.seq <= stash_ref
                        and m.type is MessageType.OP)
                    for sub in batch["ops"] if "runtime" not in sub
                ]
                lower = min(own_refs, default=load_ref)
                if lower >= load_ref:
                    converged = True
                    break
                load_ref = lower
                if load_ref < summary_seq:
                    break  # need an older summary: refetch
        runtime.load(summary)

        container = Container(doc_id, runtime,
                              self._delta_manager(doc_id, service))

        # Catch-up replay: one fetch of the whole tail, split at the
        # earliest replayed authoring point and at the stash point.  THE
        # hot loop the TPU catch-up service obsoletes when it keeps
        # summaries fresh.
        pre = [m for m in tail if load_ref is None or m.seq <= load_ref]
        mid = [m for m in tail
               if load_ref is not None and load_ref < m.seq <= stash_ref]
        post_stash = tail[len(pre) + len(mid):]
        for msg in pre:
            runtime.process(msg)
        # The mid tail counts as storage catch-up only where it is actually
        # replayed by _apply_stashed below; on drop/no-stash paths it is
        # delivered by the post-connect live drain instead (ADVICE r3).
        container.catchup_ops = len(pre)
        container.delta_manager.note_delivered(runtime.ref_seq)

        if pending_state is not None and pending_state["pending"]:
            # Stash staleness: the collaboration window moved past the
            # stash's view while the session was down.  Default ("rebase"):
            # proceed — the resubmit below regenerates each op against the
            # current view (per-DDS, segment-identity-exact for sequences).
            head_msn = max((m.min_seq for m in post_stash),
                           default=runtime.min_seq)
            if pending_state["refSeq"] < head_msn:
                # Only ops that will actually be re-applied gate the load:
                # stashed ops already in the durable tail are deduped away
                # and never need a rebase.
                sequenced = self._already_sequenced(pending_state,
                                                    post_stash)
                old_ids = pending_state.get("clientIds", [])

                def _cannot_rebase(p) -> bool:
                    ds = runtime.datastores.get(p["ds"])
                    ch = ds.channels.get(p["channel"]) if ds else None
                    # A channel attaching in the mid tail isn't
                    # materialized yet; its ops replay normally.
                    return ch is not None and not ch.can_rebase

                cannot = sorted({
                    p["channel"] for p in pending_state["pending"]
                    if not any((cid, p["clientSeq"]) in sequenced
                               for cid in old_ids)
                    and _cannot_rebase(p)
                }) if stale_pending == "rebase" else []
                if stale_pending == "drop":
                    pending_state = None
                elif stale_pending == "raise" or cannot:
                    from ..dds.shared_object import StaleOpError

                    why = (f"channels {cannot} cannot rebase their pending "
                           f"ops; " if cannot else "")
                    raise StaleOpError(
                        f"{doc_id}: stashed pending state (refSeq "
                        f"{pending_state['refSeq']}) is below the "
                        f"collaboration window ({head_msn}); {why}pass "
                        f"stale_pending='drop' to load without it"
                        + ("" if cannot else " or 'rebase' to regenerate "
                           "against the current view")
                    )

        if client_id is not None:
            # Connect first (channels need a live submit path), then re-apply
            # stashed ops INTERLEAVED with the tail between their authoring
            # points — each op resolves against exactly the view it was
            # created on (earlier stashed ops re-applied on top as pending).
            container.runtime.connect(container.delta_manager, client_id)
            if pending_state is not None:
                # Hold the auto-flush so the stashed re-submissions buffer in
                # the outbox instead of hitting the wire: they are pinned to
                # views that may lie below the live collaboration window.
                # Discard the buffered batch, adopt the crashed session's
                # client ids (ops of ours that DID sequence arrive in the
                # post-stash tail as OUR acks — nacks are synchronous at
                # submit, so the sequenced subset is always a clientSeq
                # prefix and the ack FIFOs stay ordered), catch up to head,
                # and resubmit what remains pending — ops go out pinned to
                # an in-window view, regenerated (rebased) where the
                # original view is stale.
                aliases: dict = {}
                runtime.adopt_stashed_session(
                    pending_state.get("clientIds", []), aliases
                )
                runtime._batching += 1
                try:
                    self._apply_stashed(runtime, pending_state, mid,
                                        post_stash, stash_ref, aliases)
                finally:
                    runtime._batching -= 1
                container.catchup_ops += len(mid)
                container.delta_manager.note_delivered(runtime.ref_seq)
                container.discard_outbound()
                container.drain()
                # This session's id differs from the crashed one's, so
                # old-view-pinned resubmission would lie about own-op
                # visibility: always regenerate against the current view.
                container.resubmit_pending(force_rebase=True)
            container.drain()
            container.runtime.flush()
        return container

    # -- internals -------------------------------------------------------------

    @staticmethod
    def _already_sequenced(pending_state: dict,
                           post_stash_tail: List[SequencedMessage]):
        """(old client id, clientSeq) pairs from the stash that appear in
        the durable tail — ops that DID reach the sequencer; the session
        just crashed before processing the ack.  The tail is decoded
        through the full op pipeline (grouped, compressed, AND chunked
        batches), or over-threshold batches would hide sequenced ops and
        cause a double-apply."""
        from ..runtime.op_pipeline import decode_stream

        old_ids = set(pending_state.get("clientIds", []))
        sequenced = set()
        for msg, batch in decode_stream(
                m for m in post_stash_tail
                if m.client_id in old_ids and m.type is MessageType.OP):
            for sub in batch["ops"]:
                sequenced.add((msg.client_id, sub["clientSeq"]))
        return sequenced

    def _apply_stashed(self, runtime: ContainerRuntime, pending_state: dict,
                       mid_tail: List[SequencedMessage],
                       post_stash_tail: List[SequencedMessage],
                       stash_ref: int, aliases: Dict[tuple, int]) -> None:
        """Re-apply the crashed session's ops as fresh local mutations
        (optimistic apply + submit) on exactly the state each was created
        against (the reference's PendingStateManager).

        The replay set is the stash's pending ops MERGED with the old
        session's own ops already sequenced in the mid tail — the latter
        were still pending when later ops were authored, so their
        optimistic text is part of those ops' views.  The tail between the
        load point and the stash point is applied incrementally, pausing
        at each op's authoring ``refSeq``; sequenced own copies arriving
        in the drain ack the re-applied ops through the (caller-adopted,
        incrementally filled) ``aliases`` map."""
        from ..runtime.op_pipeline import decode_stream

        old_ids = set(pending_state.get("clientIds", []))
        # Sorted once up front: the loops below run per pending op, and
        # alias-adoption order must not depend on set hash order.
        old_sorted = sorted(old_ids)
        if any(p.get("refSeq") is None for p in pending_state["pending"]):
            # Legacy stash (no per-op authoring points): previous
            # semantics — drop ops the tail will deliver, re-apply the
            # rest at the stash point.  (No aliases: adopted copies apply
            # as remote, exactly as before.)
            sequenced = self._already_sequenced(pending_state,
                                                post_stash_tail)
            for msg in mid_tail:
                runtime.process(msg)
            for p in pending_state["pending"]:
                if any((cid, p["clientSeq"]) in sequenced
                       for cid in old_sorted):
                    continue
                ds = runtime.datastores[p["ds"]]
                ds.channels[p["channel"]].apply_stashed_op(p["contents"])
            return

        def chan(p):
            ds = runtime.datastores.get(p["ds"])
            return ds.channels.get(p["channel"]) if ds is not None else None

        # Channels whose dsAttach/channelAttach echo still rides the mid
        # tail don't exist at the load point: their ops' replay refs FLOOR
        # at the attach seq (no remote channel op can precede the attach,
        # so delaying to it is exact), keeping the main drain loop from
        # overshooting later ops' authoring views.
        attach_floor: Dict[tuple, int] = {}
        for msg, batch in _decode_stream(
            m for m in mid_tail if m.type is MessageType.OP
        ):
            for sub in batch["ops"]:
                if sub.get("runtime") == "dsAttach":
                    attach_floor[(sub["ds"], None)] = msg.seq
                elif sub.get("runtime") == "channelAttach":
                    attach_floor[(sub["ds"], sub["channel"])] = msg.seq

        def replay_ref(p):
            # Channels that cannot rebase keep the documented stash-point
            # reinterpretation — re-applying at the fresh stash view is
            # their recovery semantics and keeps their resubmission off
            # the rebase path.  (All built-in DDSes, including the matrix
            # since its handle-based rebase landed, are rebasable and take
            # the exact per-op path.)
            c = chan(p)
            base = p["refSeq"] if c is None or c.can_rebase else stash_ref
            return max(
                base,
                attach_floor.get((p["ds"], None), 0),
                attach_floor.get((p["ds"], p["channel"]), 0),
            )

        own_mid: List[dict] = []
        for msg, batch in decode_stream(
            m for m in mid_tail
            if m.client_id in old_ids and m.type is MessageType.OP
        ):
            for sub in batch["ops"]:
                if "runtime" in sub:
                    continue
                entry = {
                    "clientSeq": sub["clientSeq"],
                    "refSeq": sub.get("refSeq", msg.ref_seq),
                    "ds": sub["ds"], "channel": sub["channel"],
                    "contents": sub["contents"],
                    "aliases": [[msg.client_id, sub["clientSeq"]]],
                }
                # A non-rebasable channel's own sequenced op would replay
                # AFTER its wire copy drained (it defers to the stash
                # point) — the copy already applied as remote, so
                # re-applying would double-apply.  Skip it.  (A channel
                # not yet materialized attaches in the mid tail: its ops
                # replay normally.)
                c = chan(entry)
                if c is None or c.can_rebase:
                    own_mid.append(entry)
        ops = sorted(own_mid + list(pending_state["pending"]),
                     key=lambda p: (replay_ref(p), p["clientSeq"]))
        i = 0
        for p in ops:
            ref = replay_ref(p)
            while i < len(mid_tail) and mid_tail[i].seq <= ref:
                runtime.process(mid_tail[i])
                i += 1
            channel = chan(p)
            if channel is None:
                raise KeyError(
                    f"stashed op targets unknown channel "
                    f"{p['ds']}/{p['channel']}"
                )
            channel.apply_stashed_op(p["contents"])
            new_cs = channel._pending[-1][0]
            op_aliases = p.get("aliases")
            if op_aliases is None:
                op_aliases = [[c, p["clientSeq"]] for c in old_sorted]
            for cid, cs in op_aliases:
                aliases[(cid, cs)] = new_cs
        while i < len(mid_tail):
            runtime.process(mid_tail[i])
            i += 1

    def _wire(self, doc_id: str, runtime: ContainerRuntime, service,
              client_id: str) -> Container:
        container = Container(doc_id, runtime,
                              self._delta_manager(doc_id, service))
        container.delta_manager.note_delivered(runtime.ref_seq)
        container.runtime.connect(container.delta_manager, client_id)
        container.drain()
        container.runtime.flush()
        return container
