"""Typed event emission — the capability of the reference's
``TypedEventEmitter`` (core-utils; SURVEY.md §2.1; upstream paths
UNVERIFIED — empty reference mount).

DDSes emit change events ("valueChanged", "sequenceDelta", …) that app
code and the undo-redo stack subscribe to.  Listener errors propagate —
the reference's op-reentrancy rule applies: mutating a DDS from inside its
own change event is a programming error the runtime guards against
(see ``SharedObject._emit``)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List


class EventEmitter:
    """Minimal ordered event emitter."""

    def __init__(self) -> None:
        self._listeners: Dict[str, List[Callable]] = {}

    def on(self, event: str, fn: Callable) -> Callable:
        """Subscribe; returns ``fn`` for easy unsubscription."""
        self._listeners.setdefault(event, []).append(fn)
        return fn

    def off(self, event: str, fn: Callable) -> None:
        listeners = self._listeners.get(event)
        if listeners and fn in listeners:
            listeners.remove(fn)

    def once(self, event: str, fn: Callable) -> Callable:
        def wrapper(*args: Any, **kwargs: Any):
            self.off(event, wrapper)
            return fn(*args, **kwargs)

        return self.on(event, wrapper)

    def emit(self, event: str, *args: Any, **kwargs: Any) -> None:
        for fn in list(self._listeners.get(event, [])):
            fn(*args, **kwargs)

    def listener_count(self, event: str) -> int:
        return len(self._listeners.get(event, []))
