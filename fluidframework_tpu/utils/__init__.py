"""Shared utilities: events, telemetry, configuration."""

from .events import EventEmitter

__all__ = ["EventEmitter"]
