"""Telemetry: logger tree, performance events, monitoring context.

Capability-equivalent of the reference's ``telemetry-utils`` (SURVEY.md
§2.4/§5: ``createChildLogger``, ``PerformanceEvent.timedExec``,
``MonitoringContext``/``IConfigProvider`` feature gates; upstream paths
UNVERIFIED — empty reference mount).

The logger contract is one duck-typed method — ``send(event: dict)`` —
so hosts plug in anything (stdout, a file, a metrics pipe).  Loggers
compose into a tree: children prefix a namespace and merge inherited
properties, exactly the host-injected shape the reference uses."""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class NullLogger:
    """Swallow everything (the default when hosts inject nothing)."""

    def send(self, event: dict) -> None:
        pass


class CollectingLogger:
    """Keep events in memory (tests, devtools)."""

    def __init__(self) -> None:
        self.events: List[dict] = []

    def send(self, event: dict) -> None:
        self.events.append(event)


class StreamLogger:
    """One JSON line per event (winston/Lumberjack-style sink)."""

    def __init__(self, stream=None) -> None:
        self._stream = stream if stream is not None else sys.stderr

    def send(self, event: dict) -> None:
        self._stream.write(json.dumps(event, sort_keys=True,
                                      default=str) + "\n")


class ChildLogger:
    """Namespace prefix + inherited properties over a base logger."""

    def __init__(self, base, namespace: str,
                 properties: Optional[Dict[str, Any]] = None) -> None:
        self._base = base
        self.namespace = namespace
        self.properties = properties or {}

    def send(self, event: dict) -> None:
        out = dict(self.properties)
        out.update(event)
        name = event.get("eventName", "")
        out["eventName"] = f"{self.namespace}:{name}" if name \
            else self.namespace
        self._base.send(out)


def create_child_logger(base=None, namespace: str = "",
                        properties: Optional[Dict[str, Any]] = None):
    return ChildLogger(base if base is not None else NullLogger(),
                       namespace, properties)


class PerformanceEvent:
    """Duration-measuring event: emits <name>_start / <name>_end (or
    <name>_cancel with the error) around a phase — the reference's
    ``PerformanceEvent.timedExec``."""

    @staticmethod
    @contextlib.contextmanager
    def timed_exec(logger, event_name: str, **properties):
        start = time.perf_counter()
        logger.send({"eventName": f"{event_name}_start", **properties})
        holder = {"extra": {}}
        try:
            yield holder
        except BaseException as err:
            logger.send({
                "eventName": f"{event_name}_cancel",
                "durationMs": round((time.perf_counter() - start) * 1000, 3),
                "error": repr(err),
                **properties,
            })
            raise
        logger.send({
            "eventName": f"{event_name}_end",
            "durationMs": round((time.perf_counter() - start) * 1000, 3),
            **properties,
            **holder["extra"],
        })


class CounterSet:
    """Named monotonic counters for steady-state subsystems (caches,
    retry loops): cheap bumps on the hot path, one dict snapshot for
    telemetry/bench reporting.  NOT internally synchronized — owners that
    bump from several threads do so under their own lock (the catch-up
    cache holds its LRU lock across every bump)."""

    def __init__(self, *names: str) -> None:
        self._counts: Dict[str, int] = {name: 0 for name in names}

    def bump(self, name: str, by: int = 1) -> int:
        value = self._counts.get(name, 0) + by
        self._counts[name] = value
        return value

    def get(self, name: str) -> int:
        return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        return dict(self._counts)

    def delta(self, since: Dict[str, int]) -> Dict[str, int]:
        """Counts accumulated since an earlier :meth:`snapshot`: current
        minus ``since`` per counter, zero-delta counters dropped — the
        one subtraction every per-phase attribution and replay-identity
        assertion shares instead of hand-rolling dict arithmetic.
        Counters are monotonic, so a negative delta means ``since`` came
        from a different counter set — fail loudly, not quietly."""
        out: Dict[str, int] = {}
        for name, value in self.snapshot().items():
            diff = value - since.get(name, 0)
            if diff < 0:
                raise ValueError(
                    f"counter {name!r} went backwards ({diff}): 'since' "
                    "is not an earlier snapshot of this counter set")
            if diff:
                out[name] = diff
        return out


class LockedCounterSet(CounterSet):
    """A :class:`CounterSet` with its own lock: for subsystems whose
    bumps arrive from several threads with no natural owning lock (the
    fault injector fires from client threads, the TCP reader, and server
    executor threads; retry loops bump from any caller).  Snapshot is a
    consistent point-in-time copy."""

    def __init__(self, *names: str) -> None:
        super().__init__(*names)
        self._lock = threading.Lock()

    def bump(self, name: str, by: int = 1) -> int:
        with self._lock:
            return super().bump(name, by)

    def get(self, name: str) -> int:
        with self._lock:
            return super().get(name)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return super().snapshot()


class IngressMeter:
    """Ingress-stage accounting for batched op submission: wall time,
    op/batch counts split by path (columnar vs boxed), and the wire
    footprint of encoded/decoded column batches.

    Wall-clock derived — deliberately OUTSIDE every replay-identity
    surface (two bit-identical runs will disagree on wall time); callers
    report it next to, never inside, their deterministic counters.
    """

    def __init__(self) -> None:
        self.wall_sec = 0.0
        self.columnar_ops = 0
        self.boxed_ops = 0
        self.batches = 0
        self.encode_bytes = 0
        self.decode_bytes = 0

    @contextlib.contextmanager
    def timed(self):
        """Accumulate the elapsed wall time of one ingress call."""
        start = time.perf_counter()
        try:
            yield self
        finally:
            self.wall_sec += time.perf_counter() - start

    @property
    def ops(self) -> int:
        return self.columnar_ops + self.boxed_ops

    @property
    def us_per_op(self) -> float:
        return (self.wall_sec * 1e6 / self.ops) if self.ops else 0.0

    def snapshot(self) -> Dict[str, float]:
        """The bench-report shape (``ingress_us_per_op`` et al.)."""
        return {
            "ingress_us_per_op": round(self.us_per_op, 3),
            "ingress_wall_sec": round(self.wall_sec, 6),
            "ingress_ops": self.ops,
            "columnar_ops": self.columnar_ops,
            "boxed_ops": self.boxed_ops,
            "batches": self.batches,
            "encode_bytes": self.encode_bytes,
            "decode_bytes": self.decode_bytes,
        }


class ConfigProvider:
    """Layered feature gates: explicit dict over environment variables
    (``FLUID_TPU_<KEY>``), read through typed getters — the reference's
    IConfigProvider resolved via MonitoringContext."""

    ENV_PREFIX = "FLUID_TPU_"

    def __init__(self, settings: Optional[Dict[str, Any]] = None) -> None:
        self._settings = dict(settings or {})

    def raw(self, key: str) -> Optional[Any]:
        if key in self._settings:
            return self._settings[key]
        env_key = self.ENV_PREFIX + key.replace(".", "_").upper()
        return os.environ.get(env_key)

    def get_bool(self, key: str, default: bool = False) -> bool:
        value = self.raw(key)
        if value is None:
            return default
        if isinstance(value, bool):
            return value
        return str(value).strip().lower() in ("1", "true", "yes", "on")

    def get_int(self, key: str, default: int = 0) -> int:
        value = self.raw(key)
        try:
            return int(value)
        except (TypeError, ValueError):
            return default

    def get_str(self, key: str, default: str = "") -> str:
        value = self.raw(key)
        return default if value is None else str(value)


class MonitoringContext:
    """logger + config bundle threaded through subsystems."""

    def __init__(self, logger=None,
                 config: Optional[ConfigProvider] = None) -> None:
        self.logger = logger if logger is not None else NullLogger()
        self.config = config if config is not None else ConfigProvider()

    def child(self, namespace: str,
              properties: Optional[Dict[str, Any]] = None
              ) -> "MonitoringContext":
        return MonitoringContext(
            create_child_logger(self.logger, namespace, properties),
            self.config,
        )
