"""Crash-safe append-only JSONL primitives shared by the durable stores
(file summary store, op log).

The append-only contract: writers emit one canonical-JSON record plus a
trailing newline per append.  A crash can tear the FINAL line only; torn
non-final lines are corruption and must fail loudly.
"""

from __future__ import annotations

import json
import os


def repair_jsonl_tail(path: str) -> bool:
    """Repair a crash-torn JSONL file IN PLACE before appending resumes.

    A partial final line is truncated away (the crashed append never
    acked); a valid final record missing its trailing newline gets one —
    without this, the next append would MERGE onto it, silently losing the
    new record on the following reopen and corrupting the file for good.
    Returns True if the file was modified.  Torn NON-final lines are left
    for the reader to reject."""
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        return False
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        back = min(size, 1 << 20)
        f.seek(size - back)
        tail = f.read()
        if b"\n" not in tail and back < size:
            f.seek(0)
            tail = f.read()
    nl = tail.rfind(b"\n")
    last = tail[nl + 1:]
    if not last.strip():
        return False  # clean EOF (trailing newline present)
    # Records are canonical-JSON OBJECTS (the writers' contract), so a
    # sealable final line must start with '{' AND parse: a torn prefix of
    # an object can never parse, while a torn scalar/array could (e.g.
    # '1234' torn from '123456') — without the prefix check that fragment
    # would be sealed as a valid record (ADVICE r4).
    complete = last.lstrip().startswith(b"{")
    if complete:
        try:
            json.loads(last.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            complete = False
    if not complete:
        with open(path, "r+b") as f:
            f.truncate(size - len(last))
        return True
    with open(path, "ab") as f:  # complete record, torn newline
        f.write(b"\n")
    return True


def iter_jsonl_tolerant(path: str):
    """Yield records; a torn FINAL line (crash mid-append) is dropped so a
    read-only consumer degrades to losing the last record.  A torn line
    anywhere else still raises.  Writers should call
    :func:`repair_jsonl_tail` first instead of relying on this."""
    if not os.path.exists(path):
        return
    pending = None  # one-line lookahead keeps the read streaming
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if pending is not None:
                yield json.loads(pending)  # a torn NON-final line raises
            pending = line
    if pending is not None:
        if not pending.startswith("{"):
            return  # torn fragment of an object record (objects-only contract)
        try:
            yield json.loads(pending)
        except json.JSONDecodeError:
            return
