"""Sharded ordering tier (ISSUE 7): rendezvous router determinism and
stability, the sharded service behind the single-service surface,
epoch-fenced failover (in-proc and over TCP), and the single-flight
log-replay recovery.

The load-bearing oracle: the SAME deterministic op schedule driven
through ``ShardedOrderingService(n=4)`` with a mid-run shard kill and
through a never-killed single ``LocalOrderingService`` must produce
byte-identical per-document summaries and strictly contiguous seq
numbers — the log-append-before-broadcast invariant means failover can
never fork or lose sequencing.
"""

import threading
import time

import pytest

from fluidframework_tpu.drivers.file_driver import FileSummaryStorage
from fluidframework_tpu.drivers.network_driver import (
    NetworkDocumentServiceFactory,
)
from fluidframework_tpu.protocol.messages import (MessageType, RawOperation,
                                                  ShardFencedError)
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service import orderer as orderer_mod
from fluidframework_tpu.service.orderer import LocalOrderingService
from fluidframework_tpu.service.server import OrderingServer
from fluidframework_tpu.service.sharding import (ShardedOrderingService,
                                                 ShardRouter)
from fluidframework_tpu.testing.load import (ShardedLoadSpec,
                                             run_sharded_load)


def _op(client, client_seq, ref_seq=0, contents=None):
    return RawOperation(client_id=client, client_seq=client_seq,
                        ref_seq=ref_seq, type=MessageType.OP,
                        contents=contents or {})


# --- router -------------------------------------------------------------------


def test_router_deterministic_across_instances():
    ids = ["s0", "s1", "s2", "s3"]
    a, b = ShardRouter(ids), ShardRouter(list(reversed(ids)))
    for i in range(200):
        doc = f"doc{i}"
        assert a.owner(doc) == b.owner(doc)  # order-independent too


def test_router_spreads_documents():
    router = ShardRouter([f"s{i}" for i in range(4)])
    counts = {}
    for i in range(400):
        counts[router.owner(f"doc{i}")] = \
            counts.get(router.owner(f"doc{i}"), 0) + 1
    assert len(counts) == 4
    assert min(counts.values()) > 400 // 4 // 3  # no starved shard


def test_router_add_shard_moves_about_one_over_n():
    ids = [f"s{i}" for i in range(4)]
    before = ShardRouter(ids)
    after = ShardRouter(ids + ["s4"])
    docs = [f"doc{i}" for i in range(1000)]
    moved = [d for d in docs if before.owner(d) != after.owner(d)]
    # Rendezvous: exactly the docs whose top choice is the new shard move
    # (every moved doc moves TO s4), expectation 1/5 — assert a generous
    # band and the direction invariant.
    assert 100 <= len(moved) <= 320
    assert all(after.owner(d) == "s4" for d in moved)


def test_router_kill_moves_only_dead_shards_docs():
    router = ShardRouter([f"s{i}" for i in range(4)])
    docs = [f"doc{i}" for i in range(300)]
    before = {d: router.owner(d) for d in docs}
    assert router.mark_dead("s2")
    for d in docs:
        if before[d] == "s2":
            assert router.owner(d) != "s2"  # re-owned
        else:
            assert router.owner(d) == before[d]  # untouched
    assert router.mark_dead("s2") is False  # idempotent


def test_router_refuses_to_kill_last_shard():
    router = ShardRouter(["a", "b"])
    router.mark_dead("a")
    with pytest.raises(RuntimeError):
        router.mark_dead("b")
    with pytest.raises(ValueError):
        ShardRouter(["x", "x"])


# --- sharded service surface --------------------------------------------------


def test_sharded_service_routes_and_lists():
    svc = ShardedOrderingService(n_shards=4)
    docs = [f"d{i}" for i in range(10)]
    for d in docs:
        svc.create_document(d)
        ep = svc.endpoint(d)
        ep.connect("c")
        ep.submit(_op("c", 1, ref_seq=ep.head_seq))
    assert svc.doc_ids() == sorted(docs)
    assert all(svc.has_document(d) for d in docs)
    assert not svc.has_document("nope")
    # every doc's orderer lives on exactly the shard the router names
    for d in docs:
        shard = svc.shard_service(svc.shard_of(d))
        with shard.state_lock:
            assert d in shard._orderers
    load = svc.shard_load()
    assert sum(n for n, _ in load.values()) == len(docs)
    assert sum(ops for _, ops in load.values()) == \
        sum(svc.oplog.head(d) for d in docs)


def test_sharded_vs_single_shard_oracle_no_kill():
    """Same deterministic schedule, 4 shards vs 1 service: per-document
    sequencing is independent, so the final summaries must be
    byte-identical per doc."""
    spec = dict(seed=7, docs=6, clients_per_doc=2, steps=100)
    sharded = run_sharded_load(ShardedLoadSpec(shards=4, **spec))
    single = run_sharded_load(ShardedLoadSpec(shards=1, **spec))
    assert sharded.per_doc_head == single.per_doc_head
    assert sharded.per_doc_digest == single.per_doc_digest
    assert sharded.killed_shard is None and not sharded.epoch_bumped
    # the docs really were spread: more than one shard holds orderers
    assert len([s for s, n in sharded.shard_docs.items() if n > 0]) >= 2


def test_failover_byte_identical_to_never_killed_oracle():
    """THE acceptance gate: kill 1 of 4 shards mid-traffic under
    VirtualClock; fenced clients reconnect through the epoch fence; final
    per-doc state is byte-identical to the never-killed single-shard
    oracle and seq numbers stay strictly contiguous per doc (contiguity
    is asserted inside run_sharded_load)."""
    spec = dict(seed=3, docs=8, clients_per_doc=2, steps=120)
    killed = run_sharded_load(
        ShardedLoadSpec(shards=4, kill_at=60, **spec))
    assert killed.killed_shard is not None
    assert killed.fenced_docs, "victim shard owned no documents"
    assert killed.epoch_bumped
    assert killed.reconnects >= len(killed.fenced_docs)
    # Oracle twin: no kill, ONE service — but the same clients perform a
    # voluntary reconnect at the same step (a reconnect stamps the same
    # LEAVE+JOIN whether it crosses a fence or not).
    oracle = run_sharded_load(ShardedLoadSpec(
        shards=1, scripted_reconnect_at=60,
        scripted_docs=tuple(killed.fenced_docs), **spec))
    assert killed.per_doc_head == oracle.per_doc_head
    assert killed.per_doc_digest == oracle.per_doc_digest


def test_failover_lazy_fence_reaction_converges():
    """Clients that DON'T get a fence event (in-proc, no push channel)
    discover the fence on their next submit via the DeltaManager's
    fence_required flag, reconnect through the router, and still
    converge with contiguous sequencing."""
    result = run_sharded_load(ShardedLoadSpec(
        seed=11, shards=4, docs=6, clients_per_doc=2, steps=160,
        kill_at=40, fence_reaction="lazy"))
    assert result.killed_shard is not None
    assert result.epoch_bumped
    assert result.reconnects >= 1


def test_fenced_endpoint_cannot_sequence_or_serve_head():
    svc = ShardedOrderingService(n_shards=4)
    svc.create_document("d")
    ep = svc.endpoint("d")
    ep.connect("c")
    ep.submit(_op("c", 1, ref_seq=ep.head_seq))
    stale = svc.endpoint("d")
    head_before = svc.oplog.head("d")
    svc.kill_shard(svc.shard_of("d"))
    with pytest.raises(ShardFencedError):
        stale.submit(_op("c", 2))
    with pytest.raises(ShardFencedError):
        stale.head_seq
    with pytest.raises(ShardFencedError):
        stale.connect("c2")
    stale.disconnect("c")          # teardown of a dead shard: no-op
    stale.update_ref_seq("c", 1)   # heartbeat to a dead shard: no-op
    stale.submit_signal("c", {"x": 1})  # ephemeral: dropped
    # nothing the fenced orderer did reached the durable log
    assert svc.oplog.head("d") == head_before
    # the recovered owner continues the sequence exactly
    fresh = svc.endpoint("d")
    msg = fresh.submit(_op("c", 2, ref_seq=fresh.head_seq))
    assert msg.seq == head_before + 1


def test_kill_shard_is_idempotent_and_fence_token_deterministic():
    svc = ShardedOrderingService(n_shards=4)
    svc.create_document("d")
    ep = svc.endpoint("d")
    ep.connect("c")
    victim = svc.shard_of("d")
    expected = svc.fence_token(victim)
    affected = svc.kill_shard(victim)
    assert affected == ["d"]
    assert svc.storage.epoch == expected  # derived, replayable fence
    assert svc.kill_shard(victim) == []
    assert svc.fences == 1


def test_summary_only_document_survives_failover():
    """A document created and summarized but never opped has nothing in
    the durable log; after its shard dies the new owner re-creates the
    orderer from the (shared, content-addressed) summary store."""
    svc = ShardedOrderingService(n_shards=4)
    svc.create_document("quiet")
    seeded = ContainerRuntime()
    seeded.create_datastore("ds").create_channel("sequence-tpu", "t")
    svc.storage.upload("quiet", seeded.summarize(), 0)
    svc.kill_shard(svc.shard_of("quiet"))
    assert svc.has_document("quiet")
    ep = svc.endpoint("quiet")  # re-owned from storage, empty orderer
    ep.connect("c")
    assert ep.submit(_op("c", 1, ref_seq=ep.head_seq)).seq >= 1


def test_sharded_checkpoint_restore_roundtrip():
    svc = ShardedOrderingService(n_shards=4)
    for i in range(5):
        doc = f"d{i}"
        svc.create_document(doc)
        ep = svc.endpoint(doc)
        ep.connect("c")
        for j in range(3):
            ep.submit(_op("c", j + 1, ref_seq=ep.head_seq))
    ckpt = svc.checkpoint()
    restored = ShardedOrderingService.restore(
        svc.oplog, svc.storage, ckpt,
        shard_ids=svc.router.shard_ids())
    for i in range(5):
        doc = f"d{i}"
        assert restored.endpoint(doc).head_seq == svc.endpoint(doc).head_seq
        # ownership re-derives identically (same shard list)
        assert restored.shard_of(doc) == svc.shard_of(doc)
        # sequencing resumes without re-stamping
        msg = restored.endpoint(doc).submit(
            _op("c", 4, ref_seq=restored.endpoint(doc).head_seq))
        assert msg.seq == svc.oplog.head(doc)


def test_epoch_bump_persists_in_file_storage(tmp_path):
    storage = FileSummaryStorage(str(tmp_path / "store"))
    svc = ShardedOrderingService(n_shards=2, storage=storage)
    svc.create_document("d")
    ep = svc.endpoint("d")
    ep.connect("c")
    ep.submit(_op("c", 1, ref_seq=ep.head_seq))
    svc.kill_shard(svc.shard_of("d"))
    bumped = storage.epoch
    reopened = FileSummaryStorage(str(tmp_path / "store"))
    assert reopened.epoch == bumped  # restart lands POST-fence


# --- single-flight recovery ---------------------------------------------------


def test_recovery_is_single_flight_under_a_connect_herd(monkeypatch):
    """N concurrent endpoint() calls for a log-only document replay the
    log ONCE: the first caller leads, everyone else joins its flight —
    the restructured begin/publish/abandon shape that burned the
    FL-RACE-CHECKACT suppression."""
    svc = LocalOrderingService()
    svc.create_document("doc")
    ep = svc.endpoint("doc")
    ep.connect("c")
    for i in range(10):
        ep.submit(_op("c", i + 1, ref_seq=ep.head_seq))
    # Simulate a restart: same durable log, fresh service.
    fresh = LocalOrderingService(oplog=svc.oplog, storage=svc.storage)

    calls = []
    real_recover = orderer_mod.DocumentOrderer.recover

    def slow_recover(doc_id, oplog, storage):
        calls.append(doc_id)
        time.sleep(0.15)  # widen the herd window
        return real_recover(doc_id, oplog, storage)

    monkeypatch.setattr(orderer_mod.DocumentOrderer, "recover",
                        staticmethod(slow_recover))
    endpoints = []
    errors = []

    def connect():
        try:
            endpoints.append(fresh.endpoint("doc"))
        except Exception as exc:  # pragma: no cover - surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=connect) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert errors == []
    assert len(calls) == 1, f"herd replayed {len(calls)} times"
    assert len(endpoints) == 8
    assert {e.head_seq for e in endpoints} == {svc.oplog.head("doc")}
    with fresh.state_lock:
        assert fresh._recoveries == {}  # no flight survives


def test_recovery_abandon_on_leader_failure(monkeypatch):
    """A leader that dies mid-replay wakes waiters, and the next claimer
    replays successfully (abandon/retry, not a wedged flight)."""
    svc = LocalOrderingService()
    svc.create_document("doc")
    ep = svc.endpoint("doc")
    ep.connect("c")
    ep.submit(_op("c", 1, ref_seq=ep.head_seq))
    fresh = LocalOrderingService(oplog=svc.oplog, storage=svc.storage)

    real_recover = orderer_mod.DocumentOrderer.recover
    boom = {"armed": True}

    def flaky_recover(doc_id, oplog, storage):
        if boom.pop("armed", False):
            raise RuntimeError("leader died mid-replay")
        return real_recover(doc_id, oplog, storage)

    monkeypatch.setattr(orderer_mod.DocumentOrderer, "recover",
                        staticmethod(flaky_recover))
    with pytest.raises(RuntimeError):
        fresh.endpoint("doc")
    with fresh.state_lock:
        assert fresh._recoveries == {}  # abandoned, not leaked
    assert fresh.endpoint("doc").head_seq == svc.oplog.head("doc")


def test_kill_mid_recovery_publishes_a_fenced_orderer(monkeypatch):
    """A single-flight recovery in flight on the victim shard when
    kill_shard runs must NOT install a live orderer after the fence
    sweep: the shard-level fence makes the late publish land fenced, so
    the recovering client gets ShardFencedError and re-resolves through
    the router — sequencing cannot fork."""
    seed_svc = ShardedOrderingService(n_shards=4)
    seed_svc.create_document("d")
    ep = seed_svc.endpoint("d")
    ep.connect("c")
    for i in range(4):
        ep.submit(_op("c", i + 1, ref_seq=ep.head_seq))
    # Fresh sharded service over the same durable log: every doc is
    # log-only, recovery pending.
    svc = ShardedOrderingService(
        n_shards=4, oplog=seed_svc.oplog, storage=seed_svc.storage)
    victim = svc.shard_of("d")

    real_recover = orderer_mod.DocumentOrderer.recover
    started = threading.Event()
    release = threading.Event()

    def gated_recover(doc_id, oplog, storage):
        started.set()
        assert release.wait(timeout=30)
        return real_recover(doc_id, oplog, storage)

    monkeypatch.setattr(orderer_mod.DocumentOrderer, "recover",
                        staticmethod(gated_recover))
    results = {}

    def recover_on_victim():
        try:
            results["ep"] = svc.endpoint("d")
        except Exception as exc:
            results["err"] = exc

    t = threading.Thread(target=recover_on_victim)
    t.start()
    assert started.wait(timeout=30)
    # Kill the victim while its recovery replay is mid-flight; the
    # orderer map is still empty, so the per-orderer sweep sees nothing.
    monkeypatch.setattr(orderer_mod.DocumentOrderer, "recover",
                        staticmethod(real_recover))  # new owner replays live
    svc.kill_shard(victim)
    release.set()
    t.join(timeout=30)
    assert not t.is_alive()
    # The late-published orderer must be fenced: its endpoint refuses.
    if "ep" in results:
        with pytest.raises(ShardFencedError):
            results["ep"].submit(_op("c", 5, ref_seq=0))
    # The re-resolved owner sequences, contiguously.
    fresh = svc.endpoint("d")
    msg = fresh.submit(_op("c", 5, ref_seq=fresh.head_seq))
    seqs = [m.seq for m in svc.oplog.get("d")]
    assert seqs == list(range(1, len(seqs) + 1))
    assert msg.seq == seqs[-1]


def test_server_fence_recovers_only_subscribed_docs(monkeypatch):
    """Failover cost scales with LIVE subscriptions, not shard size: the
    front door's fence handler re-attaches (and therefore replays) only
    documents with broadcast channels; idle documents recover lazily on
    next touch."""
    svc = ShardedOrderingService(n_shards=2, shard_ids=["sa", "sb"])
    srv = OrderingServer(svc, port=0)
    # find ≥2 docs owned by one shard; subscribe a fake session to ONE
    docs_on = {"sa": [], "sb": []}
    for i in range(12):
        doc = f"d{i}"
        svc.create_document(doc)
        ep = svc.endpoint(doc)
        ep.connect("c")
        ep.submit(_op("c", 1, ref_seq=ep.head_seq))
        docs_on[svc.shard_of(doc)].append(doc)
    victim = "sa" if len(docs_on["sa"]) >= 2 else "sb"
    hot, *idle = docs_on[victim]

    class _Sink:
        def write_frame(self, data):
            return True

        def write_signal(self, data, signal):
            return True

        def on_demoted(self, doc_id, head):
            pass

        def on_fence(self, doc_id, epoch, head):
            self.fenced = (doc_id, epoch)

    sink = _Sink()
    srv.broadcaster.attach(hot, svc.endpoint(hot), sink)

    recovers = []
    real_recover = orderer_mod.DocumentOrderer.recover
    monkeypatch.setattr(
        orderer_mod.DocumentOrderer, "recover",
        staticmethod(lambda d, o, s: (recovers.append(d),
                                      real_recover(d, o, s))[1]))
    svc.kill_shard(victim)
    assert recovers == [hot], (
        f"fence replayed idle docs eagerly: {recovers}")
    assert sink.fenced[0] == hot
    # idle docs still recover fine — just lazily
    assert svc.endpoint(idle[0]).head_seq == svc.oplog.head(idle[0])
    assert sorted(recovers) == sorted([hot, idle[0]])


# --- failover over TCP --------------------------------------------------------


def test_tcp_fence_event_unpins_and_broadcast_survives_failover():
    """Network clients ride the fence: the server pushes a fence event
    (driver unpins the dead generation centrally), the broadcast channel
    re-attaches to the recovered owner, and the SAME connection keeps
    submitting and receiving — reconnect-through-the-fence without a
    torn op stream."""
    svc = ShardedOrderingService(n_shards=4)
    srv = OrderingServer(svc, port=0)
    srv.start_in_thread()
    factory = NetworkDocumentServiceFactory(port=srv.port)
    try:
        seeded = ContainerRuntime()
        seeded.create_datastore("ds").create_channel("sequence-tpu", "t")
        doc = factory.create_document("net-doc", seeded.summarize())
        conn = doc.connection()
        got = []
        conn.subscribe(lambda m: got.append(m.seq))
        conn.connect("cA")
        doc.storage.latest()  # pin the pre-fence epoch
        rpc = factory._rpc
        pinned = rpc.epoch
        assert pinned is not None
        ref = conn.head_seq
        for i in range(3):
            ref = conn.submit(_op("cA", i + 1, ref_seq=ref)).seq
        svc.kill_shard(svc.shard_of("net-doc"))
        deadline = time.time() + 10
        while rpc.epoch is not None and time.time() < deadline:
            time.sleep(0.02)
        assert rpc.epoch is None, "fence event never unpinned the driver"
        assert conn.fences_seen == 1
        # same connection, recovered owner, contiguous sequencing
        msg = conn.submit(_op("cA", 4, ref_seq=ref))
        assert msg.seq == ref + 1
        deadline = time.time() + 10
        while msg.seq not in got and time.time() < deadline:
            time.sleep(0.02)
        assert msg.seq in got, "live broadcast lost across failover"
        # next storage RPC adopts the POST-fence generation
        doc.storage.latest()
        assert rpc.epoch == svc.storage.epoch != pinned
    finally:
        factory.close()
