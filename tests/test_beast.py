"""beastTest-style soak (SURVEY.md §4: merge-tree's large randomized
text-edit soak, the shape BASELINE config #1 names).

One document, thousands of sequenced random edits (inserts, removes,
annotates, obliterates), periodically window-advanced — replayed through
the CPU oracle AND the device kernel, asserting byte-identical summaries
at several checkpoints along the way and at the end.
"""

import random

from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    replay_mergetree_batch,
)
from fluidframework_tpu.protocol.messages import MessageType, SequencedMessage

ALPHABET = "abcdefghijklmnopqrstuvwxyz "


def _beast_ops(seed: int, n_ops: int, obliterate: bool):
    rng = random.Random(seed)
    ops, length, msn = [], 0, 0
    for i in range(n_ops):
        seq = i + 1
        client = f"client{i % 5}"
        # concurrency: refs lag up to 8 behind the head
        ref = max(msn, seq - 1 - rng.randint(0, 8))
        r = rng.random()
        # positions resolve in the SEQUENCED view at ref... generating
        # valid concurrent positions requires view tracking; keep refs
        # sequential for structural ops and spice with window advances.
        ref = seq - 1
        if rng.random() < 0.02:
            msn = min(seq - 1, msn + rng.randint(1, 6))
        if r < 0.55 or length < 6:
            pos = rng.randint(0, length)
            text = "".join(rng.choice(ALPHABET)
                           for _ in range(rng.randint(1, 12)))
            contents = {"kind": "insert", "pos": pos, "text": text}
            length += len(text)
        elif r < 0.75:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 10))
            contents = {"kind": "remove", "start": start, "end": end}
            length -= end - start
        elif obliterate and r < 0.85:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 10))
            contents = {"kind": "obliterate", "start": start, "end": end}
            length -= end - start
        else:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 10))
            contents = {"kind": "annotate", "start": start, "end": end,
                        "props": {rng.choice("xyz"): rng.randint(0, 4)}}
        ops.append(SequencedMessage(
            seq=seq, client_id=client, client_seq=seq, ref_seq=ref,
            min_seq=msn, type=MessageType.OP, contents=contents,
        ))
    return ops


def _checkpoint_digests(ops, points):
    """Oracle digests at each checkpoint prefix."""
    replica = SharedString("beast")
    digests = {}
    it = iter(points)
    nxt = next(it, None)
    for msg in ops:
        replica.process(msg, local=False)
        if nxt is not None and msg.seq == nxt:
            digests[nxt] = replica.summarize().digest()
            nxt = next(it, None)
    return digests, replica


def test_beast_soak_oracle_vs_kernel():
    N = 3000
    points = [500, 1500, N]
    for seed, obliterate in ((1, False), (2, True)):
        ops = _beast_ops(seed, N, obliterate)
        digests, replica = _checkpoint_digests(ops, points)
        for point in points:
            prefix = [m for m in ops if m.seq <= point]
            doc = MergeTreeDocInput(
                doc_id="beast", ops=prefix, final_seq=point,
                final_msn=max(m.min_seq for m in prefix),
            )
            [summary] = replay_mergetree_batch([doc])
            assert summary.digest() == digests[point], (
                f"seed={seed} obliterate={obliterate} checkpoint={point}: "
                f"kernel != oracle"
            )
        assert len(replica.text) > 200  # the soak built a real document


def test_beast_warm_restart_chain():
    """Catch-up chaining under soak: summarize at N/3 and 2N/3, re-enter
    each summary as the next leg's base — byte-identical to the one-shot
    fold at the end."""
    import json

    N = 1800
    ops = _beast_ops(7, N, obliterate=True)
    digests, _ = _checkpoint_digests(ops, [N])

    legs = [(0, N // 3), (N // 3, 2 * N // 3), (2 * N // 3, N)]
    base_records, base_seq, base_msn = None, 0, 0
    summary = None
    for lo, hi in legs:
        leg_ops = [m for m in ops if lo < m.seq <= hi]
        doc = MergeTreeDocInput(
            doc_id="beast", ops=leg_ops,
            base_records=base_records, base_seq=base_seq, base_msn=base_msn,
            final_seq=hi, final_msn=max(m.min_seq for m in leg_ops),
        )
        [summary] = replay_mergetree_batch([doc])
        base_records = json.loads(summary.blob_bytes("body"))
        header = json.loads(summary.blob_bytes("header"))
        base_seq, base_msn = header["seq"], header["minSeq"]
    assert summary.digest() == digests[N]
