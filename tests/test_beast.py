"""beastTest-style soak (SURVEY.md §4: merge-tree's large randomized
text-edit soak, the shape BASELINE config #1 names).

Multiple clients drive one document through thousands of random edits
(inserts, removes, annotates, obliterates) via the mock factory with
RANDOM PARTIAL DELIVERY, so sequenced ops carry genuinely lagged refs —
the generator tracks per-client sequenced views instead of faking
``ref = seq - 1`` (VERDICT r4 weak #2: the old soak's concurrency knob
was dead code).  The resulting log replays through the CPU oracle, the
device kernel, and the Pallas-interpret fold with byte-identical
summaries asserted at checkpoints and at the end.
"""

import json
import random

from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    replay_mergetree_batch,
)
from fluidframework_tpu.testing.mocks import (
    MockContainerRuntimeFactory,
    channel_log,
)

ALPHABET = "abcdefghijklmnopqrstuvwxyz "

#: the soak is only a concurrency soak if a real fraction of structural
#: ops were authored against a lagged view (VERDICT r4 item 5)
MIN_LAGGED_FRACTION = 0.30


def _beast_log(seed: int, n_ops: int, obliterate: bool, n_clients: int = 4):
    """Drive ``n_clients`` SharedString replicas through ``n_ops`` random
    local edits with random partial delivery; returns the sequenced
    channel log (genuine concurrent refs) after asserting the live
    replicas converged."""
    rng = random.Random(seed)
    factory = MockContainerRuntimeFactory()
    replicas = []
    for i in range(n_clients):
        client = factory.create_client(f"client{i}")
        replicas.append(client.attach(SharedString("beast")))

    for _ in range(n_ops):
        replica = replicas[rng.randrange(n_clients)]
        n = len(replica)
        r = rng.random()
        if r < 0.55 or n < 6:
            pos = rng.randint(0, n)
            text = "".join(rng.choice(ALPHABET)
                           for _ in range(rng.randint(1, 12)))
            replica.insert_text(pos, text)
        elif r < 0.75:
            start = rng.randint(0, n - 2)
            replica.remove_range(start, min(n, start + rng.randint(1, 10)))
        elif obliterate and r < 0.85:
            start = rng.randint(0, n - 2)
            replica.obliterate_range(
                start, min(n, start + rng.randint(1, 10)))
        else:
            start = rng.randint(0, n - 2)
            end = min(n, start + rng.randint(1, 10))
            replica.annotate_range(
                start, end, {rng.choice("xyz"): rng.randint(0, 4)})
        # Random partial delivery keeps a backlog alive, so concurrent
        # submissions genuinely lag the head; occasional full syncs +
        # MSN advances exercise zamboni mid-soak.
        if rng.random() < 0.22 and factory.pending_count:
            factory.process_some_messages(
                rng.randint(1, max(1, factory.pending_count // 2)))
        if rng.random() < 0.01:
            factory.process_all_messages()
            factory.advance_min_seq()
    factory.process_all_messages()
    digests = {r.summarize().digest() for r in replicas}
    assert len(digests) == 1, f"live replicas diverged (seed={seed})"
    log = channel_log(factory, "beast")
    assert len(log) == n_ops
    return log, replicas[0]


def _lagged_fraction(log) -> float:
    structural = [m for m in log
                  if m.contents.get("kind") in
                  ("insert", "remove", "obliterate")]
    lagged = [m for m in structural if m.ref_seq < m.seq - 1]
    return len(lagged) / max(1, len(structural))


def _oracle_digests(log, points):
    """Fresh catch-up oracle digests at each checkpoint prefix."""
    replica = SharedString("beast")
    digests = {}
    it = iter(points)
    nxt = next(it, None)
    for msg in log:
        replica.process(msg, local=False)
        if nxt is not None and msg.seq >= nxt:
            digests[nxt] = replica.summarize().digest()
            nxt = next(it, None)
    return digests, replica


def _checkpoints(log, n_points):
    """Checkpoint SEQS at evenly spaced log positions (seqs are not
    contiguous: join messages and other clients' interleavings consume
    sequence numbers too)."""
    idxs = [len(log) * (i + 1) // n_points - 1 for i in range(n_points)]
    return [log[i].seq for i in idxs]


def test_beast_soak_oracle_vs_kernel():
    N = 3000
    for seed, obliterate in ((11, False), (12, True)):
        log, live = _beast_log(seed, N, obliterate)
        frac = _lagged_fraction(log)
        assert frac >= MIN_LAGGED_FRACTION, (
            f"seed={seed}: only {frac:.0%} of structural ops lagged — "
            f"the soak is not exercising concurrency"
        )
        points = _checkpoints(log, 3)
        digests, replica = _oracle_digests(log, points)
        for point in points:
            prefix = [m for m in log if m.seq <= point]
            doc = MergeTreeDocInput(
                doc_id="beast", ops=prefix, final_seq=point,
                final_msn=max(m.min_seq for m in prefix),
            )
            [summary] = replay_mergetree_batch([doc])
            assert summary.digest() == digests[point], (
                f"seed={seed} obliterate={obliterate} checkpoint={point}: "
                f"kernel != oracle"
            )
        assert len(replica.text) > 200  # the soak built a real document


def test_beast_soak_pallas_interpret():
    """The genuinely-concurrent log through the Pallas-interpret fold:
    byte-identical summaries vs the fresh oracle.  A shorter prefix than
    the scan soak — interpret mode runs the step loop in Python — but the
    SAME generator, so arrival kills / overlap removers / lagged
    annotates all appear."""
    import jax.numpy as jnp

    from fluidframework_tpu.ops.mergetree_kernel import (
        _export_flags,
        _export_state,
        export_to_numpy,
        pack_mergetree_batch,
        summaries_from_export,
    )
    from fluidframework_tpu.ops.pallas_fold import replay_vmapped_pallas

    N = 700
    log, _live = _beast_log(21, N, obliterate=True)
    assert _lagged_fraction(log) >= MIN_LAGGED_FRACTION
    digests, _ = _oracle_digests(log, [log[-1].seq])
    doc = MergeTreeDocInput(
        doc_id="beast", ops=log, final_seq=log[-1].seq,
        final_msn=max(m.min_seq for m in log),
    )
    state, ops, meta = pack_mergetree_batch([doc])
    final = replay_vmapped_pallas(state, ops, interpret=True)
    i16, ob_rows, ov_rows, i8, props_rows = _export_flags(meta)
    doc_base = jnp.asarray(meta["doc_base"]) if i16 else \
        jnp.zeros((1,), jnp.int32)
    export = export_to_numpy(
        _export_state(final, doc_base, i16, ob_rows, ov_rows, i8,
                      props_rows=props_rows))
    [summary] = summaries_from_export(meta, export)
    assert summary.digest() == digests[log[-1].seq], (
        "pallas-interpret summary != oracle on the concurrent soak"
    )


def test_beast_warm_restart_chain():
    """Catch-up chaining under the concurrent soak: summarize at N/3 and
    2N/3, re-enter each summary as the next leg's base — byte-identical
    to the one-shot fold at the end."""
    N = 1800
    log, _live = _beast_log(17, N, obliterate=True)
    assert _lagged_fraction(log) >= MIN_LAGGED_FRACTION
    final_point = log[-1].seq
    digests, _ = _oracle_digests(log, [final_point])

    cuts = [0] + _checkpoints(log, 3)
    base_records, base_seq, base_msn = None, 0, 0
    summary = None
    for lo, hi in zip(cuts, cuts[1:]):
        leg_ops = [m for m in log if lo < m.seq <= hi]
        doc = MergeTreeDocInput(
            doc_id="beast", ops=leg_ops,
            base_records=base_records, base_seq=base_seq, base_msn=base_msn,
            final_seq=hi, final_msn=max(m.min_seq for m in leg_ops),
        )
        [summary] = replay_mergetree_batch([doc])
        base_records = json.loads(summary.blob_bytes("body"))
        header = json.loads(summary.blob_bytes("header"))
        base_seq, base_msn = header["seq"], header["minSeq"]
    assert summary.digest() == digests[final_point]
