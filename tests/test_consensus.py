"""Consensus DDSes (queue / registers / task manager) and the distributed
id compressor, driven through the real loader + service stack."""

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime.id_compressor import IdCompressor
from fluidframework_tpu.service import LocalOrderingService


def make_two(build):
    service = LocalOrderingService()
    loader = Loader(LocalDocumentServiceFactory(service))
    a = loader.create("doc", "alice", build)
    b = loader.resolve("doc", "bob")
    a.drain()
    return service, loader, a, b


def drain(*containers):
    for c in containers:
        c.drain()


def chan(container, name="x"):
    return container.runtime.get_datastore("ds").get_channel(name)


# --- ConsensusQueue ----------------------------------------------------------


def build_queue(rt):
    rt.create_datastore("ds").create_channel("ordered-collection-tpu", "x")


def test_queue_add_acquire_complete():
    _s, _l, a, b = make_two(build_queue)
    chan(a).add("job1")
    chan(a).add("job2")
    drain(a, b)
    # pessimistic: nothing visible until sequenced — already drained here
    assert chan(a).items == ["job1", "job2"] == chan(b).items

    chan(b).acquire()
    drain(a, b)
    assert chan(b).held_by_me == {"item-0": "job1"}
    assert chan(a).held_by_me == {}
    assert chan(a).holder_of("item-0") == "bob"
    assert chan(a).items == ["job2"]

    chan(b).complete("item-0")
    drain(a, b)
    assert chan(a).holder_of("item-0") is None


def test_queue_concurrent_acquire_one_winner():
    _s, _l, a, b = make_two(build_queue)
    chan(a).add("only")
    drain(a, b)
    chan(a).acquire()
    chan(b).acquire()
    drain(a, b)
    holders = [bool(chan(a).held_by_me), bool(chan(b).held_by_me)]
    assert holders.count(True) == 1
    # the loser's acquire was a no-op on an empty queue
    assert chan(a).items == [] == chan(b).items


def test_queue_release_requeues_at_front():
    _s, _l, a, b = make_two(build_queue)
    chan(a).add("j1")
    chan(a).add("j2")
    drain(a, b)
    chan(b).acquire()
    drain(a, b)
    chan(b).release("item-0")
    drain(a, b)
    assert chan(a).items == ["j1", "j2"]


def test_queue_holder_leave_requeues():
    _s, _l, a, b = make_two(build_queue)
    chan(a).add("work")
    drain(a, b)
    chan(b).acquire()
    drain(a, b)
    assert chan(a).holder_of("item-0") == "bob"
    b.disconnect()  # LEAVE sequenced
    drain(a)
    assert chan(a).holder_of("item-0") is None
    assert chan(a).items == ["work"]


def test_queue_summary_roundtrip():
    _s, loader, a, b = make_two(build_queue)
    chan(a).add("j1")
    chan(b).acquire()
    drain(a, b)
    ro = loader.resolve("doc")
    assert ro.runtime.summarize().digest() == \
        b.runtime.summarize().digest()


# --- ConsensusRegisterCollection ---------------------------------------------


def build_registers(rt):
    rt.create_datastore("ds").create_channel("register-collection-tpu", "x")


def test_register_sequential_write_supersedes():
    _s, _l, a, b = make_two(build_registers)
    chan(a).write("cfg", 1)
    drain(a, b)
    chan(b).write("cfg", 2)
    drain(a, b)
    assert chan(a).read("cfg") == 2
    assert chan(a).read_versions("cfg") == [2]


def test_register_concurrent_writes_all_versions_survive():
    _s, _l, a, b = make_two(build_registers)
    # both write without seeing each other (submit before drain)
    chan(a).write("cfg", "A")
    chan(b).write("cfg", "B")
    drain(a, b)
    assert chan(a).read_versions("cfg") == chan(b).read_versions("cfg")
    assert set(chan(a).read_versions("cfg")) == {"A", "B"}
    # atomic read: first write in total order wins, same on both
    assert chan(a).read("cfg") == chan(b).read("cfg") == "A"


def test_register_summary_roundtrip():
    _s, loader, a, b = make_two(build_registers)
    chan(a).write("k1", [1, 2])
    chan(b).write("k2", {"x": 1})
    drain(a, b)
    ro = loader.resolve("doc")
    assert ro.runtime.summarize().digest() == a.runtime.summarize().digest()
    assert chan(ro).read("k1") == [1, 2]


# --- TaskManager -------------------------------------------------------------


def build_tasks(rt):
    rt.create_datastore("ds").create_channel("task-manager-tpu", "x")


def test_task_volunteer_order_and_abandon():
    _s, _l, a, b = make_two(build_tasks)
    chan(a).volunteer("summarizer")
    chan(b).volunteer("summarizer")
    drain(a, b)
    assert chan(a).assigned_to("summarizer") == "alice"
    assert chan(b).assigned_to_me("summarizer") is False
    assert chan(b).queued("summarizer") == ["alice", "bob"]

    chan(a).abandon("summarizer")
    drain(a, b)
    assert chan(b).assigned_to_me("summarizer") is True


def test_task_assignee_leave_passes_down():
    _s, _l, a, b = make_two(build_tasks)
    chan(a).volunteer("gc")
    chan(b).volunteer("gc")
    drain(a, b)
    a.disconnect()
    drain(b)
    assert chan(b).assigned_to("gc") == "bob"


def test_task_complete_clears_queue():
    _s, _l, a, b = make_two(build_tasks)
    chan(a).volunteer("once")
    chan(b).volunteer("once")
    drain(a, b)
    chan(a).complete("once")
    drain(a, b)
    assert chan(b).assigned_to("once") is None
    assert chan(b).queued("once") == []


# --- IdCompressor ------------------------------------------------------------


def test_id_compressor_local_then_final():
    comp = IdCompressor(session_id="s1", cluster_capacity=4)
    ids = [comp.generate() for _ in range(3)]
    assert ids == [-1, -2, -3]
    rng = comp.take_next_creation_range()
    assert rng == {"session": "s1", "firstGen": 1, "count": 3}
    assert comp.take_next_creation_range() is None
    comp.finalize_range(rng)
    finals = [comp.normalize_to_op_space(i) for i in ids]
    assert finals == [0, 1, 2]
    # stable decompression is session:gen
    assert comp.decompress(finals[0]) == "s1:1"
    assert comp.recompress("s1:2") == 1


def test_id_compressor_two_sessions_disjoint_finals():
    a = IdCompressor(session_id="a", cluster_capacity=4)
    b = IdCompressor(session_id="b", cluster_capacity=4)
    ra = {"session": "a", "firstGen": 1, "count": 2}
    rb = {"session": "b", "firstGen": 1, "count": 6}
    # both folds see the same sequenced order
    for comp in (a, b):
        comp.finalize_range(ra)
        comp.finalize_range(rb)
    assert a.serialize() == b.serialize()
    # a's finals and b's finals never collide
    a_finals = {a._final_of("a", g) for g in (1, 2)}
    b_finals = {a._final_of("b", g) for g in range(1, 7)}
    assert not (a_finals & b_finals)
    # normalize round trip from b's perspective
    f = b.normalize_to_op_space(-3)
    assert f >= 0 and b.normalize_to_session_space(f, "b") == -3


def test_id_compressor_serialize_roundtrip():
    comp = IdCompressor(session_id="s", cluster_capacity=2)
    comp.finalize_range({"session": "s", "firstGen": 1, "count": 5})
    restored = IdCompressor.deserialize(comp.serialize(), session_id="s")
    assert restored.serialize() == comp.serialize()
    assert restored.decompress(4) == comp.decompress(4)


def test_id_compressor_through_runtime_batches():
    """Ids minted on one client finalize identically everywhere via the
    sequenced batch idRange."""
    def build(rt):
        rt.create_datastore("ds").create_channel("map-tpu", "x")

    _s, _l, a, b = make_two(build)
    local = a.runtime.id_compressor.generate()
    chan(a).set("marker", "v")  # flush carries the creation range
    drain(a, b)
    final = a.runtime.id_compressor.normalize_to_op_space(local)
    assert final >= 0
    # bob's compressor allocated the identical final for alice's id
    stable = a.runtime.id_compressor.decompress(final)
    assert b.runtime.id_compressor.recompress(stable) == final
    assert (a.runtime.id_compressor.serialize()
            == b.runtime.id_compressor.serialize())
    # and it rides summaries byte-identically
    assert (a.runtime.summarize().digest()
            == b.runtime.summarize().digest())
