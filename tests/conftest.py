"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip sharding is validated
without TPU hardware; the driver separately dry-runs __graft_entry__).  The
env vars must be set before the first ``import jax`` anywhere in the test
process, which conftest guarantees.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
