"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip sharding is validated without TPU hardware (the driver separately
dry-runs __graft_entry__).  Note the axon sitecustomize force-sets
JAX_PLATFORMS=axon at interpreter startup, so the env var alone is not
enough — jax.config.update must run before the first backend use, which this
conftest guarantees (it executes before any test module imports jax).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running (nightly) tests, excluded from tier-1's "
        "-m 'not slow' run",
    )
