"""Tooling (replay CLI, bench harness) and the load/stress harness."""

import json
import subprocess
import sys

import pytest

from fluidframework_tpu.drivers import FileDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.testing.load import LoadSpec, run_load
from fluidframework_tpu.tools.bench_harness import (
    benchmark,
    benchmark_memory,
)
from fluidframework_tpu.tools.replay import replay


# --- replay tool -------------------------------------------------------------


def _make_store(tmp_path):
    root = str(tmp_path / "store")
    factory = FileDocumentServiceFactory(root)
    loader = Loader(factory)

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("sequence-tpu", "text")

    a = loader.create("doc", "alice", build)
    text = a.runtime.get_datastore("ds").get_channel("text")
    seqs = []
    for i in range(5):
        text.insert_text(0, f"[{i}]")
        a.drain()
        seqs.append((a.runtime.ref_seq, text.text))
    factory.close()
    return root, seqs


def test_replay_tool_reconstructs_history(tmp_path):
    root, seqs = _make_store(tmp_path)
    for seq, expected_text in seqs:
        report = replay(root, "doc", to_seq=seq)
        runtime = report.pop("_runtime")
        assert report["seq"] == seq
        channel = runtime.get_datastore("ds").get_channel("text")
        assert channel.text == expected_text
    head = replay(root, "doc")
    assert head["seq"] == seqs[-1][0]
    assert head["datastores"] == {"ds": {"text": "sequence-tpu"}}


def test_replay_cli(tmp_path):
    root, seqs = _make_store(tmp_path)
    out = subprocess.run(
        [sys.executable, "-m", "fluidframework_tpu.tools.replay",
         root, "doc", "--json"],
        capture_output=True, text=True, check=True, cwd="/root/repo",
    )
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["seq"] == seqs[-1][0]
    assert report["summaryDigest"]

    shown = subprocess.run(
        [sys.executable, "-m", "fluidframework_tpu.tools.replay",
         root, "doc", "--show", "ds/text"],
        capture_output=True, text=True, check=True, cwd="/root/repo",
    )
    assert seqs[-1][1] in shown.stdout


# --- bench harness -----------------------------------------------------------


def test_benchmark_statistics():
    calls = []
    result = benchmark(lambda: calls.append(1), name="noop",
                       min_runs=5, min_time_s=0.0, warmup_runs=1)
    assert result.runs >= 5
    assert len(calls) == result.runs + 1  # warmup included
    assert result.mean >= 0
    assert result.p50 <= result.p95 or result.runs < 3
    assert "noop" in result.report()


def test_benchmark_setup_untimed():
    def setup():
        return list(range(1000))

    timed = benchmark(lambda data: sum(data), min_runs=3, min_time_s=0,
                      warmup_runs=0, setup=setup)
    assert timed.runs == 3


def test_benchmark_memory():
    result = benchmark_memory(lambda: bytearray(5_000_000), name="alloc")
    assert result.peak_bytes > 4_000_000
    assert "alloc" in result.report()


# --- load harness ------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_load_run_converges(seed):
    result = run_load(LoadSpec(seed=seed, clients=3, steps=120))
    assert result.edits > 0
    assert result.sequenced_ops > 0
    assert result.final_clients >= 1
    assert len(result.summary_digest) == 64


def test_load_run_with_heavy_faults_converges():
    spec = LoadSpec(seed=7, clients=4, steps=200, edit_weight=0.5,
                    sync_weight=0.2, disconnect_weight=0.15,
                    stash_weight=0.1, late_join_weight=0.05)
    result = run_load(spec)
    assert result.disconnects > 0
    assert result.rehydrates + result.late_joins > 0


def test_devtools_inspector_snapshot():
    """The runtime inspector renders live state read-only: channels, quorum,
    proposals, connection and summarizer stats — and inspecting twice gives
    the same snapshot (no mutation)."""
    import json as _json

    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.runtime.summarizer import (
        SummarizerOptions,
        SummaryManager,
    )
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.tools.devtools import inspect_runtime

    service = LocalOrderingService()
    ep = service.create_document("doc")
    rt = ContainerRuntime()
    ds = rt.create_datastore("ds")
    ds.create_channel("sequence-tpu", "text")
    ds.create_channel("map-tpu", "kv")
    ds.create_channel("counter-tpu", "n")
    rt.connect(ep, "alice")
    rt.drain()
    mgr = SummaryManager(rt, service.storage, "doc",
                         SummarizerOptions(ops_per_summary=1000))
    rt.get_datastore("ds").get_channel("text").insert_text(0, "hello")
    rt.get_datastore("ds").get_channel("kv").set("k", 1)
    rt.get_datastore("ds").get_channel("n").increment(2)
    rt.propose("code", "v1")
    rt.drain()

    snap = inspect_runtime(rt, summary_manager=mgr)
    _json.dumps(snap)  # JSON-safe
    assert snap["clientId"] == "alice"
    assert snap["quorum"] == ["alice"]
    channels = snap["datastores"]["ds"]["channels"]
    assert channels["text"]["preview"] == "hello"
    assert channels["kv"]["preview"] == {"k": 1}
    assert channels["n"]["value"] == 2
    assert snap["proposals"]["pending"] or snap["proposals"]["accepted"]
    assert snap["summarizer"]["isSummarizer"] is True
    assert inspect_runtime(rt, summary_manager=mgr) == snap  # read-only


def test_wire_soak_1k_docs_through_catchup_rpc(tmp_path):
    """Scale soak (SURVEY §4 load/stress; VERDICT r3 #8): >=1k mixed-channel
    documents seeded by client SUBPROCESSES against the standalone server,
    folded centrally through the catchup RPC — device routing must dominate
    (device_docs >> cpu_docs) and sampled fresh loads must reproduce the
    seeders' summaries byte-identically with zero catch-up replay."""
    import os
    import subprocess
    import sys
    import time

    n_docs = int(os.environ.get("SOAK_DOCS", "1024"))
    procs = 4
    edits = 6
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    srv = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.server",
         "--dir", str(tmp_path / "store"), "--port", "0",
         "--platform", "cpu"],  # beat any site-forced accelerator platform
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=repo,
    )
    try:
        port = None
        for _ in range(400):
            line = srv.stdout.readline()
            if "listening" in line:
                port = int(line.rsplit(":", 1)[-1].strip())
                break
        assert port, "server did not report a port"
        # Keep draining the merged stdout/stderr pipe: server logging
        # under 1k-doc load could otherwise fill the OS pipe buffer and
        # block the event loop (deadlocking the whole soak).
        import threading

        threading.Thread(target=lambda: [None for _ in srv.stdout],
                         daemon=True).start()

        t0 = time.time()
        per = n_docs // procs
        workers = [
            subprocess.Popen(
                [sys.executable, "-m", "fluidframework_tpu.testing.load",
                 "--wire-worker", "127.0.0.1", str(port), str(w * per),
                 # last worker takes the remainder so any SOAK_DOCS works
                 str(n_docs if w == procs - 1 else (w + 1) * per),
                 str(edits), "42"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                cwd=repo,
            )
            for w in range(procs)
        ]
        expected = {}
        for w in workers:
            out, err = w.communicate(timeout=600)
            assert w.returncode == 0, err[-2000:]
            expected.update(json.loads(out.strip().splitlines()[-1]))
        seed_time = time.time() - t0
        assert len(expected) == n_docs

        from fluidframework_tpu.drivers.network_driver import (
            NetworkDocumentServiceFactory,
        )

        # The bulk fold of 1k docs takes minutes on the CPU backend
        # (XLA-emulated kernels + compiles): size the RPC timeout to the
        # workload, not the default interactive 30s.
        f = NetworkDocumentServiceFactory(host="127.0.0.1", port=port,
                                          timeout=600.0)
        try:
            t0 = time.time()
            res = f._rpc.request("catchup", {})
            fold_time = time.time() - t0
            assert len(res["docs"]) == n_docs
            # Device routing must dominate: every doc here is a pure
            # kernel-channel doc (string/map/matrix/tree).
            assert res["deviceDocs"] >= 0.95 * n_docs, (
                res["deviceDocs"], res["cpuDocs"])

            # Sampled fresh loads: zero catch-up replay, byte-identical to
            # the seeders' read-only summaries.
            sample = sorted({min(i, n_docs - 1)
                             for i in (0, 1, 2, 3, 4, n_docs // 2,
                                       n_docs - 1)})
            loader = Loader(f)
            for i in sample:
                doc = f"soak{i:05d}"
                c = loader.resolve(doc)
                assert c.catchup_ops == 0, (doc, c.catchup_ops)
                assert c.runtime.summarize().digest() == expected[doc], doc
                c.close()
            print(f"wire soak: {n_docs} docs, {procs} procs, seed "
                  f"{seed_time:.1f}s, catchup fold {fold_time:.1f}s, "
                  f"device {res['deviceDocs']} / cpu {res['cpuDocs']}")
        finally:
            f.close()
    finally:
        srv.terminate()
        srv.wait(timeout=15)


# --- TPU-window preflight gate -----------------------------------------------


def test_tpu_preflight_exits_zero_on_cpu():
    """The preflight must be green on CPU (interpret mode): it is the
    gate that keeps a real TPU window from being burned on failures CPU
    could already report (kernel lint, fold parity, bench schema)."""
    import os
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[1]
    proc = subprocess.run(
        [sys.executable, str(root / "tools" / "tpu_preflight.py")],
        capture_output=True, text=True, cwd=str(root),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert doc["preflight_ok"] is True
    assert set(doc["gates"]) == {"kernel_lint", "mergetree_parity",
                                 "tree_parity", "bench_schema"}
    assert all(g["ok"] for g in doc["gates"].values())


def test_tpu_window_runs_preflight_first():
    """The window catcher's healthy block starts with the preflight —
    before the pallas canary and every bench — and keeps probing on a
    preflight failure instead of spending the window."""
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[1]
    src = (root / "tools" / "tpu_window.sh").read_text(encoding="utf-8")
    assert "tools/tpu_preflight.py" in src
    assert src.index("tpu_preflight.py") < src.index("pallas_probe.py")
