"""Op attribution (SURVEY §1 layer 8): seq -> (user, timestamp) recorded at
the container runtime, serialized columnar into summaries, resolved from
SharedString / SharedTree reads, surviving summarize/load round-trips."""

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime.attributor import Attributor
from fluidframework_tpu.runtime.container import ContainerRuntimeOptions
from fluidframework_tpu.service import LocalOrderingService


def make_stack():
    """Attribution is a per-DOCUMENT opt-in (upstream
    enableRuntimeAttribution): creators pass the option; loaders adopt the
    document's .metadata stamp regardless of their own options."""
    service = LocalOrderingService()
    return service, Loader(
        LocalDocumentServiceFactory(service),
        runtime_options=ContainerRuntimeOptions(attribution=True),
    )


def build(rt):
    ds = rt.create_datastore("ds")
    ds.create_channel("sequence-tpu", "text")
    ds.create_channel("tree-tpu", "tree")


def test_attribution_resolves_users_on_string_reads():
    _service, loader = make_stack()
    a = loader.create("doc", "alice", build)
    b = loader.resolve("doc", client_id="bob")
    ta = a.runtime.get_datastore("ds").get_channel("text")
    tb = b.runtime.get_datastore("ds").get_channel("text")

    ta.insert_text(0, "aaa")
    a.runtime.flush()
    a.drain(), b.drain()
    tb.insert_text(3, "BBB")
    b.runtime.flush()
    a.drain(), b.drain()

    assert ta.text == "aaaBBB"
    attr_a = ta.attribution_at(0)
    attr_b = ta.attribution_at(4)
    assert attr_a["user"] == "alice"
    assert attr_b["user"] == "bob"
    # Sequencer clock is monotone: bob's edit is later.
    assert attr_b["timestamp"] >= attr_a["timestamp"]
    assert attr_b["seq"] > attr_a["seq"]
    # Both replicas resolve identically.
    assert tb.attribution_at(0) == attr_a
    assert tb.attribution_at(4) == attr_b


def test_attribution_survives_summary_load_round_trip():
    service, loader = make_stack()
    a = loader.create("doc", "alice", build)
    ta = a.runtime.get_datastore("ds").get_channel("text")
    tree_a = a.runtime.get_datastore("ds").get_channel("tree")
    ta.insert_text(0, "hello")
    node_ids = tree_a.insert("", "a", 0, [tree_a.build("n", value=1)])
    a.runtime.flush()
    a.drain()
    tree_a.set_value(node_ids[0], 42)
    a.runtime.flush()
    a.drain()

    # Summarize at head: the catch-up client loads ONLY the summary (no
    # tail replay below it), so any attribution it resolves came through
    # the .attribution blob.
    service.storage.upload("doc", a.runtime.summarize(),
                           ref_seq=a.runtime.ref_seq)
    c = loader.resolve("doc", client_id="carol")
    tc = c.runtime.get_datastore("ds").get_channel("text")
    tree_c = c.runtime.get_datastore("ds").get_channel("tree")
    assert tc.text == "hello"
    assert tc.attribution_at(2)["user"] == "alice"
    nid = tree_c.children("", "a")[0]
    assert tree_c.attribution_of(nid)["user"] == "alice"
    value_attr = tree_c.attribution_of(nid, kind="value")
    assert value_attr["user"] == "alice"
    assert value_attr["seq"] > tree_c.attribution_of(nid)["seq"]


def test_pending_local_insert_unattributed_until_ack():
    _service, loader = make_stack()
    a = loader.create("doc", "alice", build)
    ta = a.runtime.get_datastore("ds").get_channel("text")
    ta.insert_text(0, "x")
    # Not flushed/drained: the segment's insert seq is still UNASSIGNED.
    assert ta.attribution_at(0) is None
    a.runtime.flush()
    a.drain()
    assert ta.attribution_at(0)["user"] == "alice"


def test_detached_channel_attribution_is_none():
    from fluidframework_tpu.dds.sequence import SharedString

    s = SharedString("standalone")
    s.insert_text(0, "free")
    assert s.attribution_at(0) is None


def test_attributor_columnar_round_trip_and_idempotence():
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    att = Attributor()
    for seq, client, ts in ((3, "a", 10), (5, "b", 11), (9, "a", 15)):
        att.observe(SequencedMessage(
            seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
            min_seq=0, type=MessageType.OP, contents={}, timestamp=ts,
        ))
    # replay overlap is ignored; non-op and server messages are ignored
    att.observe(SequencedMessage(
        seq=9, client_id="c", client_seq=9, ref_seq=8, min_seq=0,
        type=MessageType.OP, contents={}, timestamp=99.0,
    ))
    att.observe(SequencedMessage(
        seq=10, client_id=None, client_seq=0, ref_seq=9, min_seq=0,
        type=MessageType.OP, contents={}, timestamp=99.0,
    ))
    att.observe(SequencedMessage(
        seq=11, client_id="a", client_seq=10, ref_seq=9, min_seq=0,
        type=MessageType.JOIN, contents={"clientId": "a"}, timestamp=99.0,
    ))
    assert len(att) == 3
    assert att.get(5) == {"user": "b", "timestamp": 11, "seq": 5}
    assert att.get(4) is None

    state = att.serialize()
    # deltas keep the payload small ints
    assert state["seqD"] == [3, 2, 4]
    assert state["tsD"] == [10, 1, 4]
    back = Attributor.deserialize(state)
    assert back.get(3) == att.get(3)
    assert back.get(9) == att.get(9)
    assert Attributor.deserialize(None).get(3) is None


def test_document_stamp_beats_loader_options():
    """A loader WITHOUT the attribution option still adopts the document's
    .metadata stamp — attribution is uniform per document, never mixed."""
    service, loader_on = make_stack()
    a = loader_on.create("doc", "alice", build)
    ta = a.runtime.get_datastore("ds").get_channel("text")
    ta.insert_text(0, "hi")
    a.runtime.flush()
    a.drain()
    service.storage.upload("doc", a.runtime.summarize(),
                           ref_seq=a.runtime.ref_seq)

    plain = Loader(LocalDocumentServiceFactory(service))  # no option
    c = plain.resolve("doc", client_id="carol")
    assert c.runtime.attribution_enabled
    tc = c.runtime.get_datastore("ds").get_channel("text")
    assert tc.attribution_at(0)["user"] == "alice"
    # and its own summaries keep the stamp + blob
    s = c.runtime.summarize()
    import json
    assert json.loads(s.blob_bytes(".metadata"))["attribution"] is True
    assert ".attribution" in s.children


def test_attribution_off_documents_emit_no_attribution_bytes():
    """Legacy/off documents: byte-stable summaries — no .attribution blob,
    no channel attribution blobs, no metadata stamp (the golden contract)."""
    import json

    service = LocalOrderingService()
    loader = Loader(LocalDocumentServiceFactory(service))
    a = loader.create("doc", "alice", build)
    ta = a.runtime.get_datastore("ds").get_channel("text")
    ta.insert_text(0, "plain")
    a.runtime.flush()
    a.drain()
    s = a.runtime.summarize()
    assert ".attribution" not in s.children
    assert "attribution" not in json.loads(s.blob_bytes(".metadata"))
    string_summary = s.get(".datastores").get("ds").get("text")
    assert "attribution" not in string_summary.children
    assert ta.attribution_at(0) is None


def test_catchup_service_preserves_attribution():
    """The bulk catch-up service folds attribution-enabled documents on
    the DEVICE path (round 5; string + tree channels both emit their key
    blobs from the export, the container table folds over the tail) —
    byte-identical to the CPU container fold, and a client loading the
    service summary still resolves attribution."""
    import json

    from fluidframework_tpu.service.catchup import CatchupService

    service, loader = make_stack()
    a = loader.create("doc", "alice", build)
    ta = a.runtime.get_datastore("ds").get_channel("text")
    ta.insert_text(0, "served")
    a.runtime.flush()
    a.drain()

    cpu = CatchupService(service)
    cpu._device_plan = lambda w: None  # force the container fold
    cpu_results = cpu.catch_up(upload=False)
    assert cpu.cpu_docs == 1

    svc = CatchupService(service)
    dev_results = svc.catch_up(upload=False)
    assert svc.device_docs == 1 and svc.cpu_docs == 0
    assert dev_results == cpu_results, (
        "device attribution fold != container fold (string+tree doc)")
    svc.catch_up()

    tree, _seq = service.storage.latest("doc")
    assert json.loads(tree.blob_bytes(".metadata"))["attribution"] is True
    assert ".attribution" in tree.children

    c = loader.resolve("doc", client_id="carol")
    tc = c.runtime.get_datastore("ds").get_channel("text")
    assert tc.attribution_at(0)["user"] == "alice"


def test_merged_run_split_preserves_per_author_attribution():
    """Two authors' adjacent text whose seqs fall below the window clamps
    to identical records and MERGES in the summary body; the run-length
    key blob must split it back on load so neither author's text reads as
    the other's (review r4: one key per record mis-attributed the second
    author)."""
    import json

    service, loader = make_stack()
    a = loader.create("doc", "alice", build)
    b = loader.resolve("doc", client_id="bob")
    ta = a.runtime.get_datastore("ds").get_channel("text")
    tb = b.runtime.get_datastore("ds").get_channel("text")

    ta.insert_text(0, "foo")
    a.runtime.flush()
    a.drain(), b.drain()
    tb.insert_text(3, "bar")
    b.runtime.flush()
    a.drain(), b.drain()
    # Advance the window past both inserts: later traffic from both
    # clients raises the MSN above the first two seqs.
    for k in range(3):
        ta.insert_text(len(ta.text), ".")
        a.runtime.flush()
        a.drain(), b.drain()
        tb.insert_text(len(tb.text), "!")
        b.runtime.flush()
        a.drain(), b.drain()
    assert ta.text == tb.text

    summary = a.runtime.summarize()
    string_summary = summary.get(".datastores").get("ds").get("text")
    body = json.loads(string_summary.blob_bytes("body"))
    merged = [rec for rec in body if "foo" in rec["t"] and "bar" in rec["t"]]
    assert merged, (
        "test setup must produce a merged foo+bar record; body=%r" % body
    )

    service.storage.upload("doc", summary, ref_seq=a.runtime.ref_seq)
    c = loader.resolve("doc", client_id="carol")
    tc = c.runtime.get_datastore("ds").get_channel("text")
    assert tc.text == ta.text
    assert tc.attribution_at(0)["user"] == "alice"   # 'f' of foo
    assert tc.attribution_at(2)["user"] == "alice"   # 'o' of foo
    assert tc.attribution_at(3)["user"] == "bob"     # 'b' of bar
    assert tc.attribution_at(5)["user"] == "bob"     # 'r' of bar
    # and carol's own re-summarize reproduces alice's string BODY bytes
    # exactly — the split runs re-merge under the clamp (the container
    # digests legitimately differ by carol's own JOIN advancing the seq)
    carol_string = c.runtime.summarize().get(".datastores").get("ds") \
        .get("text")
    assert carol_string.blob_bytes("body") == \
        string_summary.blob_bytes("body")
    assert carol_string.blob_bytes("attribution") == \
        string_summary.blob_bytes("attribution")


def build_string_only(rt):
    ds = rt.create_datastore("ds")
    ds.create_channel("sequence-tpu", "text")


def test_catchup_device_path_preserves_attribution():
    """String-only attribution documents fold on the DEVICE path (round 5:
    the export carries pre-clamp ins_seq, so the extractor emits the key
    blob; the container table folds host-side) — byte-identical to the CPU
    container fold, and a loading client still resolves attribution."""
    import json

    from fluidframework_tpu.service.catchup import CatchupService

    service, loader = make_stack()
    a = loader.create("doc", "alice", build_string_only)
    b = loader.resolve("doc", client_id="bob")
    ta = a.runtime.get_datastore("ds").get_channel("text")
    tb = b.runtime.get_datastore("ds").get_channel("text")
    ta.insert_text(0, "foo")
    a.runtime.flush()
    a.drain(), b.drain()
    tb.insert_text(3, "bar")
    b.runtime.flush()
    a.drain(), b.drain()
    # Window advance past both inserts: the device extractor must emit
    # run-length keys for the clamped, author-merged record.
    for _k in range(3):
        ta.insert_text(len(ta.text), ".")
        a.runtime.flush()
        a.drain(), b.drain()
        tb.insert_text(len(tb.text), "!")
        b.runtime.flush()
        a.drain(), b.drain()

    cpu = CatchupService(service)
    cpu._device_plan = lambda w: None  # force the container fold
    cpu_results = cpu.catch_up(upload=False)
    assert cpu.cpu_docs == 1

    dev = CatchupService(service)
    dev_results = dev.catch_up(upload=False)
    assert dev.device_docs == 1 and dev.cpu_docs == 0, (
        dev.device_docs, dev.cpu_docs)
    assert dev_results == cpu_results, (
        "device attribution fold != container fold")

    # upload for real and load: attribution resolves through the service
    # summary, per-author across the merged run
    dev2 = CatchupService(service)
    dev2.catch_up()
    tree, _seq = service.storage.latest("doc")
    assert json.loads(tree.blob_bytes(".metadata"))["attribution"] is True
    assert ".attribution" in tree.children
    string_summary = tree.get(".datastores").get("ds").get("text")
    assert "attribution" in string_summary.children

    c = loader.resolve("doc", client_id="carol")
    tc = c.runtime.get_datastore("ds").get_channel("text")
    assert tc.attribution_at(0)["user"] == "alice"
    assert tc.attribution_at(3)["user"] == "bob"


def test_catchup_device_attribution_fallback_doc_keeps_keys():
    """A known-fallback doc (interval ops + obliterate) inside an
    attribution document still emits its keys blob through the oracle
    escape hatch."""
    from fluidframework_tpu.ops.mergetree_kernel import (
        MergeTreeDocInput,
        oracle_fallback_summary,
    )
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    def msg(seq, client, contents, min_seq=0):
        return SequencedMessage(
            seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
            min_seq=min_seq, type=MessageType.OP, contents=contents,
        )

    ops = [
        msg(1, "alice", {"kind": "insert", "pos": 0, "text": "abcdef"}),
        msg(2, "bob", {"kind": "obliterate", "start": 4, "end": 6}),
        msg(3, "alice", {"kind": "intervalAdd", "label": "c",
                         "id": "iv0", "start": 0, "end": 2, "props": {}},
            min_seq=2),
        msg(4, "bob", {"kind": "insert", "pos": 2, "text": "zz"},
            min_seq=3),
    ]
    doc = MergeTreeDocInput(doc_id="fb", ops=ops, final_seq=4, final_msn=3,
                            attribution=True)
    summary = oracle_fallback_summary(doc)
    assert "attribution" in summary.children, (
        "fallback summary lost the attribution keys blob"
    )


def test_kernel_attribution_parity_direct():
    """replay_mergetree_batch(attribution=True) == the oracle with an
    attributor, byte-for-byte, across a window clamp that merges two
    authors' runs."""
    from fluidframework_tpu.dds.sequence import SharedString
    from fluidframework_tpu.ops.mergetree_kernel import (
        MergeTreeDocInput,
        replay_mergetree_batch,
    )
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    def msg(seq, client, contents, min_seq=0):
        return SequencedMessage(
            seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
            min_seq=min_seq, type=MessageType.OP, contents=contents,
        )

    ops = [
        msg(1, "alice", {"kind": "insert", "pos": 0, "text": "foo"}),
        msg(2, "bob", {"kind": "insert", "pos": 3, "text": "bar"}),
        msg(3, "alice", {"kind": "insert", "pos": 6, "text": "."},
            min_seq=2),
        msg(4, "bob", {"kind": "insert", "pos": 7, "text": "!"},
            min_seq=3),
    ]
    oracle = SharedString("doc")
    oracle._attributor = Attributor()
    for m in ops:
        oracle.process(m, local=False)
    want = oracle.summarize()
    assert "attribution" in want.children  # the clamp produced keys

    [got] = replay_mergetree_batch([MergeTreeDocInput(
        doc_id="doc", ops=ops, final_seq=4, final_msn=3, attribution=True,
    )])
    assert got.digest() == want.digest()
    assert got.blob_bytes("attribution") == want.blob_bytes("attribution")


def test_catchup_device_tree_attribution_with_window_clamp():
    """Tree-channel attribution through the device fold across a window
    clamp: the kernel's key blob (pre-clamp insert/value seqs per emitted
    node) must match the container fold byte-for-byte, and a fresh client
    resolves authors for clamped nodes."""
    from fluidframework_tpu.service.catchup import CatchupService

    def build_tree_only(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("tree-tpu", "tree")

    service, loader = make_stack()
    a = loader.create("doc", "alice", build_tree_only)
    b = loader.resolve("doc", client_id="bob")
    tra = a.runtime.get_datastore("ds").get_channel("tree")
    trb = b.runtime.get_datastore("ds").get_channel("tree")
    tra.insert("", "items", 0, [{"id": "n0", "type": "t", "value": 1}])
    a.runtime.flush()
    a.drain(), b.drain()
    trb.set_value("n0", 2)
    trb.insert("", "items", 1, [{"id": "n1", "type": "t", "value": 7}])
    b.runtime.flush()
    a.drain(), b.drain()
    # Advance the window past those edits so the summary clamps them.
    for k in range(3):
        tra.set_value("n1", 10 + k)
        a.runtime.flush()
        a.drain(), b.drain()
        trb.set_value("n1", 20 + k)
        b.runtime.flush()
        a.drain(), b.drain()

    cpu = CatchupService(service)
    cpu._device_plan = lambda w: None
    cpu_results = cpu.catch_up(upload=False)
    assert cpu.cpu_docs == 1

    dev = CatchupService(service)
    dev_results = dev.catch_up(upload=False)
    assert dev.device_docs == 1 and dev.cpu_docs == 0
    assert dev_results == cpu_results, (
        "device tree attribution fold != container fold")

    dev.catch_up()
    c = loader.resolve("doc", client_id="carol")
    trc = c.runtime.get_datastore("ds").get_channel("tree")
    assert trc.attribution_of("n0")["user"] == "alice"
    assert trc.attribution_of("n1")["user"] == "bob"


def test_catchup_device_warm_string_attribution_base():
    """A WARM catch-up whose base summary already carries a string key
    blob: the pack splits the merged base records back (the oracle's
    load-split), so the device re-fold regenerates identical body and
    keys over the new tail."""
    from fluidframework_tpu.service.catchup import CatchupService

    service, loader = make_stack()
    a = loader.create("doc", "alice", build_string_only)
    b = loader.resolve("doc", client_id="bob")
    ta = a.runtime.get_datastore("ds").get_channel("text")
    tb = b.runtime.get_datastore("ds").get_channel("text")
    ta.insert_text(0, "foo")
    a.runtime.flush()
    a.drain(), b.drain()
    tb.insert_text(3, "bar")
    b.runtime.flush()
    a.drain(), b.drain()
    for _k in range(3):  # clamp both authors' inserts below the window
        ta.insert_text(len(ta.text), ".")
        a.runtime.flush()
        a.drain(), b.drain()
        tb.insert_text(len(tb.text), "!")
        b.runtime.flush()
        a.drain(), b.drain()

    # First catch-up: produces the keyed base summary.
    first = CatchupService(service)
    first.catch_up()
    assert first.device_docs == 1
    base_tree, _seq = service.storage.latest("doc")
    assert "attribution" in base_tree.get(".datastores").get("ds") \
        .get("text").children

    # New tail on top of the keyed base.
    ta.insert_text(0, "warm:")
    a.runtime.flush()
    a.drain(), b.drain()

    cpu = CatchupService(service)
    cpu._device_plan = lambda w: None
    cpu_results = cpu.catch_up(upload=False)
    assert cpu.cpu_docs == 1

    dev = CatchupService(service)
    dev_results = dev.catch_up(upload=False)
    assert dev.device_docs == 1 and dev.cpu_docs == 0
    assert dev_results == cpu_results, (
        "warm keyed-base device fold != container fold")

    dev.catch_up()
    c = loader.resolve("doc", client_id="carol")
    tc = c.runtime.get_datastore("ds").get_channel("text")
    assert tc.text.startswith("warm:")
    assert tc.attribution_at(5)["user"] == "alice"   # 'f' of foo
    assert tc.attribution_at(8)["user"] == "bob"     # 'b' of bar
