"""Round-trip conformance for the wire error-code registry (ISSUE 19).

``protocol/errors.py`` declares every wire error code with the typed
exception and retryability class it promises.  fluidlint's FL-ERR family
pins the STATIC mirror (every produced literal is registered, every
registered row is produced); this suite pins the RUNTIME contract: every
registered code is PROVOKED against a real server — frame and nack codes
over a real TCP ``OrderingServer``, outcome codes through the shardhost
codec the out-of-process tier ships — and must surface driver-side as
exactly the declared exception, with no in-place resend for the
reconnect/fatal/nack-paced classes.

Coverage is exhaustive by construction: ``PROVOKERS`` is asserted to key
exactly the registry, so registering a new code without a provoker (or
retiring one and leaving its provoker behind) fails this file before it
ships.
"""

import builtins
import json
import socket
import threading

import pytest

from fluidframework_tpu.drivers import network_driver as nd
from fluidframework_tpu.protocol import errors as wire_errors
from fluidframework_tpu.protocol import messages
from fluidframework_tpu.protocol.wire import LEN, frame_bytes
from fluidframework_tpu.service import retry as retry_mod
from fluidframework_tpu.service.orderer import SubmitOutcome
from fluidframework_tpu.service.procclient import _decode_outcome
from fluidframework_tpu.service.retry import RetryPolicy
from fluidframework_tpu.service.server import EpochMismatch, OrderingServer
from fluidframework_tpu.service.shardhost import _outcome_wire
from fluidframework_tpu.utils.telemetry import MonitoringContext


def _real_exception(name):
    """The live class a registry row names, wherever it is defined."""
    for mod in (nd, messages, retry_mod):
        cls = getattr(mod, name, None)
        if cls is not None:
            return cls
    return getattr(builtins, name)


class _CaptureLogger:
    def __init__(self):
        self.events = []

    def send(self, event):
        self.events.append(dict(event))


# -- live provocation: frame + nack channels ----------------------------------


def _provoke(code, handler_body=None, *, drain=False):
    """Run one real TCP request that the server answers with ``code``.

    Returns ``(exception, server_calls, client)``: the driver-side
    exception, how many times the provoking handler actually ran (the
    no-in-place-resend pin), and the client for counter/telemetry
    asserts.  The client carries a live RetryPolicy so a code that
    WOULD be blindly resent shows up as ``server_calls > 1``.
    """
    srv = OrderingServer(port=0)
    calls = {"n": 0}

    def handler(session, params):
        calls["n"] += 1
        return handler_body(session, params)

    srv.extra_methods["provoke"] = handler
    srv.start_in_thread()
    if drain:
        srv.draining = True
    logger = _CaptureLogger()
    rpc = nd._RpcClient(
        "127.0.0.1", srv.port, timeout=10.0,
        mc=MonitoringContext(logger=logger),
        retry=RetryPolicy(max_attempts=4, base_delay=0.0, budget=1.0))
    rpc._captured_events = logger.events
    try:
        with pytest.raises(Exception) as excinfo:
            rpc.request("provoke", {})
    finally:
        rpc.close()
    return excinfo.value, calls["n"], rpc


def _raiser(make_exc):
    return lambda session, params: (_ for _ in ()).throw(make_exc())


def _kill_transport(session, params):
    # Die without answering: the client's reader drains every waiter
    # with the "connectionLost" frame — the one frame-channel code the
    # DRIVER produces (network_driver reader loop), consumed by the same
    # dispatch chain as server-produced codes.
    session.writer.transport.abort()
    return True


def _frame_provoker(code, make_exc):
    def run():
        exc, calls, rpc = _provoke(code, _raiser(make_exc))
        want = _real_exception(wire_errors.spec(code)["exception"])
        assert type(exc) is want, (code, exc)
        assert calls == 1, f"{code} was resent in place ({calls} calls)"
        assert rpc.retry_counters.get("retry.retries") == 0
        return exc
    return run


def _nack_provoker(code, *, drain=False):
    def run():
        body = (None if drain else
                _raiser(lambda: messages.NackError(
                    f"refused:{code}", retry_after=0.125, code=code)))
        exc, calls, rpc = _provoke(
            code, body or (lambda s, p: True), drain=drain)
        want = _real_exception(wire_errors.spec(code)["exception"])
        assert type(exc) is want, (code, exc)
        assert exc.code == code
        assert exc.retry_after > 0.0
        if not drain:
            assert calls == 1, f"{code} was resent in place"
        assert rpc.retry_counters.get("retry.retries") == 0
        return exc
    return run


# -- outcome channel: the shardhost codec round trip --------------------------


def _outcome_provoker(code, make_error):
    def run():
        wire = _outcome_wire(SubmitOutcome(
            stamped=[], consumed=1, error=make_error()))
        assert wire["code"] == code
        out = _decode_outcome(wire)
        assert isinstance(out.error, ConnectionError)
        assert f"[{code}]" in str(out.error)
        return out.error
    return run


def _shard_dead_provoker():
    # Produced by the FRONT DOOR (a dead shard's whole submit answers
    # with this shape), decoded by the same adapter path.
    wire = {"stamped": 0, "consumed": 0,
            "error": "shard shard00 died mid-submit", "code": "shardDead"}
    out = _decode_outcome(wire)
    assert isinstance(out.error, ConnectionError)
    assert "[shardDead]" in str(out.error)
    return out.error


PROVOKERS = {
    # frame channel
    "epochMismatch": _frame_provoker(
        "epochMismatch", lambda: EpochMismatch("gen-a", "gen-b")),
    "shardFenced": _frame_provoker(
        "shardFenced",
        lambda: messages.ShardFencedError("doc-1", "shard fenced")),
    "wrongShard": _frame_provoker(
        "wrongShard",
        lambda: messages.DocRelocatedError("doc-1", "moved to shard01")),
    "internal": _frame_provoker(
        "internal", lambda: RuntimeError("handler fault")),
    "connectionLost": lambda: _connection_lost_provoker(),
    # nack channel
    "throttled": _nack_provoker("throttled"),
    "staleView": _nack_provoker("staleView"),
    "overloaded": _nack_provoker("overloaded"),
    # shuttingDown takes the REAL drain refusal in _dispatch, not a
    # synthetic raise — the handler never runs.
    "shuttingDown": _nack_provoker("shuttingDown", drain=True),
    # outcome channel
    "fenced": _outcome_provoker(
        "fenced", lambda: messages.ShardFencedError("doc-1", "fenced")),
    "unknownDoc": _outcome_provoker(
        "unknownDoc", lambda: KeyError("no-such-doc")),
    "fault": _outcome_provoker(
        "fault", lambda: RuntimeError("append fault")),
    "shardDead": _shard_dead_provoker,
}


def _connection_lost_provoker():
    exc, calls, rpc = _provoke("connectionLost", _kill_transport)
    assert type(exc) is nd.ConnectionLostError
    assert calls == 1, "a dead socket must never be resent in place"
    assert rpc.retry_counters.get("retry.retries") == 0
    return exc


def test_provokers_cover_exactly_the_registry():
    assert set(PROVOKERS) == set(wire_errors.codes()), (
        "every registered wire code needs a provoker (and every "
        "provoker a registered code): %r"
        % sorted(set(PROVOKERS) ^ set(wire_errors.codes())))


@pytest.mark.parametrize("code", sorted(wire_errors.codes()))
def test_registered_code_round_trips_as_declared(code):
    """The registry row IS the runtime behavior: provoking the code
    against a real server/codec surfaces the declared exception type,
    and reconnect/fatal/nack-paced codes are never resent in place."""
    PROVOKERS[code]()


def test_exception_table_matches_real_hierarchy():
    """Every EXCEPTIONS row names a live class, and the declared parent
    chain is the class's real inheritance — the registry can never
    describe a hierarchy the code does not have (FL-ERR-RETRY walks
    these chains to find reconnect exceptions hiding under retried
    bases)."""
    for name, row in wire_errors.EXCEPTIONS.items():
        cls = _real_exception(name)
        assert isinstance(cls, type) and issubclass(cls, BaseException)
        for ancestor in wire_errors.ancestors(name):
            assert issubclass(cls, _real_exception(ancestor)), (
                name, ancestor)
    # the PR 9 regression, as a registry fact: ConnectionLostError's
    # chain passes through the transport-retried base, which is exactly
    # why every retry site must pin it in no_retry.
    assert "RpcTransportError" in wire_errors.ancestors(
        "ConnectionLostError")
    assert wire_errors.exception_spec(
        "ConnectionLostError")["retry"] == "reconnect"


def test_outcome_decode_tags_unregistered_codes():
    """Taxonomy drift on the outcome channel is stamped into the error
    text, never silently passed off as a registered failure."""
    out = _decode_outcome({"stamped": 0, "consumed": 0,
                           "error": "who knows", "code": "mysteryOutcome"})
    assert "[unregistered:mysteryOutcome]" in str(out.error)


# -- unknown-code hardening (the nack.get("code", "throttled") bugfix) --------


class _ScriptedPeer:
    """A TCP peer speaking the frame protocol but answering every
    request with ONE crafted frame — the version-skewed / corrupt server
    the real OrderingServer can never be."""

    def __init__(self, make_response):
        self._srv = socket.socket()
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(1)
        self.port = self._srv.getsockname()[1]
        self._make = make_response
        threading.Thread(target=self._run, daemon=True).start()

    def _recv_exact(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _run(self):
        conn, _ = self._srv.accept()
        try:
            while True:
                hdr = self._recv_exact(conn, LEN.size)
                if hdr is None:
                    return
                payload = self._recv_exact(conn, LEN.unpack(hdr)[0])
                req = json.loads(payload)
                conn.sendall(frame_bytes(self._make(req)))
        except OSError:
            pass
        finally:
            conn.close()
            self._srv.close()


def _scripted_request(make_response):
    peer = _ScriptedPeer(make_response)
    logger = _CaptureLogger()
    rpc = nd._RpcClient(
        "127.0.0.1", peer.port, timeout=10.0,
        mc=MonitoringContext(logger=logger),
        retry=RetryPolicy(max_attempts=4, base_delay=0.0, budget=1.0))
    try:
        with pytest.raises(Exception) as excinfo:
            rpc.request("anything", {})
    finally:
        rpc.close()
    return excinfo.value, rpc, logger.events


def test_nack_without_code_is_loud_typed_and_unretried():
    """Regression for the silent ``nack.get("code", "throttled")``
    default: a nack missing its code must NOT be paced as a throttle —
    it raises the typed loud error, bumps the drift counter, emits
    telemetry, and is never retried."""
    exc, rpc, events = _scripted_request(lambda req: {
        "v": 1, "re": req["id"], "ok": False, "error": "busy",
        "nack": {"retryAfter": 0.5, "reason": "busy"}})
    assert type(exc) is nd.UnknownWireCodeError
    assert not isinstance(exc, messages.NackError)
    assert exc.channel == "nack" and exc.code is None
    assert rpc.retry_counters.get("rpc.unknown_code") == 1
    assert rpc.retry_counters.get("retry.retries") == 0
    assert any(e.get("eventName", "").endswith("unknownWireCode") for e in events)


def test_nack_with_unregistered_code_is_loud_typed_and_unretried():
    exc, rpc, events = _scripted_request(lambda req: {
        "v": 1, "re": req["id"], "ok": False, "error": "busy",
        "nack": {"retryAfter": 0.5, "reason": "busy",
                 "code": "mysteryPacing"}})
    assert type(exc) is nd.UnknownWireCodeError
    assert exc.channel == "nack" and exc.code == "mysteryPacing"
    assert rpc.retry_counters.get("rpc.unknown_code") == 1
    assert rpc.retry_counters.get("retry.retries") == 0
    assert any(e.get("eventName", "").endswith("unknownWireCode")
               and e.get("channel") == "nack" for e in events)


def test_frame_with_unregistered_code_is_loud_typed_and_unretried():
    exc, rpc, events = _scripted_request(lambda req: {
        "v": 1, "re": req["id"], "ok": False, "error": "??",
        "code": "fluxCapacitor"})
    assert type(exc) is nd.UnknownWireCodeError
    assert exc.channel == "frame" and exc.code == "fluxCapacitor"
    assert rpc.retry_counters.get("rpc.unknown_code") == 1
    assert rpc.retry_counters.get("retry.retries") == 0
    assert any(e.get("eventName", "").endswith("unknownWireCode")
               and e.get("channel") == "frame" for e in events)


def test_codeless_error_frame_still_raises_plain_rpc_error():
    """A bare ``{"ok": false, "error": ...}`` frame (no code at all) is
    legacy-compatible: plain RpcError, not the unknown-code path."""
    exc, rpc, _ = _scripted_request(lambda req: {
        "v": 1, "re": req["id"], "ok": False, "error": "plain refusal"})
    assert type(exc) is nd.RpcError
    assert rpc.retry_counters.get("rpc.unknown_code") == 0
