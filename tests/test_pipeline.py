"""The product's pipelined bulk replay (ops/pipeline.py) vs the one-batch
replay_mergetree_batch: identical summaries in the caller's order across
cold, warm, interval, attribution, and oracle-fallback docs — the service
and the bench harness both ride this path."""

import numpy as np
import pytest

import bench
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    replay_mergetree_batch,
)
from fluidframework_tpu.ops.pipeline import pipelined_mergetree_replay
from fluidframework_tpu.testing.fuzz import StringFuzzSpec, run_fuzz
from fluidframework_tpu.testing.mocks import channel_log
from tests.test_upload_narrow import _warm_doc


def _mixed_docs():
    docs = [bench.synth_doc(i, 40) for i in range(40)]      # cold binary
    docs += [_warm_doc(260 + i) for i in range(3)]          # warm
    for seed in (270, 271):                                  # fuzz logs
        _r, f = run_fuzz(StringFuzzSpec(annotate=True, intervals=True),
                         seed=seed, n_clients=3, rounds=8, sync_every=2)
        docs.append(MergeTreeDocInput(
            doc_id=f"mix{seed}", ops=channel_log(f, "fuzz"),
            final_seq=f.sequencer.seq, final_msn=f.sequencer.min_seq))
    return docs


def test_pipelined_matches_one_batch_replay():
    docs = _mixed_docs()
    base_stats: dict = {}
    expect = [s.digest() for s in replay_mergetree_batch(docs, base_stats)]
    stats: dict = {}
    stage: dict = {}
    packed: list = []
    got = pipelined_mergetree_replay(
        docs, chunk_docs=16, pack_threads=2, extract_threads=2,
        fetch_depth=1, stats=stats, stage=stage, packed_out=packed)
    assert [s.digest() for s in got] == expect, "pipeline changed bytes"
    assert len(packed) == (len(docs) + 15) // 16
    assert all(len(entry) == 4 for entry in packed)  # (state, ops, meta, S)
    assert stats.get("device_docs", 0) > 0
    assert stats.get("fallback_docs", 0) == base_stats.get("fallback_docs", 0)
    assert stage.get("pack", 0) > 0 and stage.get("download", 0) >= 0
    # Honest stage attribution (ISSUE 6): the async fold wait is split
    # out of "download", and the d2h byte counter records real traffic.
    assert "device_wait" in stage
    assert stage.get("d2h_bytes", 0) > 0


def test_pipelined_schedule_returns_caller_order():
    """Fact scheduling reorders chunks internally; results must come back
    in the caller's order (alternate props/pure docs so the sort really
    permutes)."""
    docs = []
    for i in range(30):
        docs.append(bench.synth_doc(3 * i + 1, 32))  # mix annotate/pure
    expect = [s.digest() for s in replay_mergetree_batch(docs)]
    got = pipelined_mergetree_replay(docs, chunk_docs=8)
    assert [s.digest() for s in got] == expect


def test_pipelined_empty_and_single():
    assert pipelined_mergetree_replay([]) == []
    [one] = pipelined_mergetree_replay([bench.synth_doc(5, 24)])
    [ref] = replay_mergetree_batch([bench.synth_doc(5, 24)])
    assert one.digest() == ref.digest()
