"""Device map kernel vs CPU oracle: byte-identical summaries.

The acceptance gate from SURVEY.md §7 layer 3: replay fuzz-generated op logs
through the device LWW kernel and through the oracle; canonical summary bytes
must be equal.  (Runs on the virtual CPU backend under pytest; the same code
path runs on real TPU.)
"""

import pytest

from fluidframework_tpu.dds import SharedMap
from fluidframework_tpu.ops.map_kernel import MapDocInput, replay_map_batch
from fluidframework_tpu.testing.fuzz import MapFuzzSpec, run_fuzz
from fluidframework_tpu.testing.mocks import channel_log


@pytest.mark.parametrize("seed", range(6))
def test_map_kernel_matches_oracle_on_fuzz_logs(seed):
    replicas, factory = run_fuzz(MapFuzzSpec(), seed=seed, n_clients=3, rounds=25)
    oracle_digest = replicas[0].summarize().digest()
    ops = channel_log(factory, "fuzz")
    [summary] = replay_map_batch([MapDocInput(doc_id="fuzz", ops=ops)])
    assert summary.digest() == oracle_digest


def test_map_kernel_batches_many_docs_at_once():
    """Document parallelism: many independent logs in one flat device call."""
    docs, oracle_digests = [], []
    for seed in range(5):
        replicas, factory = run_fuzz(
            MapFuzzSpec(), seed=100 + seed, n_clients=2, rounds=10
        )
        docs.append(
            MapDocInput(doc_id=f"doc{seed}", ops=channel_log(factory, "fuzz"))
        )
        oracle_digests.append(replicas[0].summarize().digest())
    summaries = replay_map_batch(docs)
    assert [s.digest() for s in summaries] == oracle_digests


def test_map_kernel_replays_tail_from_base_summary():
    """Catch-up shape: summary at seq S + op tail == full replay."""
    import json

    replicas, factory = run_fuzz(MapFuzzSpec(), seed=7, n_clients=3, rounds=12)
    ops = channel_log(factory, "fuzz")
    mid_seq = ops[len(ops) // 2].seq
    # Oracle state at the midpoint becomes the base summary.
    partial = SharedMap("fuzz")
    for msg in ops:
        if msg.seq <= mid_seq:
            partial.process(msg, local=False)
    base = json.loads(partial.summarize().blob_bytes("header"))["data"]
    tail = [m for m in ops if m.seq > mid_seq]
    [summary] = replay_map_batch([MapDocInput("fuzz", tail, base=base)])
    assert summary.digest() == replicas[0].summarize().digest()


def test_map_kernel_empty_and_clear_only_docs():
    from fluidframework_tpu.protocol.messages import MessageType, SequencedMessage

    clear = SequencedMessage(
        seq=5, client_id="A", client_seq=1, ref_seq=0, min_seq=0,
        type=MessageType.OP, contents={"kind": "clear"},
    )
    empty, cleared = replay_map_batch(
        [
            MapDocInput("empty", ops=[]),
            MapDocInput("cleared", ops=[clear], base={"k": 1}),
        ]
    )
    fresh = SharedMap("x")
    assert empty.digest() == fresh.summarize().digest()
    assert cleared.digest() == fresh.summarize().digest()
