"""Runtime-shell tests: registry, datastores, batching, summarizer,
catch-up load — the production-shaped stack over the in-proc sequencer."""

import pytest

from fluidframework_tpu.dds.tree import ROOT_ID
from fluidframework_tpu.protocol.sequencer import Sequencer
from fluidframework_tpu.protocol.summary import SummaryStorage
from fluidframework_tpu.runtime import (
    ContainerRuntime,
    SummarizerOptions,
    SummaryManager,
    default_registry,
)


def make_runtime(sequencer, client_id, registry=None):
    rt = ContainerRuntime(registry)
    rt.connect(sequencer, client_id)  # subscribes, backfills, then joins
    return rt


def drain_all(*runtimes):
    for rt in runtimes:
        rt.drain()


def test_registry_types():
    registry = default_registry()
    assert "map-tpu" in registry.types()
    assert "tree-tpu" in registry.types()
    with pytest.raises(KeyError):
        registry.get("bogus")


def test_two_clients_mixed_channels_converge():
    seq = Sequencer()
    a = make_runtime(seq, "alice")
    b = make_runtime(seq, "bob")
    drain_all(a, b)

    ds_a = a.create_datastore("default")
    ds_b = b.create_datastore("default")
    map_a = ds_a.create_channel("map-tpu", "settings")
    str_a = ds_a.create_channel("sequence-tpu", "text")
    map_b = ds_b.create_channel("map-tpu", "settings")
    str_b = ds_b.create_channel("sequence-tpu", "text")

    map_a.set("theme", "dark")
    str_b.insert_text(0, "hello")
    str_a.insert_text(0, ">> ")
    map_b.set("lang", "en")
    drain_all(a, b)

    assert map_a.get("theme") == "dark" and map_b.get("theme") == "dark"
    assert str_a.text == str_b.text
    assert a.summarize().digest() == b.summarize().digest()


def test_grouped_batch_is_atomic():
    seq = Sequencer()
    a = make_runtime(seq, "alice")
    b = make_runtime(seq, "bob")
    drain_all(a, b)
    ds_a = a.create_datastore("d")
    ds_b = b.create_datastore("d")
    m_a = ds_a.create_channel("map-tpu", "m")
    s_a = ds_a.create_channel("sequence-tpu", "s")
    ds_b.create_channel("map-tpu", "m")
    ds_b.create_channel("sequence-tpu", "s")

    head_before = seq.seq  # attach ops for the channels are already out
    with a.order_sequentially():
        m_a.set("k", 1)
        s_a.insert_text(0, "x")
        m_a.set("k2", 2)
    # One grouped message on the wire for the three ops.
    op_msgs = [m for m in seq.log if m.type.value == "op"
               and m.seq > head_before]
    assert len(op_msgs) == 1
    assert len(op_msgs[0].contents["ops"]) == 3
    drain_all(a, b)
    assert a.summarize().digest() == b.summarize().digest()


def test_summary_load_catchup():
    seq = Sequencer()
    a = make_runtime(seq, "alice")
    drain_all(a)
    ds = a.create_datastore("d")
    m = ds.create_channel("map-tpu", "m")
    t = ds.create_channel("tree-tpu", "t")
    m.set("k", "v")
    t.insert(ROOT_ID, "", 0, [t.build("n", value=7)])
    drain_all(a)
    summary = a.summarize()

    m.set("k", "v2")
    t.insert(ROOT_ID, "", 1, [t.build("n", value=8)])
    drain_all(a)

    fresh = ContainerRuntime()
    base_seq = fresh.load(summary)
    for msg in seq.log:
        if msg.seq > base_seq:
            fresh.process(msg)
    assert fresh.summarize().digest() == a.summarize().digest()
    fm = fresh.get_datastore("d").get_channel("m")
    assert fm.get("k") == "v2"


def test_summarizer_election_and_heuristics():
    seq = Sequencer()
    storage = SummaryStorage()
    a = make_runtime(seq, "alice")
    b = make_runtime(seq, "bob")
    mgr_a = SummaryManager(a, storage, "doc",
                           SummarizerOptions(ops_per_summary=5))
    mgr_b = SummaryManager(b, storage, "doc",
                           SummarizerOptions(ops_per_summary=5))
    drain_all(a, b)
    assert mgr_a.election.elected == "alice"  # oldest joins first

    ds_a = a.create_datastore("d")
    ds_b = b.create_datastore("d")
    m_a = ds_a.create_channel("map-tpu", "m")
    ds_b.create_channel("map-tpu", "m")
    for i in range(12):
        m_a.set(f"k{i}", i)
        drain_all(a, b)
    # Alice (elected) summarized at least twice; bob wrote none.
    assert mgr_a.summaries_written >= 2
    assert mgr_b.summaries_written == 0
    # Every client tracked the accepted summary.
    assert mgr_b.last_ack_handle == mgr_a.last_ack_handle
    tree, ref_seq = storage.latest("doc")
    assert tree is not None and ref_seq == mgr_a.last_summary_seq

    # Takeover: alice leaves; bob becomes the summarizer.
    seq.disconnect("alice")
    drain_all(a, b)
    assert mgr_b.election.elected == "bob"
    for i in range(6):
        m_b = b.get_datastore("d").get_channel("m")
        m_b.set(f"x{i}", i)
        drain_all(a, b)
    assert mgr_b.summaries_written >= 1


def test_catchup_from_latest_summary_via_storage():
    """The full catch-up shape: latest summary + op tail from the log."""
    seq = Sequencer()
    storage = SummaryStorage()
    a = make_runtime(seq, "alice")
    SummaryManager(a, storage, "doc", SummarizerOptions(ops_per_summary=4))
    drain_all(a)
    ds = a.create_datastore("d")
    s = ds.create_channel("sequence-tpu", "s")
    for i in range(10):
        s.insert_text(0, f"{i} ")
        drain_all(a)
    tree, base_seq = storage.latest("doc")
    assert tree is not None and base_seq > 0
    fresh = ContainerRuntime()
    loaded_seq = fresh.load(tree)
    assert loaded_seq == base_seq
    for msg in seq.log:
        if msg.seq > loaded_seq:
            fresh.process(msg)
    assert fresh.summarize().digest() == a.summarize().digest()
    assert fresh.get_datastore("d").get_channel("s").text == s.text


def test_channel_attach_materializes_on_remote():
    """A dynamically created channel announces itself: peers that never
    created it locally materialize it from the sequenced attach op."""
    seq = Sequencer()
    a = make_runtime(seq, "alice")
    b = make_runtime(seq, "bob")
    drain_all(a, b)
    ds_a = a.create_datastore("d")
    ds_a.create_channel("map-tpu", "m")
    ds_a.get_channel("m").set("k", 1)
    drain_all(a, b)
    assert b.get_datastore("d").get_channel("m").get("k") == 1
    assert a.summarize().digest() == b.summarize().digest()


def test_unknown_channel_op_raises():
    """A genuinely unknown channel (no attach op, not in any summary) is a
    corruption signal: routing raises rather than dropping silently."""
    from fluidframework_tpu.protocol.messages import (
        MessageType as MT,
        SequencedMessage as SM,
    )

    seq = Sequencer()
    b = make_runtime(seq, "bob")
    drain_all(b)
    b.create_datastore("d")
    b.drain()
    rogue = SM(
        seq=seq.seq + 1, client_id="ghost", client_seq=1,
        ref_seq=seq.seq, min_seq=0, type=MT.OP,
        contents={"type": "groupedBatch", "ops": [
            {"clientSeq": 1, "ds": "d", "channel": "nope", "contents": {}}
        ]},
    )
    with pytest.raises(KeyError):
        b.process(rogue)
