"""Family-generic pipeline, second instance (ISSUE 14): SharedTree
through the four-tier catch-up stack.

The acceptance matrix: ``tree pipelined-on == pipelined-off ==
replay_tree_batch == dds/tree.py oracle`` on golden shapes AND 3-seed
fuzz logs, across warm summary re-entry, grown-tail suffix hits, forced
repacks, every fallback shape (per-reason counted), and the mesh twin.
"""

import dataclasses

import numpy as np
import pytest

from fluidframework_tpu.ops.tree_kernel import (
    TreeDocInput,
    oracle_fallback_summary,
    replay_tree_batch,
)
from fluidframework_tpu.ops.tree_pipeline import (
    pipelined_tree_replay,
    tree_device_cache,
    tree_pack_cache,
)
from fluidframework_tpu.service.catchup_cache import DeltaExportCache
from tests.test_tree_kernel import run_fuzz_doc
from tools.bench_kernels import synth_tree_messages, tree_doc, tree_shape


def _caches():
    return dict(pack_cache=tree_pack_cache(),
                device_cache=tree_device_cache(),
                delta_cache=DeltaExportCache())


def _digests(summaries):
    return [s.digest() for s in summaries]


def _fold(docs, caches, **kw):
    stage: dict = {}
    stats: dict = {}
    out = _digests(pipelined_tree_replay(docs, chunk_docs=8, stage=stage,
                                         stats=stats, **caches, **kw))
    return out, stage, stats


def _fuzz_docs(seed, n=6, steps=40, cut=0):
    docs = []
    for k in range(n):
        _f, _t, log, fs, fm = run_fuzz_doc(seed * 100 + k, steps=steps,
                                           with_moves=(k % 2 == 0))
        window = log[:len(log) - cut] if cut else log
        docs.append(TreeDocInput(
            f"d{seed}-{k}", ops=window, final_seq=window[-1].seq,
            final_msn=(fm if not cut else 0),
            cache_token=("ep", f"d{seed}-{k}", 0, "")))
    return docs


def test_golden_parity_every_shape():
    """The bench generator's five shapes (deep-move chains, wide
    containers, revive, multi-id move, MAX_DEPTH overflow): caches-on ==
    caches-off == replay_tree_batch == dds oracle, with the per-reason
    fallback split live."""
    docs = [tree_doc(i, synth_tree_messages(i, 40), 40) for i in range(32)]
    assert {tree_shape(i) for i in range(32)} == {
        "deep-move", "wide-container", "revive", "multi_id_move",
        "max_depth"}
    oracle = [oracle_fallback_summary(d).digest() for d in docs]
    on, _stage, stats = _fold(docs, _caches())
    off, _stage2, _stats2 = _fold(docs, {})
    assert on == oracle
    assert off == oracle
    assert _digests(replay_tree_batch(list(docs))) == oracle
    assert stats["fallback_docs"] == (
        stats.get("fallback_revive", 0)
        + stats.get("fallback_multi_id_move", 0)
        + stats.get("fallback_max_depth", 0)
        + stats.get("fallback_purged_parent_insert", 0)
        + stats.get("fallback_base_limbo", 0))
    assert stats.get("fallback_revive", 0) >= 1
    assert stats.get("fallback_multi_id_move", 0) >= 1
    assert stats.get("fallback_max_depth", 0) >= 1


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_fuzz_parity_pipelined_on_off_batch_oracle(seed):
    docs = _fuzz_docs(seed)
    oracle = [oracle_fallback_summary(d).digest() for d in docs]
    on, _st, _s = _fold(docs, _caches())
    off, _st2, _s2 = _fold(docs, {})
    assert on == oracle
    assert off == oracle
    assert _digests(replay_tree_batch(list(docs))) == oracle


def test_warm_summary_reentry_through_the_tiers():
    """A warm base summary re-enters the kernel as packed base state;
    the warm window then serves exact from tiers 2/2.5/0 with zero
    upload and only the digest plane downloaded."""
    from fluidframework_tpu.dds.tree import SharedTree

    docs = []
    for k in range(4):
        _f, _t, log, fs, fm = run_fuzz_doc(7000 + k, steps=36,
                                           with_moves=(k % 2 == 0))
        mid = len(log) // 2
        base = SharedTree("t")
        for m in log[:mid]:
            base.process(m, local=False)
        docs.append(TreeDocInput(
            f"w{k}", ops=log[mid:], base_summary=base.summarize(),
            final_seq=fs, final_msn=fm,
            cache_token=("ep", f"w{k}", 0, "")))
    oracle = [oracle_fallback_summary(d).digest() for d in docs]
    caches = _caches()
    cold, _stage, _stats = _fold(docs, caches)
    warm, stage, stats = _fold(docs, caches)
    assert cold == oracle and warm == oracle
    # Warm-base docs without fallback shapes serve exact: zero h2d, the
    # digest plane only on d2h.
    n_device = stats.get("delta_docs", 0)
    assert n_device >= 1
    assert stage.get("h2d_bytes", 0) == 0
    assert caches["pack_cache"].stats()["exact_hits"] >= 1
    assert caches["device_cache"].stats()["served"] >= 1


def test_grown_tail_suffix_hits_and_splice():
    """A grown tail extends the cached window: tier 2 packs ONLY the
    suffix (suffix_hits), tier 2.5 splices in place (spliced) with the
    h2d bytes collapsing to the new rows, and the bytes stay oracle-
    identical."""
    base = _fuzz_docs(31, cut=3)
    full = _fuzz_docs(31, cut=0)
    oracle = [oracle_fallback_summary(d).digest() for d in full]
    caches = _caches()
    _fold(base, caches)
    grown, stage, _stats = _fold(full, caches)
    assert grown == oracle
    assert caches["pack_cache"].stats()["suffix_hits"] >= 1
    assert caches["device_cache"].stats()["spliced"] >= 1
    _off, stage_off, _s = _fold(full, {})
    assert stage["h2d_bytes"] < stage_off["h2d_bytes"], (
        "suffix splice did not shrink the upload")


def test_second_splice_advances_the_watermark():
    """Two consecutive grown-tail splices: the resident entry's edit-row
    watermark must advance with each splice (review-found: a stale
    watermark makes every later splice re-upload all rows since the
    last full store), so the second splice gathers only the SECOND
    round's rows — and the bytes stay oracle-identical."""
    from fluidframework_tpu.ops.tree_pipeline import TreeDeviceOps

    base = _fuzz_docs(31, cut=4)
    mid = _fuzz_docs(31, cut=2)
    full = _fuzz_docs(31, cut=0)
    caches = _caches()
    _fold(base, caches)
    _fold(mid, caches)
    grown, _stage, _stats = _fold(full, caches)
    assert grown == [oracle_fallback_summary(d).digest() for d in full]
    dev = caches["device_cache"]
    assert dev.stats()["spliced"] == 2
    (entry,) = dev._entries.values()
    np.testing.assert_array_equal(
        np.asarray(entry.t_rows), TreeDeviceOps.t_rows(entry.ops))


def test_forced_repack_on_bucket_growth_still_byte_identical():
    """A tail that blows the edit-row bucket must REPACK (no suffix
    hit), never corrupt — the tier loses the win, keeps the bytes."""
    msgs = synth_tree_messages(3, 120)  # wide-container shape
    base = [tree_doc(3, msgs, 30)]      # bucket 32
    full = [tree_doc(3, msgs, 120)]     # bucket 128: forced repack
    caches = _caches()
    _fold(base, caches)
    grown, _stage, _stats = _fold(full, caches)
    assert grown == [oracle_fallback_summary(full[0]).digest()]
    assert caches["pack_cache"].stats()["suffix_hits"] == 0
    assert caches["pack_cache"].stats()["misses"] >= 2
    assert caches["device_cache"].stats()["spliced"] == 0


def test_duplicate_id_suffix_forces_repack_never_corrupts():
    """A grown tail whose suffix re-inserts an ALREADY-INTERNED node id
    (nothing validates client-minted ids) rewrites a row BELOW the
    cached watermark — which the device splice could never mirror.  The
    extension must bail to a full repack, bytes staying identical to
    the caches-off fold and the oracle."""
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    def msg(seq, edits):
        return SequencedMessage(
            seq=seq, client_id="c0", client_seq=seq, ref_seq=seq - 1,
            min_seq=0, type=MessageType.OP, contents={"edits": edits})

    def spec(nid, value):
        return {"id": nid, "type": "n", "value": value}

    def ins(nid, value, field="a"):
        return {"kind": "insert", "parent": "", "field": field,
                "anchor": None, "content": [spec(nid, value)]}

    log = [msg(1, [ins("n1", 1)]), msg(2, [ins("n2", 2)]),
           msg(3, [ins("n1", 99)]),           # the duplicate-id suffix
           msg(4, [ins("n3", 3, field="b")])]

    def doc(n):
        window = log[:n]
        return TreeDocInput(
            "dupdoc", ops=window, final_seq=window[-1].seq,
            cache_token=("ep", "dupdoc", 0, ""))

    caches = _caches()
    _fold([doc(2)], caches)
    grown, _stage, _stats = _fold([doc(4)], caches)
    assert grown == [oracle_fallback_summary(doc(4)).digest()]
    off, _st, _s = _fold([doc(4)], {})
    assert off == grown
    assert caches["pack_cache"].stats()["suffix_hits"] == 0, (
        "duplicate-id suffix must force a full repack")
    assert caches["device_cache"].stats()["spliced"] == 0


def test_partial_delta_gather_serves_unchanged_docs():
    """One chunk, SOME docs grown: the tier-0 route goes partial — the
    unchanged docs serve cached summaries, only the changed docs' forest
    rows cross — and the merged result is byte-identical."""
    streams = [synth_tree_messages(100 + i, 40) for i in range(8)]
    # keep non-fallback shapes so every doc stays on the device path
    streams = [s for i, s in enumerate(streams)
               if tree_shape(100 + i) in ("deep-move", "wide-container")]
    base = [tree_doc(i, s, len(s) - 2) for i, s in enumerate(streams)]
    grown = [tree_doc(i, s, len(s) if i % 2 else len(s) - 2)
             for i, s in enumerate(streams)]
    oracle = [oracle_fallback_summary(d).digest() for d in grown]
    caches = _caches()
    _fold(base, caches)
    got, _stage, stats = _fold(grown, caches)
    assert got == oracle
    delta = caches["delta_cache"].stats()
    assert delta["served"] >= 1, delta
    # a grown doc drifts its HOST ANCHOR (window length moved), which is
    # a tier-0 miss — `changed` is reserved for digest mismatches under
    # a matching anchor (pinned in tests/test_delta_download.py)
    assert delta["misses"] >= 1, delta
    assert stats.get("delta_docs", 0) >= 1


def test_mesh_tree_stack_parity_and_stage_schema():
    """The forced 8-device CPU mesh (conftest) serves the IDENTICAL
    four-tier stack: byte parity with the single-device pipeline, warm
    serves from the resident tier, and the same stage-key schema."""
    from fluidframework_tpu.parallel.shard import (
        doc_mesh,
        replay_tree_sharded,
    )

    docs = _fuzz_docs(21, n=5)
    oracle = [oracle_fallback_summary(d).digest() for d in docs]
    pack, dev, delta = tree_pack_cache(), tree_device_cache(), \
        DeltaExportCache()
    stage: dict = {}
    stats: dict = {}
    mesh = doc_mesh()
    cold = _digests(replay_tree_sharded(
        docs, mesh=mesh, stage=stage, stats=stats, pack_cache=pack,
        delta_cache=delta, device_cache=dev))
    assert cold == oracle
    single_stage: dict = {}
    single = _digests(pipelined_tree_replay(docs, chunk_docs=8,
                                            stage=single_stage))
    assert single == oracle
    assert set(stage) == set(single_stage), (
        f"mesh stage schema {sorted(stage)} != "
        f"single-device {sorted(single_stage)}")
    warm_stage: dict = {}
    warm = _digests(replay_tree_sharded(
        docs, mesh=mesh, stage=warm_stage, pack_cache=pack,
        delta_cache=delta, device_cache=dev))
    assert warm == oracle
    assert dev.stats()["served"] >= 1
    assert pack.stats()["exact_hits"] >= 1
    assert warm_stage.get("h2d_bytes", 0) == 0
    # the digest plane is the only d2h traffic on a fully-served chunk
    assert warm_stage.get("d2h_bytes", 0) <= 8 * (len(docs) + mesh.size)


def test_tree_digest_is_padding_invariant():
    """An unchanged document's digest survives a NEIGHBOUR's growth
    (bucket padding moves, its own rows do not)."""
    import jax.numpy as jnp

    from fluidframework_tpu.ops.tree_kernel import pack_tree_batch
    from fluidframework_tpu.ops.tree_pipeline import (
        _tree_export_fn,
    )

    small = [tree_doc(5, synth_tree_messages(5, 24), 24),
             tree_doc(7, synth_tree_messages(7, 12), 12)]
    big = [small[0],
           tree_doc(9, synth_tree_messages(9, 100), 100)]

    def digest_of(docs, d):
        state, edits, meta = pack_tree_batch(docs)
        out = _tree_export_fn(True)(
            state, edits, jnp.asarray(meta["n_nodes"]),
            jnp.asarray(meta["n_cont"]))
        return tuple(np.asarray(out[-1])[d])

    assert digest_of(small, 0) == digest_of(big, 0)
    assert digest_of(small, 0) != digest_of(small, 1)


def test_tree_collab_swarm_converges_and_probes_the_tree_tiers():
    """The fluidscale tree-collab family: boxed tree changesets through
    the real sharded service, oracle-twin convergence, and the
    fold_probe catching sampled docs up through the REAL CatchupService
    tree route (the second family's serving-tier counters live)."""
    from fluidframework_tpu.testing.scenarios import (
        build_scenario,
        run_swarm,
        run_swarm_with_oracle,
    )

    spec = build_scenario("tree-collab", seed=4, clients=300, docs=4,
                          shards=2)
    spec = dataclasses.replace(spec, fold_probe=True, sample_every=2)
    result, oracle = run_swarm_with_oracle(spec)
    assert result.sampled_digests == oracle.sampled_digests
    assert result.per_doc_head == oracle.per_doc_head
    assert result.ops_stamped > 0
    tier = result.fold_tier
    assert tier["tree_pack_cache"]["exact_hits"] >= 1, tier
    assert tier["tree_device_cache"]["served"] >= 1, tier
    assert tier["fallback_channels"] == 0
    # replay identity survives the new per-client tree bookkeeping
    again = run_swarm(dataclasses.replace(spec, fold_probe=False))
    probe_free = dataclasses.replace(spec, fold_probe=False)
    assert run_swarm(probe_free).identity() == again.identity()
