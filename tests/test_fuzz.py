"""Seeded fuzz: eventual-consistency across many seeds and client counts.

The reference's fuzz-testing strategy (SURVEY.md §4): convergence is the
oracle — after full delivery, every replica must have byte-identical canonical
summaries.  Failures print the seed for regression capture.
"""

import pytest

from fluidframework_tpu.testing.fuzz import (
    DirectoryFuzzSpec,
    MapFuzzSpec,
    MatrixFuzzSpec,
    QueueFuzzSpec,
    RegisterFuzzSpec,
    StringFuzzSpec,
    run_fuzz,
)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_shared_string(seed):
    run_fuzz(StringFuzzSpec(), seed=seed, n_clients=3, rounds=40)


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_shared_string_many_clients(seed):
    run_fuzz(StringFuzzSpec(), seed=1000 + seed, n_clients=5, rounds=25)


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_shared_map(seed):
    run_fuzz(MapFuzzSpec(), seed=seed, n_clients=4, rounds=30)


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_shared_directory(seed):
    run_fuzz(DirectoryFuzzSpec(), seed=seed, n_clients=3, rounds=30)


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_matrix(seed):
    run_fuzz(MatrixFuzzSpec(), seed=seed, n_clients=3, rounds=30)


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_matrix_fww(seed):
    run_fuzz(MatrixFuzzSpec(fww=True), seed=500 + seed, n_clients=3, rounds=30)


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_consensus_registers(seed):
    run_fuzz(RegisterFuzzSpec(), seed=700 + seed, n_clients=4, rounds=30)


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_consensus_queue(seed):
    run_fuzz(QueueFuzzSpec(), seed=800 + seed, n_clients=3, rounds=30)
