"""Summary-anchored log truncation safety (ISSUE 16, satellite 3).

Kill-9-style crash coverage at BOTH truncation fault points — seal
(before the marker is durable) and drop (after the marker, before
compaction) — with reopen recovery asserted byte-identical against an
untruncated oracle, plus the gap-repair boundary contract and recovery
of a truncated document from its marker checkpoint.
"""

import os

import pytest

from fluidframework_tpu.protocol.messages import MessageType, RawOperation
from fluidframework_tpu.protocol.wire import encode_sequenced_message
from fluidframework_tpu.protocol.summary import SummaryStorage
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service import LocalOrderingService, OpLog
from fluidframework_tpu.service.catchup import CatchupService
from fluidframework_tpu.service.oplog import TruncatedRangeError
from fluidframework_tpu.testing.faults import (
    FaultError, FaultInjector, FaultPlan, FaultPoint,
)


def op(client, client_seq, ref_seq=0, contents=None):
    return RawOperation(
        client_id=client, client_seq=client_seq, ref_seq=ref_seq,
        type=MessageType.OP, contents=contents or {"k": client_seq},
    )


def _fill(service, doc_id="doc", n=10, client="a"):
    ep = service.create_document(doc_id) \
        if not service.has_document(doc_id) else service.endpoint(doc_id)
    if client not in ep._orderer.sequencer._slots:
        ep.connect(client)
    for i in range(1, n + 1):
        ep.submit(op(client, i, ref_seq=ep.head_seq))
    return ep


def _records(oplog, doc_id="doc"):
    """The full byte-identity view of one doc's surviving records."""
    floor = oplog.floor(doc_id)
    return [encode_sequenced_message(m)
            for m in oplog.get(doc_id, from_seq=floor)]


# -- the floor contract (in-memory) ------------------------------------------


def test_truncate_drops_prefix_and_guards_reads():
    service = LocalOrderingService()
    _fill(service, n=10)  # head 11: JOIN + 10 ops
    log = service.oplog
    dropped = log.truncate("doc", 6)
    assert dropped == 6
    assert log.floor("doc") == 6
    assert log.head("doc") == 11
    # Exact-boundary gap repair is legal (half-open: floor excluded).
    assert [m.seq for m in log.get("doc", from_seq=6)] == [7, 8, 9, 10, 11]
    with pytest.raises(TruncatedRangeError):
        log.get("doc", from_seq=5)
    assert log.is_contiguous("doc")
    # Re-truncating at/below the floor is a no-op, not a corruption.
    assert log.truncate("doc", 6) == 0
    assert log.truncate("doc", 3) == 0


def test_truncate_clamps_to_head_and_empty_log_head_is_floor():
    service = LocalOrderingService()
    _fill(service, n=4)  # head 5
    log = service.oplog
    assert log.truncate("doc", 99) == 5  # clamped: everything sealed
    assert log.floor("doc") == 5
    assert log.head("doc") == 5  # empty log answers its floor
    assert log.get("doc", from_seq=5) == []


# -- crash at the SEAL point (before the marker is durable) ------------------


def test_seal_crash_reopens_byte_identical_to_untruncated_oracle(tmp_path):
    path = str(tmp_path / "ops.jsonl")
    plan = FaultPlan(seed=0, points=(
        FaultPoint("oplog.truncate.seal", "fail", at=1),))
    log = OpLog(path, autoflush=True, faults=FaultInjector(plan))
    service = LocalOrderingService(oplog=log)
    _fill(service, n=10)
    oracle = _records(log)  # the untruncated truth, pre-crash
    with pytest.raises(FaultError):
        log.truncate("doc", 6, checkpoint=service._orderers["doc"].checkpoint())
    # Crashed BEFORE the marker hit the file: nothing sealed.
    assert log.floor("doc") == 0
    log.close()  # kill -9: reopen from bytes alone
    reopened = OpLog(path)
    assert reopened.floor("doc") == 0
    assert reopened.truncation_checkpoint("doc") is None
    assert _records(reopened) == oracle
    assert reopened.is_contiguous("doc")


# -- crash at the DROP point (marker durable, compaction lost) ---------------


def test_drop_crash_marker_is_durable_and_reopen_converges(tmp_path):
    path = str(tmp_path / "ops.jsonl")
    plan = FaultPlan(seed=0, points=(
        FaultPoint("oplog.truncate.drop", "fail", at=1),))
    log = OpLog(path, autoflush=True, faults=FaultInjector(plan))
    service = LocalOrderingService(oplog=log)
    _fill(service, n=10)
    oracle_tail = [encode_sequenced_message(m)
                   for m in log.get("doc", from_seq=6)]
    bytes_before = os.path.getsize(path)
    with pytest.raises(FaultError):
        log.truncate("doc", 6, checkpoint=service._orderers["doc"].checkpoint())
    # The marker IS the commit point: the floor applied even though the
    # compaction never ran (dead bytes linger until the next rewrite).
    assert log.floor("doc") == 6
    log.close()
    reopened = OpLog(path)
    assert reopened.floor("doc") == 6
    assert reopened.truncation_checkpoint("doc") is not None
    assert _records(reopened) == oracle_tail
    with pytest.raises(TruncatedRangeError):
        reopened.get("doc", from_seq=5)
    # A clean truncation on the reopened log compacts the file: the
    # sealed prefix's dead bytes are finally reclaimed.
    reopened.truncate("doc", 8)
    assert os.path.getsize(path) < bytes_before
    assert reopened.bytes_reclaimed > 0
    assert [m.seq for m in reopened.get("doc", from_seq=8)] == [9, 10, 11]


def test_both_crash_points_then_clean_retry_is_exactly_once(tmp_path):
    # seal-crash, retry drop-crashes, retry succeeds: the floor moves
    # once, the drop count is exact, no record is dropped twice.
    path = str(tmp_path / "ops.jsonl")
    plan = FaultPlan(seed=0, points=(
        FaultPoint("oplog.truncate.seal", "fail", at=1),
        FaultPoint("oplog.truncate.drop", "fail", at=1),))
    injector = FaultInjector(plan)
    log = OpLog(path, autoflush=True, faults=injector)
    service = LocalOrderingService(oplog=log)
    _fill(service, n=10)
    with pytest.raises(FaultError):
        log.truncate("doc", 6)
    assert log.floor("doc") == 0
    with pytest.raises(FaultError):
        log.truncate("doc", 6)
    assert log.floor("doc") == 6  # marker durable on attempt 2
    assert log.truncate("doc", 6) == 0  # already sealed: no-op
    assert log.truncate("doc", 7) == 1  # one more record, exactly once
    assert log.truncated_msgs == 7
    assert not injector.unfired()


# -- gap repair at exactly the truncation boundary ---------------------------


def test_gap_repair_at_exact_boundary_after_reopen(tmp_path):
    path = str(tmp_path / "ops.jsonl")
    log = OpLog(path, autoflush=True)
    service = LocalOrderingService(oplog=log)
    _fill(service, n=10)
    log.truncate("doc", 6, checkpoint=service._orderers["doc"].checkpoint())
    log.close()
    reopened = OpLog(path)
    # A client whose last-seen seq IS the floor repairs its gap fine...
    assert [m.seq for m in reopened.get("doc", from_seq=6)][:2] == [7, 8]
    # ...one seq older and the log refuses loudly (re-anchor on the
    # summary instead of silently serving a hole).
    with pytest.raises(TruncatedRangeError) as exc:
        reopened.get("doc", from_seq=5)
    assert "floor" in str(exc.value)


# -- recovery of a truncated document ----------------------------------------


def test_truncated_doc_recovers_from_marker_checkpoint(tmp_path):
    """Full replay is impossible below the floor — recovery must restore
    the sequencer from the truncation marker's checkpoint and resume
    stamping contiguously."""
    path = str(tmp_path / "ops.jsonl")
    log = OpLog(path, autoflush=True)
    service = LocalOrderingService(oplog=log)
    ep = _fill(service, n=10)  # head 11
    log.truncate("doc", 6, checkpoint=service._orderers["doc"].checkpoint())
    log.close()

    service2 = LocalOrderingService(oplog=OpLog(path))
    assert service2.has_document("doc")
    ep2 = service2.endpoint("doc")
    assert ep2.head_seq == 11
    # Dedup floor survived the truncation: a replayed old client_seq is
    # rejected, the next fresh one stamps head+1.
    assert ep2.submit(op("a", 10, ref_seq=11)) is None
    msg = ep2.submit(op("a", 11, ref_seq=11))
    assert msg is not None and msg.seq == 12
    assert service2.oplog.is_contiguous("doc")
    assert ep.head_seq == 11  # the dead incarnation stayed at 11


def test_truncated_catchup_converges_with_untruncated_oracle(tmp_path):
    """End to end: summary + truncated tail folds to the same bytes as
    the oracle that never truncated."""
    def seeded(oplog):
        storage = SummaryStorage()
        rt = ContainerRuntime()
        rt.create_datastore("ds").create_channel("sequence-tpu", "text")
        storage.upload("doc", rt.summarize(), 0)
        service = LocalOrderingService(oplog=oplog, storage=storage)
        service.create_document("doc")
        ep = service.endpoint("doc")
        ep.connect("c")
        for i in range(1, 13):
            ep.submit(RawOperation(
                client_id="c", client_seq=i, ref_seq=ep.head_seq,
                type=MessageType.OP,
                contents={"type": "groupedBatch", "ops": [
                    {"ds": "ds", "channel": "text", "clientSeq": i,
                     "contents": {"kind": "insert", "pos": 0,
                                  "text": "x"}}]}))
        return service

    truncated = seeded(OpLog(str(tmp_path / "t.jsonl"), autoflush=True))
    oracle = seeded(OpLog(str(tmp_path / "o.jsonl"), autoflush=True))
    # Publish a mid-stream summary, then cut behind it.
    mid = CatchupService(truncated, mesh=None).catch_up(
        ["doc"], upload=True)
    _handle, ref = mid["doc"]
    truncated.oplog.truncate("doc", ref - 4,
                             checkpoint=truncated._orderers["doc"].checkpoint())
    assert truncated.oplog.floor("doc") > 0
    got = CatchupService(truncated, mesh=None).catch_up(
        ["doc"], upload=False)
    want = CatchupService(oracle, mesh=None).catch_up(
        ["doc"], upload=False)
    # upload=False returns (content digest, ref_seq): byte identity.
    assert got["doc"] == want["doc"]


# -- import-side floor adoption ----------------------------------------------


def test_adopt_floor_carries_truncation_across_migration(tmp_path):
    src = OpLog(str(tmp_path / "src.jsonl"), autoflush=True)
    service = LocalOrderingService(oplog=src)
    _fill(service, n=10)
    ckpt = service._orderers["doc"].checkpoint()
    src.truncate("doc", 6, checkpoint=ckpt)

    dst = OpLog(str(tmp_path / "dst.jsonl"), autoflush=True)
    # Migration: adopt the source's floor FIRST (truncate() would clamp
    # to the empty destination's head 0), then replay the tail.
    dst.adopt_floor("doc", src.floor("doc"),
                    src.truncation_checkpoint("doc"))
    for m in src.get("doc", from_seq=src.floor("doc")):
        dst.append("doc", m)
    assert dst.floor("doc") == 6
    assert dst.head("doc") == 11
    assert _records(dst) == _records(src)
    with pytest.raises(TruncatedRangeError):
        dst.get("doc", from_seq=5)
    dst.close()
    # The adopted marker is durable: a reopen still refuses sealed reads
    # and still knows the recovery checkpoint.
    reopened = OpLog(str(tmp_path / "dst.jsonl"))
    assert reopened.floor("doc") == 6
    assert reopened.truncation_checkpoint("doc") is not None
