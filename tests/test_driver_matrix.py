"""E2E × driver matrix (SURVEY.md §4): ONE collaboration scenario runs
unchanged over every driver — in-proc local, durable file-backed, and the
TCP network driver — asserting identical behavior and byte-identical
summaries in each deployment shape."""

import time

import pytest

from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalOrderingService


def _local_factory(tmp_path):
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory

    service = LocalOrderingService()
    make = lambda: LocalDocumentServiceFactory(service)  # noqa: E731
    return make, lambda: None


def _file_factory(tmp_path):
    from fluidframework_tpu.drivers import FileDocumentServiceFactory

    factory = FileDocumentServiceFactory(str(tmp_path / "store"))
    return (lambda: factory), (lambda: None)


def _network_factory(tmp_path):
    from fluidframework_tpu.drivers.network_driver import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.service.server import OrderingServer

    srv = OrderingServer(port=0)
    srv.start_in_thread()
    factories = []

    def make():
        f = NetworkDocumentServiceFactory(port=srv.port)
        factories.append(f)
        return f

    return make, lambda: [f.close() for f in factories]


DRIVERS = {
    "local": _local_factory,
    "file": _file_factory,
    "network": _network_factory,
}


@pytest.mark.parametrize("driver", sorted(DRIVERS))
def test_scenario_runs_identically_on_every_driver(driver, tmp_path):
    make_factory, cleanup = DRIVERS[driver](tmp_path)
    try:
        a = Loader(make_factory()).create(
            "doc", "alice",
            lambda rt: rt.create_datastore("ds").create_channel(
                "sequence-tpu", "t"),
        )
        b = Loader(make_factory()).resolve("doc", "bob")
        ta = a.runtime.get_datastore("ds").get_channel("t")
        tb = b.runtime.get_datastore("ds").get_channel("t")

        ta.insert_text(0, "hello world")
        a.drain()
        deadline = time.time() + 10
        while time.time() < deadline and tb.text != "hello world":
            b.drain()
            time.sleep(0.01)
        tb.obliterate_range(5, 11)
        b.drain()
        deadline = time.time() + 10
        while time.time() < deadline:
            a.drain()
            b.drain()
            if ta.text == tb.text == "hello":
                break
            time.sleep(0.01)
        assert ta.text == tb.text == "hello"

        # a third, fresh client loads the same bytes on every driver
        fresh = Loader(make_factory()).resolve("doc")
        assert fresh.runtime.get_datastore("ds").get_channel("t").text == \
            "hello"
    finally:
        cleanup()
