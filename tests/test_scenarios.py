"""fluidscale (ISSUE 10): the batched ingress surface and the columnar
swarm scenario engine.

Layers covered:

- ``Sequencer.submit_many`` / ``connect_many`` — batch stamping semantics
  (per-batch MSN, dedup, abort-and-resubmit contract);
- ``OpLog.batch`` — ONE fsync per batch on an autoflush durable log;
- service ``submit_many`` — per-document failure isolation and post-
  failover recovery with zero caller-side special cases;
- the swarm engine — same-seed replay bit-identity for EVERY named
  scenario, byte-identity of sampled docs against the fault-free
  single-shard oracle twin (mid-run shard kill included), and the
  deferred-batch mirror under injected durable faults;
- ``tools/loadgen.py`` — scenario listing and the BENCH JSON schema.

The 10³-client smokes are tier-1 (wall-budgeted); the 10⁵ matrix is
``slow``-marked.
"""

import dataclasses
import json
import os
import time

import pytest

from fluidframework_tpu.protocol.messages import (BatchAbortedError,
                                                  MessageType, RawOperation,
                                                  ShardFencedError)
from fluidframework_tpu.protocol.sequencer import Sequencer
from fluidframework_tpu.service.oplog import OpLog
from fluidframework_tpu.service.orderer import LocalOrderingService
from fluidframework_tpu.service.sharding import ShardedOrderingService
from fluidframework_tpu.testing.faults import FaultPlan, FaultPoint
from fluidframework_tpu.testing.scenarios import (SCENARIOS, build_scenario,
                                                  run_swarm,
                                                  run_swarm_with_oracle,
                                                  scenario_docs)


def _op(cid, cs, ref=0, payload=None):
    return RawOperation(client_id=cid, client_seq=cs, ref_seq=ref,
                        type=MessageType.OP,
                        contents=payload or {"n": cs})


# -- sequencer batch stamping --------------------------------------------------


def test_submit_many_stamps_in_order_with_batch_msn():
    seq = Sequencer()
    seq.connect_many(["a", "b"])
    msgs = seq.submit_many([_op("a", 1), _op("b", 1), _op("a", 2)])
    assert [m.seq for m in msgs] == [3, 4, 5]  # after 2 JOINs
    # batch messages carry the BATCH-START MSN (conservative floor)...
    assert {m.min_seq for m in msgs} == {msgs[0].min_seq}
    # ...and the end-of-batch recompute folds the new ref_seqs in
    before = seq.min_seq
    seq.submit_many([_op("a", 3, ref=5), _op("b", 2, ref=5)])
    assert seq.min_seq >= before


def test_submit_many_skips_duplicates_and_resubmit_dedups():
    seq = Sequencer()
    seq.connect_many(["a"])
    batch = [_op("a", 1), _op("a", 2)]
    first = seq.submit_many(batch)
    assert len(first) == 2
    # whole-batch resubmit (the recovery contract): nothing re-stamps
    again = seq.submit_many(batch + [_op("a", 3)])
    assert [m.client_seq for m in again] == [3]
    assert seq.seq == first[-1].seq + 1


def test_submit_many_abort_carries_prefix_and_unwinds_cleanly():
    seq = Sequencer()
    seq.connect_many(["a"])
    boom = RuntimeError("durable refused")
    calls = {"n": 0}

    def durability_gate(msg):
        calls["n"] += 1
        if calls["n"] == 3:  # the 3rd batch message fails
            raise boom

    seq.subscribe(durability_gate)
    batch = [_op("a", i + 1) for i in range(4)]
    with pytest.raises(BatchAbortedError) as err:
        seq.submit_many(batch)
    assert err.value.consumed == 2
    assert [m.client_seq for m in err.value.stamped] == [1, 2]
    assert err.value.cause is boom
    # the failed stamp unwound: the whole batch resubmits, ops 1-2 dedup,
    # ops 3-4 stamp fresh at the SAME next seq numbers
    seq.unsubscribe(durability_gate)
    retry = seq.submit_many(batch)
    assert [m.client_seq for m in retry] == [3, 4]
    assert [m.seq for m in retry] == [err.value.stamped[-1].seq + 1,
                                      err.value.stamped[-1].seq + 2]


def test_connect_many_matches_sequential_connects():
    batched, serial = Sequencer(), Sequencer()
    batched.connect_many(["a", "b", "c"])
    for cid in ("a", "b", "c"):
        serial.connect(cid)
    assert [m.contents for m in batched.log] == \
        [m.contents for m in serial.log]
    assert batched.checkpoint()["clients"].keys() == \
        serial.checkpoint()["clients"].keys()
    # same-session re-connect resumes without a duplicate JOIN
    batched.connect_many(["b"], session=None)  # no session: LEAVE+JOIN
    head = batched.seq
    batched.connect_many(["b"], session="s1")  # fresh session: LEAVE+JOIN
    assert batched.seq == head + 2
    batched.connect_many(["b"], session="s1")  # resume: stamps nothing
    assert batched.seq == head + 2


# -- oplog group commit --------------------------------------------------------


def test_oplog_batch_pays_one_fsync(tmp_path, monkeypatch):
    flushes = {"n": 0}
    real_fsync = os.fsync

    def counting_fsync(fd):
        flushes["n"] += 1
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", counting_fsync)
    log = OpLog(str(tmp_path / "ops.jsonl"), autoflush=True)
    service = LocalOrderingService(oplog=log)
    ep = service.create_document("doc")
    ep.connect_many(["a"])
    flushes["n"] = 0
    service.submit_many({"doc": [_op("a", i + 1) for i in range(16)]})
    assert flushes["n"] == 1, "16 appends must group-commit as ONE fsync"
    # outside a batch, autoflush still fsyncs per append
    flushes["n"] = 0
    ep.submit(_op("a", 17))
    assert flushes["n"] == 1
    log.close()
    # the grouped records are durable and replayable
    reopened = OpLog(str(tmp_path / "ops.jsonl"))
    assert reopened.head("doc") == service.oplog.head("doc")


def test_oplog_batch_flushes_landed_prefix_on_abort(tmp_path):
    log = OpLog(str(tmp_path / "ops.jsonl"), autoflush=True)
    try:
        with log.batch():
            from fluidframework_tpu.protocol.messages import \
                SequencedMessage

            log.append("d", SequencedMessage(
                seq=1, client_id="a", client_seq=1, ref_seq=0, min_seq=0,
                type=MessageType.OP, contents={}))
            raise RuntimeError("mid-batch crash")
    except RuntimeError:
        pass
    log.close()
    assert OpLog(str(tmp_path / "ops.jsonl")).head("d") == 1


def test_submit_many_never_swallows_interrupts():
    """KeyboardInterrupt mid-batch must propagate — not be converted
    into a per-document SubmitOutcome a retry loop would swallow."""
    service = LocalOrderingService()
    ep = service.create_document("doc")
    ep.connect_many(["a"])

    def interrupter(msg):
        raise KeyboardInterrupt

    ep.subscribe(interrupter)
    with pytest.raises(KeyboardInterrupt):
        service.submit_many({"doc": [_op("a", 1)]})


def test_failed_deferred_flush_stays_dirty_and_retries(tmp_path):
    """A group-commit flush that fails at batch exit keeps the batch
    dirty: the records' bytes are already written, so the next
    successful flush (here: close) makes them durable — no silent
    unrepairable hole."""
    from fluidframework_tpu.testing.faults import FaultInjector

    # occurrence 1 is the JOIN's autoflush; occurrence 2 is the batch-
    # exit group-commit flush — the one under test
    plan = FaultPlan(points=(FaultPoint("oplog.flush", "fail", at=2),))
    log = OpLog(str(tmp_path / "ops.jsonl"), autoflush=True,
                faults=FaultInjector(plan))
    service = LocalOrderingService(oplog=log)
    ep = service.create_document("doc")
    ep.connect_many(["a"])
    with pytest.raises(OSError):
        with log.batch():
            ep.submit(_op("a", 1))
    log.close()  # retries the flush (fault is spent) — records land
    assert OpLog(str(tmp_path / "ops.jsonl")).head("doc") == \
        service.oplog.head("doc")


# -- service-level batched ingress --------------------------------------------


def test_service_submit_many_isolates_fenced_documents():
    service = ShardedOrderingService(n_shards=4)
    for doc in ("d0", "d1", "d2", "d3"):
        service.create_document(doc).connect_many([f"{doc}-c"])
    victim = service.shard_of("d0")
    fenced = set(service.kill_shard(victim))
    batches = {doc: [_op(f"{doc}-c", 1)] for doc in ("d0", "d1", "d2", "d3")}
    out = service.submit_many(batches)
    # every document lands — the fenced ones recover lazily inside the
    # endpoint() route, so there is no caller-visible error at all
    for doc, outcome in out.items():
        assert outcome.error is None, (doc, outcome.error)
        assert len(outcome.stamped) == 1
    assert fenced  # the kill really re-owned something


def test_endpoint_submit_batch_fails_fast_on_fenced_orderer():
    service = ShardedOrderingService(n_shards=2)
    ep = service.create_document("doc")
    ep.connect_many(["c"])
    service.kill_shard(service.shard_of("doc"))
    with pytest.raises(ShardFencedError):
        ep.submit_batch([_op("c", 1)])  # the OLD endpoint object


# -- the swarm: smokes, replay identity, oracle -------------------------------

#: wall budget per 10³-client smoke (generous: measured ~0.3s each; the
#: budget exists to catch an accidental O(population²) inner loop)
SMOKE_BUDGET_SEC = 60.0


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_smoke_1k_clients_under_budget(name):
    t0 = time.monotonic()
    spec = build_scenario(name, seed=2, clients=1000, docs=8, shards=4)
    result = run_swarm(spec)
    assert time.monotonic() - t0 < SMOKE_BUDGET_SEC
    assert result.joins == 1000
    assert result.ops_stamped > 0
    assert result.delivery_samples == result.sequenced_ops
    assert result.sampled_digests


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_replay_is_bit_identical(name):
    spec = build_scenario(name, seed=6, clients=600, docs=6, shards=4)
    a, b = run_swarm(spec), run_swarm(spec)
    # the whole result — metrics, per-site fault observations, telemetry
    # counters, per-phase attribution — is the identity surface
    assert a.identity() == b.identity()


def test_failover_drill_converges_to_oracle_twin():
    spec = build_scenario("failover-drill", seed=5, clients=800, docs=8,
                          shards=4)
    result, oracle = run_swarm_with_oracle(spec)
    assert result.kills, "the scheduled shard kill must execute"
    assert result.fault_counts.get("shard.kill:kill") == 1
    assert oracle.kills == () and oracle.fault_counts == {}
    assert result.sampled_digests == oracle.sampled_digests
    assert result.per_doc_head == oracle.per_doc_head


def test_injected_append_faults_defer_and_still_match_oracle():
    """Mid-batch durable failures defer whole batches; the oracle twin
    replays the recorded deferral schedule and the logs still converge
    byte-identically — faults cost deferrals, never state."""
    spec = build_scenario("failover-drill", seed=9, clients=600, docs=6,
                          shards=4)
    plan = FaultPlan(seed=9, points=spec.plan.points + (
        FaultPoint("oplog.append", "fail", doc="sw-0002", at=5, count=2),
        FaultPoint("oplog.append", "fail", at=200, count=1),
    ))
    spec = dataclasses.replace(spec, plan=plan)
    result, oracle = run_swarm_with_oracle(spec)
    assert result.defers or result.join_defers, \
        "the injected faults must actually defer a batch"
    assert result.fault_counts.get("oplog.append:fail", 0) >= 2
    assert oracle.defers == result.defers
    assert oracle.join_defers == result.join_defers
    assert result.sampled_digests == oracle.sampled_digests
    assert result.per_doc_head == oracle.per_doc_head


def test_herd_and_laggards_produce_catchup_samples():
    for name in ("catchup-herd", "laggard-window"):
        spec = build_scenario(name, seed=7, clients=600, docs=6, shards=4)
        spec = dataclasses.replace(spec, catchup_rate=16)
        result = run_swarm(spec)
        assert result.catchup_samples > 0, name
        assert result.max_pending_depth > 0, name
        # per-phase counter attribution (CounterSet.delta): the cohort
        # phase is where the catch-up completions land
        phase_keys = [k for k in result.phase_counters
                      if k.endswith(("herd", "laggards"))]
        assert phase_keys, result.phase_counters.keys()


def test_durable_swarm_group_commits(tmp_path, monkeypatch):
    """A file-backed swarm run: group commit keeps the fsync count at
    O(ticks), not O(messages) — the serving-side win the batched ingress
    exists for."""
    flushes = {"n": 0}
    real_fsync = os.fsync

    def counting_fsync(fd):
        flushes["n"] += 1
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", counting_fsync)
    spec = build_scenario("steady-typing", seed=3, clients=400, docs=4,
                          shards=4)
    spec = dataclasses.replace(spec, dir=str(tmp_path))
    result = run_swarm(spec)
    assert result.sequenced_ops > 800
    assert flushes["n"] < result.sequenced_ops / 2, (
        flushes["n"], result.sequenced_ops)


# -- loadgen CLI ---------------------------------------------------------------


def test_loadgen_list_prints_every_scenario(capsys):
    from tools.loadgen import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name, doc in scenario_docs().items():
        assert name in out
        assert doc.split()[0] in out


def test_loadgen_writes_schema_stable_bench_json(tmp_path, capsys):
    from tools.loadgen import main

    out = tmp_path / "bench.json"
    rc = main(["--scenario", "steady-typing", "--clients", "400",
               "--docs", "4", "--seed", "3", "--no-oracle",
               "--out", str(out)])
    capsys.readouterr()
    assert rc == 0
    doc = json.loads(out.read_text())
    scenario = doc["scenarios"]["steady-typing"]
    # schema-stable nulls: skipped checks are present, not absent
    assert scenario["oracle_match"] is None
    assert scenario["replay_identical"] is None
    assert scenario["passed"] is True
    assert scenario["ops_per_sec"] > 0
    # the shared writer sorts keys — the file round-trips canonically
    assert out.read_text() == json.dumps(doc, indent=2, sort_keys=True) + "\n"


def test_columnar_smoke_10k_clients_under_budget():
    """ISSUE 11 tier-1 smoke: a 10⁴-client columnar steady-typing run
    through the real 4-shard service, inside the wall budget (the 10⁶
    matrix is slow-marked; this is the always-on canary for the
    columnar wire path's scaling shape)."""
    t0 = time.monotonic()
    spec = build_scenario("steady-typing", seed=11, clients=10_000,
                          docs=16, shards=4)
    result = run_swarm(spec)
    assert time.monotonic() - t0 < SMOKE_BUDGET_SEC
    assert result.joins == 10_000
    assert result.ops_stamped > 10_000
    assert result.ingress["columnar_ops"] > 0
    assert result.ingress["encode_bytes"] > 0
    # ingress accounting is wall-derived and OUTSIDE replay identity
    assert "ingress" not in result.identity()


# -- the 10⁵ matrix (slow tier) -----------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scale_matrix_100k_clients(name):
    """The r10 acceptance run: 10⁵ virtual clients through the real
    4-shard service on CPU, oracle-converged, within the slow budget."""
    spec = build_scenario(name, seed=10, clients=100_000, docs=128,
                          shards=4)
    result, oracle = run_swarm_with_oracle(spec)
    assert result.joins == 100_000
    assert result.sequenced_ops > 200_000
    assert result.sampled_digests == oracle.sampled_digests
    assert result.per_doc_head == oracle.per_doc_head


@pytest.mark.slow
def test_scale_matrix_1m_clients_columnar():
    """The r11 acceptance run: 10⁶ virtual clients through the columnar
    wire path on the real 4-shard service, oracle-converged."""
    spec = build_scenario("steady-typing", seed=10, clients=1_000_000,
                          docs=1024, shards=4)
    spec = dataclasses.replace(spec, sample_every=64)
    result, oracle = run_swarm_with_oracle(spec)
    assert result.joins == 1_000_000
    assert result.sequenced_ops > 2_000_000
    assert result.sampled_digests == oracle.sampled_digests
    assert result.per_doc_head == oracle.per_doc_head


def test_fold_probe_reports_resident_tier_counters():
    """ISSUE 13 satellite: ``fold_probe`` catches the sampled docs up
    cold+warm through a REAL CatchupService after the run — the warm
    pass must serve resident (tier 2.5) and delta (tier 0) hits — and
    the counters land in ``fold_tier``, OUTSIDE replay identity (a
    probe-off run's identity is bit-equal)."""
    spec = build_scenario("catchup-herd", seed=5, clients=96, docs=8,
                          shards=2)
    probed = dataclasses.replace(spec, fold_probe=True)
    result = run_swarm(probed)
    ft = result.fold_tier
    assert ft["docs"] == len(result.sampled_digests) >= 1
    assert ft["device_cache"]["inserts"] >= 1
    assert ft["device_cache"]["served"] >= 1, ft["device_cache"]
    assert ft["delta_cache"]["served"] >= 1, ft["delta_cache"]
    assert ft["pack_cache"]["exact_hits"] >= 1
    assert ft["h2d_bytes"] > 0 and ft["d2h_bytes"] > 0
    assert "fold_tier" not in result.identity()
    off = run_swarm(spec)
    assert off.fold_tier == {}
    assert off.identity() == result.identity(), (
        "the fold probe perturbed replay identity")
