"""Tier-1 gate: fluidlint must run clean over the whole package.

Pure-AST analysis — no JAX tracing, CPU-only, fast.  A new finding
anywhere in ``fluidframework_tpu/`` fails this test; the only escape
hatch is a reviewed entry (with a non-empty ``reason``) in
``lint_baseline.json``, and stale/reason-less entries fail too, so the
baseline can only shrink through review.
"""

import pathlib
import subprocess
import sys

from tools.fluidlint import (all_rules, analyze, apply_baseline,
                             baseline_function_hygiene, load_baseline)

ROOT = pathlib.Path(__file__).resolve().parents[1]
BASELINE = ROOT / "lint_baseline.json"


def test_package_lints_clean():
    findings = analyze(ROOT)
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    report = apply_baseline(findings, entries)
    problems = [f.render() for f in report.unsuppressed]
    problems += [f"baseline invalid: {m}" for m in report.invalid]
    problems += [
        f"baseline stale (matched no finding): [{e.get('rule')}] "
        f"{e.get('path')}: {e.get('message')}" for e in report.stale
    ]
    # Hygiene: function-scoped suppression keys rot when the function
    # they name disappears; a rotten entry fails the gate like a stale
    # one (the finding it reviewed no longer describes live code).
    problems += [f"baseline hygiene: {m}"
                 for m in baseline_function_hygiene(ROOT, entries)]
    assert not problems, (
        "fluidlint gate failed — fix the finding or add a REVIEWED "
        "suppression (with reason) to lint_baseline.json:\n"
        + "\n".join(problems))


def test_every_rule_registered_and_described():
    rules = all_rules()
    assert len(rules) >= 15, sorted(rules)  # 9 (PR 2) + 6 fluidrace
    for name, rule in rules.items():
        assert rule.description, f"{name} has no description"
        assert rule.severity in ("error", "warning"), name


def test_cli_exit_code_clean():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.fluidlint",
         "--baseline", "lint_baseline.json"],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 error(s)" in proc.stdout, proc.stdout


def test_cli_exit_code_on_findings(tmp_path, capsys):
    """The gate is real, not vacuous: a violation in a synthetic tree
    makes the CLI exit 1 and print the finding."""
    from tools.fluidlint.cli import main

    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    assert main(["--root", str(tmp_path)]) == 1
    assert "FL-DET-CLOCK" in capsys.readouterr().out


def test_cli_write_baseline_bootstraps_missing_file(tmp_path, capsys):
    """`--baseline X --write-baseline X` with no X yet is the bootstrap
    flow: it must write the skeleton, not die on 'baseline not found'
    (--write-baseline never reads the baseline)."""
    import json

    from tools.fluidlint.cli import main

    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    out = tmp_path / "lint_baseline.json"
    assert main(["--root", str(tmp_path), "--baseline", str(out),
                 "--write-baseline", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert len(doc["suppressions"]) == 1
    # a path that IS read (analysis / --check-baseline) still errors
    missing = str(tmp_path / "nope.json")
    assert main(["--root", str(tmp_path), "--baseline", missing]) == 2
    assert main(["--root", str(tmp_path), "--baseline", missing,
                 "--check-baseline"]) == 2
    capsys.readouterr()
