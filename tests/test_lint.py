"""Tier-1 gate: fluidlint must run clean over the whole package.

Pure-AST analysis — no JAX tracing, CPU-only, fast.  A new finding
anywhere in ``fluidframework_tpu/`` fails this test; the only escape
hatch is a reviewed entry (with a non-empty ``reason``) in
``lint_baseline.json``, and stale/reason-less entries fail too, so the
baseline can only shrink through review.
"""

import pathlib

from tools.fluidlint import (all_rules, analyze, apply_baseline,
                             baseline_function_hygiene,
                             baseline_rule_hygiene, load_baseline)

ROOT = pathlib.Path(__file__).resolve().parents[1]
BASELINE = ROOT / "lint_baseline.json"


def test_package_lints_clean():
    """The one full three-family analysis pass of tier-1: every other
    lint test here runs against synthetic trees or in-memory sources, so
    the package-wide walk is paid exactly once per suite run."""
    findings = analyze(ROOT)
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    report = apply_baseline(findings, entries)
    problems = [f.render() for f in report.unsuppressed]
    problems += [f"baseline invalid: {m}" for m in report.invalid]
    problems += [
        f"baseline stale (matched no finding): [{e.get('rule')}] "
        f"{e.get('path')}: {e.get('message')}" for e in report.stale
    ]
    # Hygiene: suppression entries rot two ways — the function their
    # message names disappears, or the rule id itself is unregistered
    # (renamed/deleted rule).  Both fail the gate like a stale entry
    # (the finding they reviewed no longer describes live code).
    problems += [f"baseline hygiene: {m}"
                 for m in baseline_rule_hygiene(entries)
                 + baseline_function_hygiene(ROOT, entries)]
    assert not problems, (
        "fluidlint gate failed — fix the finding or add a REVIEWED "
        "suppression (with reason) to lint_baseline.json:\n"
        + "\n".join(problems))


def test_sharding_tier_modules_lint_clean_with_zero_suppressions():
    """ISSUE 7 acceptance pin: the two new serving modules pass ALL
    module rules (fluidlint + fluidrace + fluidleak families) with zero
    findings AND zero baseline entries — the package gate would let a
    reviewed suppression through; this test would not."""
    new_modules = [
        "fluidframework_tpu/service/sharding.py",
        "fluidframework_tpu/service/broadcaster.py",
    ]
    findings = analyze(ROOT, relpaths=new_modules)
    assert findings == [], [f.render() for f in findings]
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    offenders = [e for e in entries if e.get("path") in new_modules]
    assert offenders == [], "new modules must stay suppression-free"


def test_faultline_modules_lint_clean_with_zero_suppressions():
    """ISSUE 9 acceptance pin: the fault-injection engine and the retry
    policy pass ALL module rules (fluidlint + fluidrace + fluidleak
    families) with zero findings AND zero baseline entries — robustness
    machinery must hold itself to the discipline it enforces (bounded
    waits, no swallowed failures, no wall-clock on replay paths)."""
    new_modules = [
        "fluidframework_tpu/testing/faults.py",
        "fluidframework_tpu/service/retry.py",
    ]
    findings = analyze(ROOT, relpaths=new_modules)
    assert findings == [], [f.render() for f in findings]
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    offenders = [e for e in entries if e.get("path") in new_modules]
    assert offenders == [], "new modules must stay suppression-free"


def test_baseline_is_empty():
    """ISSUE 10 satellite pin: the last two FL-RACE-CHECKACT
    suppressions are BURNED — file_driver's probe-load-setdefault and
    catchup_cache's timeout reap are each restructured so every guarded
    touch is one critical section (probe/publish and reap helpers) — and
    the baseline is pinned at ZERO entries.  It can only stay empty:
    a new finding must be fixed, not reviewed in."""
    entries = load_baseline(BASELINE)
    assert entries == [], [e.get("path") for e in entries]


def test_fluidscale_modules_lint_clean_with_zero_suppressions():
    """ISSUE 10 acceptance pin: the swarm engine and the batched-ingress
    surfaces it drives pass ALL module rules (fluidlint + fluidrace +
    fluidleak families) with zero findings AND zero baseline entries —
    the scale harness must hold itself to the determinism and lifecycle
    discipline it measures."""
    new_modules = [
        "fluidframework_tpu/testing/scenarios.py",
        "fluidframework_tpu/protocol/sequencer.py",
        "fluidframework_tpu/service/oplog.py",
    ]
    findings = analyze(ROOT, relpaths=new_modules)
    assert findings == [], [f.render() for f in findings]
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    offenders = [e for e in entries if e.get("path") in new_modules]
    assert offenders == [], "new modules must stay suppression-free"


def test_fluidproc_modules_lint_clean_with_zero_suppressions():
    """ISSUE 12 acceptance pin: the out-of-process tier — shard host,
    front door (supervision, failover, live migration), and the proc
    client adapter — passes ALL module rules (fluidlint + fluidrace +
    fluidleak families) with zero findings AND zero baseline entries.
    Deployment machinery gets no exemptions: bounded waits, no wall
    clock on replay paths, every child process reaped or supervised."""
    new_modules = [
        "fluidframework_tpu/service/shardhost.py",
        "fluidframework_tpu/service/frontdoor.py",
        "fluidframework_tpu/service/procclient.py",
    ]
    findings = analyze(ROOT, relpaths=new_modules)
    assert findings == [], [f.render() for f in findings]
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    offenders = [e for e in entries if e.get("path") in new_modules]
    assert offenders == [], "new modules must stay suppression-free"


def test_device_cache_module_lints_clean_with_zero_suppressions():
    """ISSUE 13 acceptance pin: the device-resident pack-buffer tier
    passes ALL module rules (fluidlint + fluidrace + fluidleak families)
    with zero findings AND zero baseline entries — the module that
    donates device buffers must itself satisfy the donated-read
    discipline (FL-TRACE-DONATE) it motivated."""
    new_modules = [
        "fluidframework_tpu/ops/device_cache.py",
    ]
    findings = analyze(ROOT, relpaths=new_modules)
    assert findings == [], [f.render() for f in findings]
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    offenders = [e for e in entries if e.get("path") in new_modules]
    assert offenders == [], "new modules must stay suppression-free"


def test_kernel_family_modules_lint_clean_with_zero_suppressions():
    """ISSUE 14 acceptance pin: every module the family-generic pipeline
    refactor touched or created — the descriptor, both family bindings,
    the generic tiers, the mesh twin, the reason-counting router, and
    the second-family bench harness — passes ALL module rules (fluidlint
    + fluidrace + fluidleak families) with zero findings AND zero
    baseline entries.  The load-bearing generalization layer gets no
    exemptions."""
    new_modules = [
        "fluidframework_tpu/ops/family.py",
        "fluidframework_tpu/ops/pipeline.py",
        "fluidframework_tpu/ops/tree_pipeline.py",
        "fluidframework_tpu/ops/tree_kernel.py",
        "fluidframework_tpu/ops/batching.py",
        "fluidframework_tpu/ops/device_cache.py",
        "fluidframework_tpu/parallel/shard.py",
        "fluidframework_tpu/service/catchup.py",
        "tools/bench_kernels.py",
    ]
    findings = analyze(ROOT, relpaths=new_modules)
    assert findings == [], [f.render() for f in findings]
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    offenders = [e for e in entries if e.get("path") in new_modules]
    assert offenders == [], "new modules must stay suppression-free"


def test_fluiddur_modules_lint_clean_with_zero_suppressions():
    """ISSUE 17 acceptance pin: every module the durability family
    annotates — the oplog, the sequencer, both temp-write→publish
    drivers, the gate registry and its two consumers — passes ALL module
    rules (all four families) with zero findings AND zero baseline
    entries.  The crash-consistency contract is enforced, not reviewed
    around."""
    new_modules = [
        "fluidframework_tpu/service/oplog.py",
        "fluidframework_tpu/service/gates.py",
        "fluidframework_tpu/service/shardhost.py",
        "fluidframework_tpu/service/catchup.py",
        "fluidframework_tpu/service/server.py",
        "fluidframework_tpu/protocol/sequencer.py",
        "fluidframework_tpu/drivers/file_driver.py",
        "fluidframework_tpu/ops/native_pack.py",
    ]
    findings = analyze(ROOT, relpaths=new_modules)
    assert findings == [], [f.render() for f in findings]
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    offenders = [e for e in entries if e.get("path") in new_modules]
    assert offenders == [], "durability-annotated modules stay clean"


def test_fluidfail_modules_lint_clean_with_zero_suppressions():
    """ISSUE 19 acceptance pin: the error-taxonomy registry and every
    module the FL-ERR family audits — the five serving/driver modules
    that produce or consume wire error codes — pass ALL module rules
    with zero findings AND zero baseline entries.  The true positives
    the family caught (untyped broad handlers on reply paths, the
    ConnectionLostError retry hole) were FIXED, never baselined."""
    new_modules = [
        "fluidframework_tpu/protocol/errors.py",
        "fluidframework_tpu/drivers/network_driver.py",
        "fluidframework_tpu/service/server.py",
        "fluidframework_tpu/service/frontdoor.py",
        "fluidframework_tpu/service/shardhost.py",
        "fluidframework_tpu/service/procclient.py",
    ]
    findings = analyze(ROOT, relpaths=new_modules)
    assert findings == [], [f.render() for f in findings]
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    offenders = [e for e in entries if e.get("path") in new_modules]
    assert offenders == [], "error-taxonomy modules stay suppression-free"


def test_fluidshape_modules_lint_clean_with_zero_suppressions():
    """ISSUE 20 acceptance pin: every module the kernel family audits —
    the Pallas fold, both kernel families, the resident-buffer cache,
    the pipeline, and the mesh twin — passes ALL module rules (all six
    families) with zero findings AND zero baseline entries.  The true
    positives the family caught (unannotated narrow casts in the export
    path, the unroutable delta-fetch gather index) were annotated with
    reviewed reasons, never baselined."""
    new_modules = [
        "fluidframework_tpu/ops/pallas_fold.py",
        "fluidframework_tpu/ops/mergetree_kernel.py",
        "fluidframework_tpu/ops/tree_kernel.py",
        "fluidframework_tpu/ops/device_cache.py",
        "fluidframework_tpu/ops/pipeline.py",
        "fluidframework_tpu/ops/family.py",
        "fluidframework_tpu/ops/interning.py",
        "fluidframework_tpu/parallel/shard.py",
    ]
    findings = analyze(ROOT, relpaths=new_modules)
    assert findings == [], [f.render() for f in findings]
    entries = load_baseline(BASELINE) if BASELINE.is_file() else []
    offenders = [e for e in entries if e.get("path") in new_modules]
    assert offenders == [], "kernel-layer modules stay suppression-free"


def test_counter_names_asserted_in_tests_are_produced():
    """ISSUE 17 satellite: counter-name drift.  Every namespaced counter
    literal a test references (catchup.*, fd.*, retry.*, swarm.*) must
    appear as a ``.bump()`` literal in the package — a renamed producer
    otherwise turns the assertion into a vacuous ``.get()`` default and
    the regression goes green."""
    import ast
    import re

    produced = set()
    for path in (ROOT / "fluidframework_tpu").rglob("*.py"):
        tree = ast.parse(path.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            # direct counter bumps plus one-level bump-forwarding
            # helpers (the storm driver's `self._bump("swarm.storm_x")`
            # routes its literal to counters.bump)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr.endswith("bump") and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                produced.add(node.args[0].value)
    namespaces = {n.split(".", 1)[0] for n in produced if "." in n}
    assert namespaces, "no namespaced counters produced — check .bump() scan"
    # fault sites share the dotted-lowercase shape ('catchup.slow'); they
    # are owned by the seam registry, not the counter producers
    from fluidframework_tpu.testing import faults
    sites = set(faults.SITES)
    shape = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")
    drifted = {}
    for path in sorted((ROOT / "tests").glob("*.py")):
        tree = ast.parse(path.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            lit = node.value
            if (shape.match(lit) and lit.split(".", 1)[0] in namespaces
                    and lit not in sites and lit not in produced):
                drifted.setdefault(lit, []).append(
                    f"{path.name}:{node.lineno}")
    assert not drifted, (
        f"tests reference counter names no package code bumps: {drifted}")


def test_every_rule_registered_and_described():
    rules = all_rules()
    # 9 (PR 2) + 6 fluidrace (PR 4) + 6 fluidleak (PR 5) + donate (PR 13)
    # + 6 fluiddur (PR 17) + 5 fluidfail (PR 19) + 5 fluidshape (PR 20)
    assert len(rules) >= 38, sorted(rules)
    for name, rule in rules.items():
        assert rule.description, f"{name} has no description"
        assert rule.severity in ("error", "warning"), name


def test_readme_catalog_covers_every_rule():
    """Docs cannot drift from the registry: the README rule tables must
    mention every registered rule id (pairs with --list-rules, which
    renders the same registry)."""
    text = (ROOT / "tools" / "fluidlint" / "README.md").read_text(
        encoding="utf-8")
    missing = [name for name in all_rules() if f"`{name}`" not in text]
    assert not missing, (
        f"tools/fluidlint/README.md does not document: {missing}")


def test_cli_list_rules_reports_family_and_severity(capsys):
    from tools.fluidlint.cli import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name, rule in all_rules().items():
        lines = [ln for ln in out.splitlines() if ln.startswith(name + " ")]
        assert len(lines) == 1, f"--list-rules missing {name}"
        assert f"/{rule.severity}]" in lines[0]
    assert "[lifecycle/error]" in out and "[concurrency/" in out


def test_cli_rules_family_filter(capsys):
    """ISSUE 17 satellite: `--rules dur` selects exactly the durability
    family (family name, not just rule-id prefix), and an unknown
    selector is a usage error, not a vacuously-clean run."""
    from tools.fluidlint.cli import main, rule_family

    assert main(["--rules", "dur", "--list-rules"]) == 0
    out = capsys.readouterr().out
    listed = {ln.split(" ", 1)[0] for ln in out.splitlines() if ln}
    expected = {name for name, rule in all_rules().items()
                if rule_family(rule) == "durability"}
    assert listed == expected and len(expected) == 6, (listed, expected)
    assert all("[durability/" in ln for ln in out.splitlines() if ln)
    assert main(["--rules", "nosuchfamily", "--list-rules"]) == 2
    capsys.readouterr()


def test_cli_rules_err_family_filter(capsys):
    """ISSUE 19: `--rules err` selects exactly the five-rule FL-ERR
    family (the error-taxonomy analyzer runs standalone)."""
    from tools.fluidlint.cli import main, rule_family

    assert main(["--rules", "err", "--list-rules"]) == 0
    out = capsys.readouterr().out
    listed = {ln.split(" ", 1)[0] for ln in out.splitlines() if ln}
    expected = {name for name, rule in all_rules().items()
                if rule_family(rule) == "errors"}
    assert listed == expected and len(expected) == 5, (listed, expected)
    assert all("[errors/" in ln for ln in out.splitlines() if ln)


def test_cli_rules_kern_family_filter(capsys):
    """ISSUE 20: `--rules kern` selects exactly the five-rule FL-KERN
    family (the kernel shape/dtype analyzer runs standalone — it is the
    first gate of tools/tpu_preflight.py)."""
    from tools.fluidlint.cli import main, rule_family

    assert main(["--rules", "kern", "--list-rules"]) == 0
    out = capsys.readouterr().out
    listed = {ln.split(" ", 1)[0] for ln in out.splitlines() if ln}
    expected = {name for name, rule in all_rules().items()
                if rule_family(rule) == "kernel"}
    assert listed == expected and len(expected) == 5, (listed, expected)
    assert all("[kernel/" in ln for ln in out.splitlines() if ln)


def test_cli_rules_family_filter_scopes_analysis(tmp_path, capsys):
    """A family-scoped run only reports that family's findings: a tree
    with one determinism violation is clean under `--rules dur`, red
    under `--rules det`."""
    from tools.fluidlint.cli import main

    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    assert main(["--root", str(tmp_path), "--rules", "dur"]) == 0
    assert main(["--root", str(tmp_path), "--rules", "det"]) == 1
    capsys.readouterr()


def test_cli_exit_code_clean(tmp_path, capsys):
    # Pins the CLI wiring (exit 0 + summary line) against a tiny clean
    # tree: the package-wide walk is paid exactly once per suite run,
    # in test_package_lints_clean — never re-run here.
    from tools.fluidlint.cli import main

    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "ok.py").write_text("def fine():\n    return 1\n")
    assert main(["--root", str(tmp_path)]) == 0
    assert "0 error(s)" in capsys.readouterr().out


def test_module_entry_point_runs(tmp_path):
    """`python -m tools.fluidlint` is the documented gate command —
    __main__.py and the package import wiring need real subprocess
    coverage (over a one-file tree, so the package walk stays cheap)."""
    import subprocess
    import sys

    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.fluidlint",
         "--root", str(tmp_path)],
        cwd=str(ROOT), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stderr
    assert "FL-DET-CLOCK" in proc.stdout


def test_cli_exit_code_on_findings(tmp_path, capsys):
    """The gate is real, not vacuous: a violation in a synthetic tree
    makes the CLI exit 1 and print the finding."""
    from tools.fluidlint.cli import main

    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    assert main(["--root", str(tmp_path)]) == 1
    assert "FL-DET-CLOCK" in capsys.readouterr().out


def _seeded_git_tree(tmp_path):
    """A two-commit synthetic repo for --diff: ``stale.py`` carries a
    pre-existing finding and never changes after commit one;
    ``touched.py`` gains a finding in commit two; ``gone.py`` is deleted
    in commit two; ``fresh.py`` is untracked working-tree state."""
    import subprocess

    def git(*argv):
        subprocess.run(
            ["git", "-C", str(tmp_path), "-c", "user.email=t@test",
             "-c", "user.name=t", *argv],
            check=True, capture_output=True)

    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    bad = "import time\n\ndef hold():\n    return time.time()\n"
    (pkg / "stale.py").write_text(bad)
    (pkg / "touched.py").write_text("def fine():\n    return 1\n")
    (pkg / "gone.py").write_text("def bye():\n    return 2\n")
    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "one")
    (pkg / "touched.py").write_text(bad)
    git("rm", "-q", str(pkg / "gone.py"))
    git("add", "-A")
    git("commit", "-qm", "two")
    (pkg / "fresh.py").write_text(bad)
    return pkg


def test_cli_diff_lints_only_changed_files(tmp_path, capsys):
    """ISSUE 19 satellite: `--diff GIT_REF` analyzes exactly the
    Python files changed since the ref (committed + working tree +
    untracked, deletions dropped) and reports the same findings a full
    run restricted to those files would — pre-existing findings in
    unchanged files stay out of the report."""
    import json

    from tools.fluidlint.cli import main

    _seeded_git_tree(tmp_path)
    assert main(["--root", str(tmp_path), "--diff", "HEAD~1",
                 "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert {f["path"] for f in report["unsuppressed"]} == {
        "fluidframework_tpu/loader/touched.py",
        "fluidframework_tpu/loader/fresh.py"}
    # identical findings contract: a full run restricted to the changed
    # files (the documented equivalence) produces the same report
    assert main(["--root", str(tmp_path),
                 "fluidframework_tpu/loader/touched.py",
                 "fluidframework_tpu/loader/fresh.py", "--json"]) == 1
    explicit = json.loads(capsys.readouterr().out)
    assert report["unsuppressed"] == explicit["unsuppressed"]
    # the unchanged file's finding exists — only a FULL run surfaces it
    assert main(["--root", str(tmp_path), "--json"]) == 1
    full = json.loads(capsys.readouterr().out)
    assert "fluidframework_tpu/loader/stale.py" in {
        f["path"] for f in full["unsuppressed"]}


def test_cli_diff_usage_and_git_errors(tmp_path, capsys):
    """--diff composes with nothing that contradicts it: explicit paths
    alongside it, an unknown ref, or a root outside any git repo are
    usage errors (exit 2), never a vacuously-clean exit 0."""
    from tools.fluidlint.cli import main

    repo = tmp_path / "repo"
    repo.mkdir()
    _seeded_git_tree(repo)
    assert main(["--root", str(repo), "--diff", "HEAD",
                 "fluidframework_tpu/loader/touched.py"]) == 2
    assert main(["--root", str(repo), "--diff", "no-such-ref"]) == 2
    # a root outside ANY git repo (sibling of the seeded one, so git
    # discovery cannot walk up into it)
    bare = tmp_path / "not-a-repo"
    (bare / "fluidframework_tpu").mkdir(parents=True)
    assert main(["--root", str(bare), "--diff", "HEAD"]) == 2
    capsys.readouterr()


def test_cli_sarif_writes_valid_report(tmp_path, capsys):
    """ISSUE 20 satellite: `--sarif FILE` writes a SARIF 2.1.0 document
    — registry as the tool driver, findings as results with
    repo-relative locations — while the text output and the exit code
    stay exactly what they were without it."""
    import json

    from tools.fluidlint.cli import main

    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    sarif = tmp_path / "out.sarif"
    assert main(["--root", str(tmp_path), "--sarif", str(sarif)]) == 1
    assert "FL-DET-CLOCK" in capsys.readouterr().out
    doc = json.loads(sarif.read_text())
    assert doc["version"] == "2.1.0" and "2.1.0" in doc["$schema"]
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "fluidlint"
    ids = {r["id"] for r in driver["rules"]}
    assert "FL-DET-CLOCK" in ids and "FL-KERN-BLOCK" in ids
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    (hit,) = run["results"]
    assert hit["ruleId"] == "FL-DET-CLOCK" and hit["level"] == "error"
    loc = hit["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == \
        "fluidframework_tpu/loader/bad.py"
    assert loc["region"]["startLine"] >= 1
    assert "suppressions" not in hit


def test_cli_sarif_maps_reviewed_suppressions(tmp_path, capsys):
    """A baselined finding still appears in the SARIF output, carrying
    an ``external`` suppression whose justification is the reviewed
    reason — CI diff annotation sees WHAT was reviewed away and why."""
    import json

    from tools.fluidlint.cli import main

    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    bp = tmp_path / "lint_baseline.json"
    assert main(["--root", str(tmp_path),
                 "--write-baseline", str(bp)]) == 0
    doc = json.loads(bp.read_text())
    for e in doc["suppressions"]:
        e["reason"] = "reviewed: synthetic fixture"
    bp.write_text(json.dumps(doc))
    capsys.readouterr()
    sarif = tmp_path / "out.sarif"
    assert main(["--root", str(tmp_path), "--baseline", str(bp),
                 "--sarif", str(sarif)]) == 0
    capsys.readouterr()
    run = json.loads(sarif.read_text())["runs"][0]
    (hit,) = run["results"]
    assert hit["ruleId"] == "FL-DET-CLOCK"
    (sup,) = hit["suppressions"]
    assert sup["kind"] == "external"
    assert sup["justification"] == "reviewed: synthetic fixture"


def test_cli_write_baseline_bootstraps_missing_file(tmp_path, capsys):
    """`--baseline X --write-baseline X` with no X yet is the bootstrap
    flow: it must write the skeleton, not die on 'baseline not found'
    (--write-baseline never reads the baseline)."""
    import json

    from tools.fluidlint.cli import main

    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    out = tmp_path / "lint_baseline.json"
    assert main(["--root", str(tmp_path), "--baseline", str(out),
                 "--write-baseline", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert len(doc["suppressions"]) == 1
    # a path that IS read (analysis / --check-baseline) still errors
    missing = str(tmp_path / "nope.json")
    assert main(["--root", str(tmp_path), "--baseline", missing]) == 2
    assert main(["--root", str(tmp_path), "--baseline", missing,
                 "--check-baseline"]) == 2
    capsys.readouterr()
