"""Single-serialization broadcaster (ISSUE 7): the counter-pinned
serialize-once contract, laggard demotion without collateral damage,
targeted-signal filtering on shared bytes, and failover re-attach."""

import json

import pytest

from fluidframework_tpu.protocol.messages import MessageType, RawOperation
from fluidframework_tpu.protocol.wire import LEN
from fluidframework_tpu.service.broadcaster import Broadcaster
from fluidframework_tpu.service.orderer import LocalOrderingService


def _op(client, client_seq, ref_seq=0, contents=None):
    return RawOperation(client_id=client, client_seq=client_seq,
                        ref_seq=ref_seq, type=MessageType.OP,
                        contents=contents or {})


class RecorderSink:
    """Accepts up to ``capacity`` frames, then reports saturation."""

    def __init__(self, capacity=10 ** 9):
        self.capacity = capacity
        self.frames = []
        self.signals = []
        self.demotions = []
        self.fences = []

    def write_frame(self, data):
        if len(self.frames) >= self.capacity:
            return False
        self.frames.append(data)
        return True

    def write_signal(self, data, signal):
        target = signal.get("targetClientId")
        if target is not None and target != getattr(self, "client_id", None):
            return True  # filtered — NOT saturation
        if len(self.frames) >= self.capacity:
            return False
        self.signals.append((data, signal))
        return True

    def on_demoted(self, doc_id, head_seq):
        self.demotions.append((doc_id, head_seq))

    def on_fence(self, doc_id, epoch, head_seq):
        self.fences.append((doc_id, epoch, head_seq))


def _decode(frame_bytes_):
    (length,) = LEN.unpack(frame_bytes_[:LEN.size])
    assert length == len(frame_bytes_) - LEN.size
    return json.loads(frame_bytes_[LEN.size:])


def _seeded_doc(n_sinks, broadcaster=None, capacity=10 ** 9):
    service = LocalOrderingService()
    service.create_document("doc")
    endpoint = service.endpoint("doc")
    endpoint.connect("c")
    bc = broadcaster or Broadcaster()
    sinks = [RecorderSink(capacity) for _ in range(n_sinks)]
    for sink in sinks:
        bc.attach("doc", endpoint, sink)
    return service, endpoint, bc, sinks


def test_serialize_once_counter_pin():
    """M clients x K ops -> exactly K encodes, and every sink receives
    the IDENTICAL bytes object (shared, not re-serialized)."""
    M, K = 7, 23
    _service, endpoint, bc, sinks = _seeded_doc(M)
    ref = endpoint.head_seq
    for i in range(K):
        ref = endpoint.submit(_op("c", i + 1, ref_seq=ref)).seq
    assert bc.stats()["encodes"] == K
    assert bc.stats()["writes"] == M * K
    for sink in sinks:
        assert len(sink.frames) == K
    for i in range(K):
        first = sinks[0].frames[i]
        for sink in sinks[1:]:
            assert sink.frames[i] is first  # same object, zero re-encode
    # the frames decode to the wire op events, in sequence order
    seqs = [_decode(f)["msg"]["sequenceNumber"] for f in sinks[0].frames]
    assert seqs == sorted(seqs)


def test_laggard_demoted_without_stalling_others():
    service, endpoint, bc, sinks = _seeded_doc(3)
    laggard = sinks[1]
    laggard.capacity = 4
    ref = endpoint.head_seq
    for i in range(10):
        ref = endpoint.submit(_op("c", i + 1, ref_seq=ref)).seq
    # laggard took its 4 frames, was demoted ONCE, got no more
    assert len(laggard.frames) == 4
    assert len(laggard.demotions) == 1
    doc, head = laggard.demotions[0]
    assert doc == "doc" and head > 0
    assert bc.stats()["demotions"] == 1
    # the healthy sinks saw every op, undisturbed
    for sink in (sinks[0], sinks[2]):
        assert len(sink.frames) == 10
        assert not sink.demotions
    assert bc.subscriber_count("doc") == 2
    # ...and the demoted client can re-subscribe (catch-up-from-oplog
    # happens in its DeltaManager; here we just verify re-attach works)
    laggard.capacity = 10 ** 9
    bc.attach("doc", endpoint, laggard)
    endpoint.submit(_op("c", 11, ref_seq=ref))
    assert len(laggard.frames) == 5


def test_signal_fanout_encodes_once_and_filters_targets():
    _service, endpoint, bc, sinks = _seeded_doc(3)
    for i, sink in enumerate(sinks):
        sink.client_id = f"client{i}"
    endpoint.submit_signal("client0", {"hello": 1})  # broadcast signal
    endpoint.submit_signal("client0", {"psst": 2},
                           target_client_id="client2")
    assert bc.stats()["signal_encodes"] == 2
    assert [s["content"] for _b, s in sinks[0].signals] == [{"hello": 1}]
    assert [s["content"] for _b, s in sinks[1].signals] == [{"hello": 1}]
    assert [s["content"] for _b, s in sinks[2].signals] == [{"hello": 1},
                                                           {"psst": 2}]
    # shared bytes for the broadcast signal
    assert sinks[0].signals[0][0] is sinks[2].signals[0][0]
    # target filtering is NOT demotion
    assert bc.stats()["demotions"] == 0


def test_empty_channel_unwires_from_the_sequencer():
    _service, endpoint, bc, sinks = _seeded_doc(2)
    for sink in sinks:
        bc.detach("doc", sink)
    assert bc.subscriber_count("doc") == 0
    ref = endpoint.head_seq
    endpoint.submit(_op("c", 1, ref_seq=ref))
    assert bc.stats()["encodes"] == 0  # no channel left to encode for
    for sink in sinks:
        assert sink.frames == []


def test_detach_all_removes_a_sink_everywhere():
    service = LocalOrderingService()
    bc = Broadcaster()
    sink = RecorderSink()
    endpoints = {}
    for doc in ("a", "b"):
        service.create_document(doc)
        endpoints[doc] = service.endpoint(doc)
        endpoints[doc].connect("c")
        bc.attach(doc, endpoints[doc], sink)
    assert bc.stats()["channels"] == 2
    bc.detach_all(sink)
    assert bc.stats()["channels"] == 0
    endpoints["a"].submit(_op("c", 1, ref_seq=endpoints["a"].head_seq))
    assert sink.frames == []


def test_refence_moves_channel_to_recovered_endpoint():
    """Shard failover: the channel re-attaches to the new owner's
    endpoint, sinks get on_fence with the new epoch, and subsequent ops
    (stamped by the recovered orderer) keep flowing."""
    service = LocalOrderingService()
    service.create_document("doc")
    old_endpoint = service.endpoint("doc")
    old_endpoint.connect("c")
    bc = Broadcaster()
    sink = RecorderSink()
    bc.attach("doc", old_endpoint, sink)
    ref = old_endpoint.submit(_op("c", 1, ref_seq=0)).seq
    # simulate the failover: fence the old orderer, recover a fresh one
    # from the shared log (a second service instance over the same log)
    with service.state_lock:
        service._orderers["doc"].fence()
    recovered = LocalOrderingService(oplog=service.oplog,
                                     storage=service.storage)
    new_endpoint = recovered.endpoint("doc")
    notified = bc.refence("doc", new_endpoint, "epoch-2")
    assert notified == 1
    assert sink.fences == [("doc", "epoch-2", ref)]
    msg = new_endpoint.submit(_op("c", 2, ref_seq=ref))
    assert msg.seq == ref + 1
    assert len(sink.frames) == 2  # pre-fence op + post-fence op
    assert bc.stats()["fences"] == 1


def test_probe_latencies_are_deterministic():
    """The VirtualClock broadcast probe yields the same latency samples
    on every run of the same spec (replay determinism of the harness)."""
    from fluidframework_tpu.testing.load import (ShardedLoadSpec,
                                                 run_sharded_load)

    spec = ShardedLoadSpec(seed=5, shards=4, docs=4, clients_per_doc=2,
                           steps=60, probe_sinks=2)
    a = run_sharded_load(spec)
    b = run_sharded_load(spec)
    assert a.broadcast_latencies == b.broadcast_latencies
    assert a.broadcast_encodes == b.broadcast_encodes > 0
    assert a.per_doc_digest == b.per_doc_digest
