"""IntervalCollection: sliding anchors, conflicts, summaries (config #3)."""

import pytest

from fluidframework_tpu.dds import SharedString
from fluidframework_tpu.testing import MockContainerRuntimeFactory
from fluidframework_tpu.testing.fuzz import StringFuzzSpec, run_fuzz


def make_pair():
    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(SharedString("s"))
    b = factory.create_client("B").attach(SharedString("s"))
    return factory, a, b


def test_interval_tracks_edits():
    factory, a, b = make_pair()
    a.insert_text(0, "hello world")
    factory.process_all_messages()
    iv = a.add_interval(6, 11)  # "world"
    factory.process_all_messages()
    b.insert_text(0, ">> ")  # shifts everything right
    factory.process_all_messages()
    assert a.get_interval_collection().endpoints(iv) == (9, 14)
    assert b.get_interval_collection().endpoints(iv) == (9, 14)
    assert a.summarize().digest() == b.summarize().digest()


def test_interval_slides_off_removed_range():
    factory, a, b = make_pair()
    a.insert_text(0, "abcdefgh")
    factory.process_all_messages()
    iv = a.add_interval(2, 5)
    factory.process_all_messages()
    b.remove_range(1, 6)  # removes both anchors' segments
    factory.process_all_messages()
    assert (
        a.get_interval_collection().endpoints(iv)
        == b.get_interval_collection().endpoints(iv)
    )
    assert a.summarize().digest() == b.summarize().digest()


def test_interval_resolution_uses_op_view():
    """A remote add created against a pre-removal view must resolve the same
    as on the author (who resolved early and slid on the removal)."""
    factory, a, b = make_pair()
    a.insert_text(0, "0123456789")
    factory.process_all_messages()
    iv = b.add_interval(4, 7)    # created against "0123456789"
    a.remove_range(2, 8)         # sequenced first
    factory.process_all_messages()
    assert (
        a.get_interval_collection().endpoints(iv)
        == b.get_interval_collection().endpoints(iv)
    )
    assert a.summarize().digest() == b.summarize().digest()


def test_concurrent_change_last_writer_wins_and_pending_masks():
    factory, a, b = make_pair()
    a.insert_text(0, "some interval text")
    factory.process_all_messages()
    iv = a.add_interval(0, 4, props={"color": "red"})
    factory.process_all_messages()
    b.change_interval(iv, start=5, end=13, props={"color": "blue"})
    a.change_interval(iv, start=0, end=8)  # sequenced after b's → wins
    factory.process_all_messages()
    assert a.get_interval_collection().endpoints(iv) == (0, 8)
    assert a.summarize().digest() == b.summarize().digest()
    # Props merged per-key LWW: color from b (a's change had no props).
    assert a.get_interval_collection().get(iv).props == {"color": "blue"}


def test_delete_beats_concurrent_change():
    factory, a, b = make_pair()
    a.insert_text(0, "abcdef")
    factory.process_all_messages()
    iv = a.add_interval(0, 3)
    factory.process_all_messages()
    a.delete_interval(iv)
    b.change_interval(iv, start=1, end=2)  # sequenced after the delete
    factory.process_all_messages()
    assert a.get_interval_collection().get(iv) is None
    assert b.get_interval_collection().get(iv) is None
    assert a.summarize().digest() == b.summarize().digest()


def test_interval_summary_roundtrip():
    factory, a, b = make_pair()
    a.insert_text(0, "persistent text")
    a.add_interval(0, 4, props={"k": 1}, label="comments")
    a.add_interval(5, 9, label="default")
    factory.process_all_messages()
    summary = a.summarize()
    fresh = SharedString("s")
    fresh.load(summary)
    assert fresh.summarize().digest() == summary.digest()
    assert len(fresh.get_interval_collection("comments")) == 1


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_string_with_intervals(seed):
    run_fuzz(
        StringFuzzSpec(intervals=True), seed=700 + seed, n_clients=3, rounds=30
    )


def test_interval_tail_over_base_ob_stamps_device_parity():
    """The interval fold's stamp-author involvement clause (fuzz seed
    1500041's rule) on its production shape: a WARM doc whose base
    records carry obliterate stamps, with an interval-op tail (no tail
    obliterates — that mix routes to the oracle pre-pack).  The device
    interval replay must resolve lagged positions with stamped
    tombstones hidden from the stamp author's views, byte-identical to
    the oracle."""
    import json as _json

    from fluidframework_tpu.ops.mergetree_kernel import (
        MergeTreeDocInput,
        replay_mergetree_batch,
    )
    from fluidframework_tpu.testing.fuzz import StringFuzzSpec, run_fuzz
    from fluidframework_tpu.testing.mocks import channel_log

    covered = 0
    for seed in range(840, 852):
        spec = StringFuzzSpec(obliterate=True)
        replicas, factory = run_fuzz(spec, seed=seed, n_clients=3,
                                     rounds=10, sync_every=3)
        base_summary = replicas[0].summarize()
        base_records = _json.loads(base_summary.blob_bytes("body"))
        if not any(r.get("ob") for r in base_records):
            continue  # no live stamps survived into this base
        base_seq = factory.sequencer.seq
        # Interval + text tail (obliterate-free) on the live session.
        import random as _random

        rng = _random.Random(seed)
        ids = []
        for step in range(25):
            c = rng.choice(replicas)
            L = len(c.text)
            k = rng.random()
            if k < 0.4 or L < 4:
                c.insert_text(rng.randint(0, L), rng.choice(["ab ", "z"]))
            elif k < 0.7 or not ids:
                a0 = rng.randint(0, L - 2)
                ids.append(c.add_interval(
                    a0, min(L - 1, a0 + rng.randint(1, 5)), {"s": str(step)}))
            else:
                c.change_interval(rng.choice(ids),
                                  start=rng.randint(0, L - 1))
            if step % 4 == 0:
                factory.process_some_messages(rng.randint(1, 3))
        factory.process_all_messages()
        full = channel_log(factory, "fuzz")
        doc = MergeTreeDocInput(
            doc_id=f"obiv{seed}",
            ops=[m for m in full if m.seq > base_seq],
            base_records=base_records,
            base_seq=base_seq,
            final_seq=factory.sequencer.seq,
            final_msn=factory.sequencer.min_seq,
        )
        stats: dict = {}
        [dev] = replay_mergetree_batch([doc], stats=stats)
        assert stats.get("fallback_docs", 0) == 0, (
            f"seed {seed}: expected the device path"
        )
        assert dev.digest() == replicas[0].summarize().digest(), (
            f"seed {seed}: warm ob-stamp + interval tail != oracle"
        )
        covered += 1
    assert covered >= 3, f"only {covered} seeds produced stamped bases"
