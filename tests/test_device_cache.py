"""Device-resident pack buffers (ISSUE 13): tier 2.5 of the catch-up
cache.  Packed chunk arrays stay resident in device memory keyed by the
chunk's token tuple; an exact warm hit dispatches with ZERO h2d pack
bytes, a grown tail uploads only its suffix rows through a donated
in-place splice, and every mismatch (bucket growth / repack, narrow↔wide
encoding flips, unknown pack lineage) falls back to the full upload.

Pinned here: golden + fuzz byte identity (resident-on == resident-off ==
the one-batch replay) across growth rounds, the donated splice's unit
parity against a numpy reference, the donation really happening (old
buffers dead), deterministic ``h2d_bytes`` gates (exact warm hit ≤
digest-plane bytes; suffix warm catch-up ≥5× less than full upload),
LRU/byte-bound eviction + epoch invalidation, and the mesh-sharded fold
serving the identical tier stack on a forced multi-device CPU mesh —
the mesh-parity acceptance criterion."""

import random

import numpy as np
import pytest

import bench
from fluidframework_tpu.ops.device_cache import DevicePackCache, _splice_ops
from fluidframework_tpu.ops.mergetree_kernel import (
    MTOps,
    MergeTreeDocInput,
    replay_mergetree_batch,
)
from fluidframework_tpu.ops.pipeline import (
    PackCache,
    pipelined_mergetree_replay,
)
from fluidframework_tpu.service.catchup_cache import DeltaExportCache


def _streams(n_docs, n_ops=128):
    return [bench.doc_ops(bench.synth_doc(i, n_ops)) for i in range(n_docs)]


def _window(streams, i, n_ops, epoch="ep"):
    msgs = streams[i][:n_ops]
    return MergeTreeDocInput(
        doc_id=f"d{i}", ops=msgs, final_seq=msgs[-1].seq, final_msn=0,
        cache_token=(epoch, f"d{i}", 0, ""),
    )


def _corpus(streams, grown=(), lo=120, hi=128, epoch="ep"):
    # 120 → 128 ops stays inside the T=128 / S=256 fine buckets, so
    # grown windows ride the tier-2 suffix path and the tier-2.5 splice;
    # the bucket-crossing repack case is exercised separately.
    return [
        _window(streams, i, hi if i in grown else lo, epoch)
        for i in range(len(streams))
    ]


def _run(docs, dev, pack, delta=None, **kw):
    stage: dict = {}
    stats: dict = {}
    out = pipelined_mergetree_replay(
        docs, chunk_docs=kw.pop("chunk_docs", 8), device_cache=dev,
        pack_cache=pack, delta_cache=delta, stage=stage, stats=stats, **kw)
    return [s.digest() for s in out], stage, stats


# --- golden byte identity ----------------------------------------------------


def test_resident_golden_byte_identity():
    """Cold fill, exact re-run, grown-tail splice: resident-on results
    are byte-identical to the one-batch replay at every step, and the
    resident counters report the serve/splice split."""
    streams = _streams(12)
    dev, pack = DevicePackCache(), PackCache()
    cold = _corpus(streams)
    got, stage_cold, _ = _run(cold, dev, pack)
    assert got == [s.digest() for s in replay_mergetree_batch(cold)]
    assert stage_cold["h2d_bytes"] > 0 and "upload" in stage_cold

    again, stage_exact, _ = _run(cold, dev, pack)
    assert again == got
    assert stage_exact["h2d_bytes"] == 0, (
        "exact warm hit must upload ZERO pack bytes")
    assert dev.stats()["served"] == 2  # both chunks resident

    grown = _corpus(streams, grown={0, 5})
    got3, stage_sfx, _ = _run(grown, dev, pack)
    assert got3 == [s.digest() for s in replay_mergetree_batch(grown)], (
        "donated suffix splice changed bytes"
    )
    st = dev.stats()
    assert st["spliced"] >= 1 and st["bytes_saved"] > 0
    assert 0 < stage_sfx["h2d_bytes"] < stage_cold["h2d_bytes"]


def test_resident_off_is_the_same_bytes():
    """device_cache=None keeps the existing full-upload pipeline exactly
    — and counts the full host arrays as h2d_bytes."""
    streams = _streams(8)
    docs = _corpus(streams)
    on, _, _ = _run(docs, DevicePackCache(), PackCache())
    off, stage, _ = _run(docs, None, PackCache())
    assert on == off
    assert stage["h2d_bytes"] > 0
    # Round 14: the stage schema is seeded identically for every
    # configuration — without the tier the key exists but no explicit
    # transfer leg ever runs (the upload rides the dispatch jit).
    assert stage["upload"] == 0.0


# --- the perf gates: bytes, not seconds --------------------------------------


def test_exact_warm_hit_uploads_at_most_digest_plane_bytes():
    """THE acceptance gate, upload side: a warm catch-up over unchanged
    documents uploads ≤ digest-plane bytes of pack data (here: zero —
    ops, state and doc_base are all resident) while the download side
    moves only the [D, 2] digest plane."""
    streams = _streams(16)
    dev, pack, delta = DevicePackCache(), PackCache(), DeltaExportCache()
    docs = _corpus(streams)
    _run(docs, dev, pack, delta)
    got, stage_warm, stats = _run(docs, dev, pack, delta)
    assert got == [s.digest() for s in replay_mergetree_batch(docs)]
    digest_plane_bytes = 8 * len(docs)
    assert stage_warm["h2d_bytes"] <= digest_plane_bytes, stage_warm
    assert stage_warm["d2h_bytes"] == digest_plane_bytes
    assert stats.get("delta_docs", 0) == len(docs)


def test_suffix_warm_catchup_5x_fewer_h2d_bytes():
    """Grown-tail warm catch-up (1/16 of documents grew) uploads ≥5×
    fewer h2d bytes than the full-upload reference over the same corpus
    — a deterministic byte-counter gate, not wall-clock."""
    streams = _streams(32)
    dev, pack = DevicePackCache(), PackCache()
    cold = _corpus(streams)
    _run(cold, dev, pack, chunk_docs=16)
    grown_idx = set(range(0, 32, 16))
    grown = _corpus(streams, grown=grown_idx)
    got_res, stage_res, _ = _run(grown, dev, pack, chunk_docs=16)
    got_full, stage_full, _ = _run(grown, None, PackCache(),
                                   chunk_docs=16)
    assert got_res == got_full, "resident and full runs disagree"
    assert stage_res["h2d_bytes"] * 5 <= stage_full["h2d_bytes"], (
        f"resident uploaded {stage_res['h2d_bytes']} B vs full "
        f"{stage_full['h2d_bytes']} B — less than the 5x floor"
    )
    # One grown doc per 16-doc chunk: both chunks splice.
    assert dev.stats()["spliced"] == 2


# --- the donated splice ------------------------------------------------------


def test_splice_unit_matches_numpy_reference():
    """``_splice_ops`` == the obvious per-doc row-write loop, for ragged
    per-doc suffix lengths including zero."""
    rng = np.random.default_rng(7)
    D, T, L, K = 5, 24, 8, 2

    def ops_of(arrs):
        return MTOps(**arrs)

    base = {f: rng.integers(0, 100, (D, T), np.int32)
            for f in MTOps._fields if f != "pvals"}
    base["pvals"] = rng.integers(0, 100, (D, T, K), np.int32)
    rows = {f: rng.integers(0, 100, (D, L), np.int32)
            for f in MTOps._fields if f != "pvals"}
    rows["pvals"] = rng.integers(0, 100, (D, L, K), np.int32)
    start = np.asarray([0, 3, 16, 20, 7], np.int32)
    count = np.asarray([2, 8, 8, 4, 0], np.int32)

    import jax

    spliced = _splice_ops(
        ops_of({f: jax.device_put(v) for f, v in base.items()}),
        ops_of({f: jax.device_put(v) for f, v in rows.items()}),
        jax.device_put(start), jax.device_put(count))
    for f in MTOps._fields:
        expect = base[f].copy()
        for d in range(D):
            for j in range(int(count[d])):
                expect[d, start[d] + j] = rows[f][d, j]
        assert np.array_equal(np.asarray(getattr(spliced, f)), expect), f


def test_donation_really_happens_old_buffers_dead():
    """The splice donates the resident buffers: after a suffix acquire
    the PREVIOUS device arrays are deleted (no 2× HBM spike) — reading a
    stale reference raises instead of aliasing garbage."""
    streams = _streams(6)
    dev, pack = DevicePackCache(), PackCache()
    _run(_corpus(streams), dev, pack, chunk_docs=6)
    [entry] = dev._entries.values()
    old_kind = entry.ops.kind
    got, _, _ = _run(_corpus(streams, grown={1}), dev, pack, chunk_docs=6)
    assert dev.stats()["spliced"] == 1
    assert entry.ops.kind is not old_kind
    with pytest.raises(RuntimeError):
        np.asarray(old_kind)


# --- fallback routes: the tier can lose a win, never corrupt -----------------


def test_bucket_crossing_repack_falls_back_to_full_upload():
    """Growth that crosses the T bucket repacks (tier-2 bails, shapes
    move) — the resident tier sees a signature mismatch, full-uploads,
    and the bytes stay identical."""
    streams = _streams(6, n_ops=48)
    dev, pack = DevicePackCache(), PackCache()
    small = [_window(streams, i, 20) for i in range(6)]
    _run(small, dev, pack, chunk_docs=6)
    grown = [_window(streams, i, 40) for i in range(6)]  # T 24 -> 48
    got, _, _ = _run(grown, dev, pack, chunk_docs=6)
    assert got == [s.digest() for s in replay_mergetree_batch(grown)]
    st = dev.stats()
    assert st["spliced"] == 0 and st["misses"] == 2
    # ...and the replaced entry serves exactly afterwards.
    _, stage, _ = _run(grown, dev, pack, chunk_docs=6)
    assert stage["h2d_bytes"] == 0


def test_narrow_wide_encoding_flip_migrates_in_graph(monkeypatch):
    """A narrow→wide upload-encoding flip (forced here via
    FF_UPLOAD_NARROW; at full scale suffix text at the shared arena
    tail does it by blowing the int16 offset bound) must NOT cost the
    full re-upload: the resident int16 buffers widen IN-GRAPH (donated,
    zero link bytes) and the suffix still splices — bytes identical,
    and the upload stays suffix-sized."""
    streams = _streams(6)
    dev, pack = DevicePackCache(), PackCache()
    cold = _corpus(streams)
    _, stage_cold, _ = _run(cold, dev, pack, chunk_docs=6)
    monkeypatch.setenv("FF_UPLOAD_NARROW", "0")
    grown = _corpus(streams, grown={2})
    got, stage, _ = _run(grown, dev, pack, chunk_docs=6)
    assert got == [s.digest() for s in replay_mergetree_batch(grown)]
    st = dev.stats()
    assert st["spliced"] == 1 and st["misses"] == 1, st
    # Wide suffix rows cost more per row than narrow ones, but still a
    # fraction of the full (now-wide) planes.
    assert 0 < stage["h2d_bytes"] < stage_cold["h2d_bytes"]
    # ...and the migrated entry's byte accounting tracks the wide size.
    assert dev.stats()["bytes"] > 0


def test_wide_to_narrow_flip_full_uploads(monkeypatch):
    """The opposite direction (resident wide, chunk narrow again) has
    no in-graph migration — full upload, never a corrupted splice."""
    streams = _streams(6)
    dev, pack = DevicePackCache(), PackCache()
    monkeypatch.setenv("FF_UPLOAD_NARROW", "0")
    _run(_corpus(streams), dev, pack, chunk_docs=6)
    monkeypatch.setenv("FF_UPLOAD_NARROW", "1")
    grown = _corpus(streams, grown={2})
    got, _, _ = _run(grown, dev, pack, chunk_docs=6)
    assert got == [s.digest() for s in replay_mergetree_batch(grown)]
    st = dev.stats()
    assert st["spliced"] == 0 and st["misses"] == 2, st


def test_suffix_without_pack_lineage_full_uploads():
    """Without tier 2 there is no lineage proof that the host arrays
    extend the resident ones (a fresh repack's arena layout may differ)
    — the suffix route must NOT splice; exact reuse still works (a
    deterministic re-pack of identical windows is byte-identical)."""
    streams = _streams(6)
    dev = DevicePackCache()
    docs = _corpus(streams)
    _run(docs, dev, None, chunk_docs=6)
    _, stage_exact, _ = _run(docs, dev, None, chunk_docs=6)
    assert stage_exact["h2d_bytes"] == 0
    assert dev.stats()["served"] == 1
    grown = _corpus(streams, grown={0})
    got, stage, _ = _run(grown, dev, None, chunk_docs=6)
    assert got == [s.digest() for s in replay_mergetree_batch(grown)]
    st = dev.stats()
    assert st["spliced"] == 0 and st["misses"] == 2, st


def test_bypasses_binary_and_tokenless_chunks():
    dev = DevicePackCache()
    binary = [bench.synth_doc(i, 16) for i in range(4)]  # no tokens
    got, stage, _ = _run(binary, dev, None, chunk_docs=4)
    assert got == [s.digest() for s in replay_mergetree_batch(binary)]
    assert dev.stats()["bypass"] == 1 and len(dev) == 0
    assert stage["h2d_bytes"] > 0  # the full upload is still counted


# --- cache unit behavior -----------------------------------------------------


def test_byte_bound_and_lru_eviction():
    streams = _streams(8, n_ops=32)
    probe, pack = DevicePackCache(), PackCache()
    docs = _corpus(streams, lo=24, hi=32)
    _run(docs, probe, pack, chunk_docs=2)  # 4 chunks
    assert len(probe) == 4
    per_entry = max(e.nbytes for e in probe._entries.values())
    dev = DevicePackCache(max_bytes=2 * per_entry)
    pack2 = PackCache()
    _run(docs, dev, pack2, chunk_docs=2)
    st = dev.stats()
    assert len(dev) <= 2 and st["evictions"] >= 2
    assert st["bytes"] <= dev.max_bytes
    # An entry larger than the whole budget is never admitted.
    tiny = DevicePackCache(max_bytes=16)
    _run(docs[:2], tiny, PackCache(), chunk_docs=2)
    assert len(tiny) == 0 and tiny.stats()["evictions"] >= 1


def test_epoch_bump_invalidates_resident_entries():
    streams = _streams(4)
    dev, pack = DevicePackCache(), PackCache()
    _run(_corpus(streams, epoch="e1"), dev, pack, chunk_docs=4)
    assert len(dev) == 1
    assert dev.invalidate_epoch("e2") == 1
    assert len(dev) == 0
    assert dev.stats()["invalidations"] == 1
    assert dev.invalidate_epoch("e2") == 0  # O(1) unchanged-epoch path
    docs2 = _corpus(streams, epoch="e2")
    got, _, _ = _run(docs2, dev, pack, chunk_docs=4)
    assert got == [s.digest() for s in replay_mergetree_batch(docs2)]


def test_service_device_gate_off(monkeypatch):
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService

    monkeypatch.setenv("FLUID_TPU_CATCHUP_DEVICERESIDENT", "off")
    svc = CatchupService(LocalOrderingService(), mesh=None)
    assert svc.device_cache is None


# --- fuzz: resident-on == resident-off across random growth ------------------


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_resident_on_matches_off(seed):
    """Random growth rounds (bucket-crossing repacks and
    interval/annotate fuzz docs included): every round's resident-tier
    results equal a fresh full replay byte-for-byte."""
    from fluidframework_tpu.testing.fuzz import StringFuzzSpec, run_fuzz
    from fluidframework_tpu.testing.mocks import channel_log

    rng = random.Random(9100 + seed)
    streams = _streams(8, n_ops=48)
    fuzz_docs = []
    for i, spec in enumerate((StringFuzzSpec(annotate=True,
                                             intervals=True),
                              StringFuzzSpec(obliterate=True))):
        _r, f = run_fuzz(spec, seed=9200 + 10 * seed + i, n_clients=3,
                         rounds=6, sync_every=2)
        fuzz_docs.append(MergeTreeDocInput(
            doc_id=f"fz{i}", ops=channel_log(f, "fuzz"),
            final_seq=f.sequencer.seq, final_msn=f.sequencer.min_seq,
            cache_token=("ep", f"fz{i}", 0, "")))
    dev, pack = DevicePackCache(), PackCache()
    delta = DeltaExportCache()
    windows = [12] * len(streams)
    for _round in range(4):
        docs = [_window(streams, i, windows[i])
                for i in range(len(streams))] + fuzz_docs
        expect = [s.digest() for s in replay_mergetree_batch(docs)]
        got, _, _ = _run(docs, dev, pack, delta, chunk_docs=6)
        assert got == expect, f"seed {seed}: resident-on != full replay"
        for i in range(len(streams)):  # grow a random subset
            if rng.random() < 0.4:
                windows[i] = min(len(streams[i]),
                                 windows[i] + rng.randint(1, 14))
    st = dev.stats()
    assert st["served"] + st["spliced"] > 0, (
        "fuzz never exercised the resident tier")


# --- mesh parity: the acceptance criterion -----------------------------------


def test_mesh_fold_serves_the_full_tier_stack():
    """The mesh-sharded fold on the forced 8-device CPU mesh serves
    tier-0 / tier-2 / tier-2.5 with the full stage-counter schema:
    byte-identical to the one-batch replay, zero h2d pack bytes on the
    exact warm pass, digest-plane-only d2h, and a suffix splice on the
    grown pass — the mesh-parity debt paid."""
    from fluidframework_tpu.parallel.shard import (
        doc_mesh,
        replay_mergetree_sharded,
    )

    mesh = doc_mesh()
    streams = _streams(11)  # not a multiple of 8: exercises pad tokens
    pack, delta, dev = PackCache(), DeltaExportCache(), DevicePackCache()
    stage: dict = {}
    cold = _corpus(streams)
    out = replay_mergetree_sharded(cold, mesh=mesh, stage=stage,
                                   pack_cache=pack, delta_cache=delta,
                                   device_cache=dev)
    expect = [s.digest() for s in replay_mergetree_batch(cold)]
    assert [s.digest() for s in out] == expect
    assert {"pack", "upload", "dispatch", "device_wait", "download",
            "extract", "h2d_bytes", "d2h_bytes"} <= set(stage)
    h2d_cold = stage["h2d_bytes"]

    stage2: dict = {}
    stats2: dict = {}
    out2 = replay_mergetree_sharded(cold, mesh=mesh, stage=stage2,
                                    stats=stats2, pack_cache=pack,
                                    delta_cache=delta, device_cache=dev)
    assert [s.digest() for s in out2] == expect
    assert stage2["h2d_bytes"] == 0, "mesh exact hit must upload nothing"
    # Digest plane only — counted PADDED (11 docs pad to 16 on the
    # 8-device mesh; the pad rows really cross the link), while the
    # tier-0 handshake itself sees only the real prefix.
    assert stage2["d2h_bytes"] == 8 * 16
    assert stats2.get("delta_docs") == len(cold)

    grown = _corpus(streams, grown={0, 5})
    stage3: dict = {}
    out3 = replay_mergetree_sharded(grown, mesh=mesh, stage=stage3,
                                    stats={}, pack_cache=pack,
                                    delta_cache=delta, device_cache=dev)
    assert [s.digest() for s in out3] == \
        [s.digest() for s in replay_mergetree_batch(grown)]
    assert dev.stats()["spliced"] == 1
    assert stage3["h2d_bytes"] * 5 <= h2d_cold


def test_mesh_service_stage_schema_matches_single_device():
    """CatchupService on the mesh serves byte-identical results through
    the same four-tier stack, and its ``pipeline_stage`` schema is
    IDENTICAL to the single-device instance's (the ISSUE 13 satellite:
    no counter the mesh path drops)."""
    from fluidframework_tpu.parallel.shard import doc_mesh
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService

    service = LocalOrderingService()
    doc_ids = bench.build_catchup_corpus(service, 6, 14)
    plain = CatchupService(service, mesh=None, cache=None,
                           pack_cache=None, delta_cache=None,
                           device_cache=None)
    expect = plain.catch_up(doc_ids, upload=False)

    single = CatchupService(service, mesh=None, cache=None)
    mesh_svc = CatchupService(service, mesh=doc_mesh(), cache=None)
    assert single.catch_up(doc_ids, upload=False) == expect
    assert single.catch_up(doc_ids, upload=False) == expect
    assert mesh_svc.catch_up(doc_ids, upload=False) == expect
    assert mesh_svc.catch_up(doc_ids, upload=False) == expect
    assert sorted(mesh_svc.pipeline_stage) == \
        sorted(single.pipeline_stage), "mesh stage schema drifted"
    for svc in (single, mesh_svc):
        assert svc.device_cache.stats()["served"] >= 1
        assert svc.delta_cache.stats()["served"] >= 1
        assert svc._pack_cache.stats()["exact_hits"] >= 1
