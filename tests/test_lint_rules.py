"""fluidlint self-tests: one positive + one negative fixture per rule.

A rule regression (pattern stops matching, scope widens/narrows, a rename
breaks registration) fails here loudly instead of silently opening a hole
in the tier-1 gate.  Module rules run through ``analyze_source`` against
in-memory fixtures; the project rule (FL-WIRE-COMPLETE) runs through
``analyze`` against a synthetic repo tree; the baseline machinery gets its
own match/stale/invalid coverage.
"""

import json
import textwrap

import pytest

from tools.fluidlint import (Finding, analyze, analyze_source,
                             apply_baseline, load_baseline)

OPS = "fluidframework_tpu/ops/x.py"          # replay + kernel scope
LOADER = "fluidframework_tpu/loader/x.py"    # replay scope only
RUNTIME = "fluidframework_tpu/runtime/x.py"  # event scope only
TESTING = "fluidframework_tpu/testing/x.py"  # exempt everywhere


def findings_for(src, relpath, rule=None):
    out = analyze_source(textwrap.dedent(src), relpath)
    return [f for f in out if rule is None or f.rule == rule]


# -- one (positive, negative) pair per module rule ---------------------------

MODULE_RULE_FIXTURES = {
    "FL-DET-CLOCK": (
        """
        import time
        def hold():
            return time.time() + 5
        """,
        """
        import time
        def hold(clock=time.monotonic):
            return clock() + 5
        """,
        LOADER,
    ),
    "FL-DET-RANDOM": (
        """
        import random
        def jitter():
            return random.random()
        """,
        """
        import random
        def jitter(rng: random.Random):
            return rng.random()
        """,
        LOADER,
    ),
    "FL-DET-SETITER": (
        """
        def order(ids):
            seen = {i for i in ids}
            return [x for x in seen]
        """,
        """
        def order(ids):
            seen = {i for i in ids}
            return [x for x in sorted(seen)]
        """,
        LOADER,
    ),
    "FL-TRACE-HOSTSYNC": (
        """
        import jax
        @jax.jit
        def fold(x):
            return x + x.sum().item()
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def fold(x):
            return x + jnp.sum(x)
        """,
        OPS,
    ),
    "FL-TRACE-PYCOND": (
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def clamp(x):
            if jnp.sum(x) > 0:
                return x
            return -x
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def clamp(x):
            return jnp.where(jnp.sum(x) > 0, x, -x)
        """,
        OPS,
    ),
    "FL-TRACE-LOOPJNP": (
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def fold(xs, n):
            acc = xs[0]
            for i in range(n):
                acc = jnp.maximum(acc, xs[i])
            return acc
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def fold(xs):
            acc = xs[0]
            for i in range(4):  # bounded constant unroll is idiomatic
                acc = jnp.maximum(acc, xs[i])
            return acc
        """,
        OPS,
    ),
    "FL-TRACE-STATIC": (
        """
        import jax
        @jax.jit(static_argnames=("cfg",))
        def fold(x, cfg: dict):
            return x
        """,
        """
        import jax
        @jax.jit(static_argnames=("cfg",))
        def fold(x, cfg: tuple):
            return x
        """,
        OPS,
    ),
    "FL-EVENT-EMITITER": (
        """
        class Emitter:
            def emit(self, event):
                for fn in self._listeners[event]:
                    fn(event)
        """,
        """
        class Emitter:
            def emit(self, event):
                for fn in list(self._listeners[event]):
                    fn(event)
        """,
        RUNTIME,
    ),
}


@pytest.mark.parametrize("rule", sorted(MODULE_RULE_FIXTURES))
def test_positive_fixture_fires(rule):
    bad, _good, relpath = MODULE_RULE_FIXTURES[rule]
    hits = findings_for(bad, relpath, rule)
    assert hits, f"{rule}: positive fixture produced no finding"
    assert all(f.line > 0 and f.message for f in hits)


@pytest.mark.parametrize("rule", sorted(MODULE_RULE_FIXTURES))
def test_negative_fixture_is_clean(rule):
    _bad, good, relpath = MODULE_RULE_FIXTURES[rule]
    assert findings_for(good, relpath, rule) == [], (
        f"{rule}: negative fixture flagged")


@pytest.mark.parametrize("rule", sorted(MODULE_RULE_FIXTURES))
def test_testing_dir_is_exempt(rule):
    bad, _good, _relpath = MODULE_RULE_FIXTURES[rule]
    assert findings_for(bad, TESTING, rule) == []


def test_setiter_reports_each_site_once():
    # a loop inside a def is visible from the module walk AND its own
    # scope walk; the walker must stop at scope boundaries or every
    # function-body site double-reports
    src = """
    def order():
        ids = {1, 2, 3}
        for i in ids:
            pass
    """
    assert len(findings_for(src, LOADER, "FL-DET-SETITER")) == 1


def test_setiter_checks_class_bodies():
    # class bodies are their own lexical scope; a hash-order-dependent
    # class attribute must not slip past the gate
    src = """
    class Registry:
        IDS = {"b", "a"}
        ORDER = [x for x in IDS]
    """
    assert len(findings_for(src, LOADER, "FL-DET-SETITER")) == 1


def test_trace_rules_do_not_fire_outside_kernel_scope():
    bad, _good, _ = MODULE_RULE_FIXTURES["FL-TRACE-HOSTSYNC"]
    assert findings_for(bad, LOADER, "FL-TRACE-HOSTSYNC") == []


def test_untraced_function_not_flagged():
    # host syncs in plain host-side code are fine — scope is traced defs
    src = """
    import numpy as np
    def host_extract(arr):
        return np.asarray(arr).tolist()
    """
    assert findings_for(src, OPS, "FL-TRACE-HOSTSYNC") == []


def test_hostsync_messages_are_function_scoped():
    # suppression keys are (rule, path, message): naming the owning def
    # keeps one reviewed suppression from masking a future host sync in
    # a different function of the same file
    src = """
    import jax
    @jax.jit
    def fold_a(x):
        return x.item()
    @jax.jit
    def fold_b(x):
        return x.item()
    """
    msgs = {f.message for f in findings_for(src, OPS, "FL-TRACE-HOSTSYNC")}
    assert len(msgs) == 2
    assert any("fold_a()" in m for m in msgs)
    assert any("fold_b()" in m for m in msgs)


def test_scan_argument_is_traced():
    # functions passed by name to lax.scan count as traced
    src = """
    import jax
    from jax import lax
    def step(carry, x):
        return carry + x.item(), x
    def fold(xs):
        return lax.scan(step, 0, xs)
    """
    assert findings_for(src, OPS, "FL-TRACE-HOSTSYNC")


# -- project rule: FL-WIRE-COMPLETE ------------------------------------------


def _write_wire_tree(root, wire_body, test_body=None):
    proto = root / "fluidframework_tpu" / "protocol"
    proto.mkdir(parents=True)
    (proto / "messages.py").write_text(textwrap.dedent("""
        import dataclasses

        @dataclasses.dataclass
        class PingMessage:
            seq: int
    """))
    (proto / "wire.py").write_text(textwrap.dedent(wire_body))
    if test_body is not None:
        tdir = root / "tests"
        tdir.mkdir()
        (tdir / "test_wire_roundtrip.py").write_text(
            textwrap.dedent(test_body))


COMPLETE_WIRE = """
    def encode_ping_message(m): return {"seq": m.seq}
    def decode_ping_message(d): return d["seq"]
    MESSAGE_CODECS = {"PingMessage": (encode_ping_message,
                                      decode_ping_message)}
"""


def test_wire_complete_positive(tmp_path):
    _write_wire_tree(tmp_path, "MESSAGE_CODECS = {}\n", test_body="x = 1\n")
    msgs = {f.message for f in analyze(tmp_path)
            if f.rule == "FL-WIRE-COMPLETE"}
    assert any("encode_ping_message" in m for m in msgs), msgs
    assert any("decode_ping_message" in m for m in msgs), msgs
    assert any("MESSAGE_CODECS" in m for m in msgs), msgs
    assert any("round-trip coverage" in m for m in msgs), msgs


def test_wire_complete_negative(tmp_path):
    _write_wire_tree(tmp_path, COMPLETE_WIRE,
                     test_body="from x import PingMessage\n")
    assert [f for f in analyze(tmp_path)
            if f.rule == "FL-WIRE-COMPLETE"] == []


def test_project_rules_skipped_on_path_scoped_runs(tmp_path):
    # whole-repo contracts don't belong to a "files I touched" run (and
    # their suppressions would be filtered out of scope with them)
    _write_wire_tree(tmp_path, "MESSAGE_CODECS = {}\n", test_body="x = 1\n")
    scoped = analyze(tmp_path,
                     relpaths=["fluidframework_tpu/protocol/messages.py"])
    assert [f for f in scoped if f.rule == "FL-WIRE-COMPLETE"] == []


def test_wire_complete_missing_test_suite(tmp_path):
    _write_wire_tree(tmp_path, COMPLETE_WIRE, test_body=None)
    msgs = {f.message for f in analyze(tmp_path)
            if f.rule == "FL-WIRE-COMPLETE"}
    assert any("no tests/test_wire*.py" in m for m in msgs), msgs


# -- baseline machinery ------------------------------------------------------


def _finding(msg="m1"):
    return Finding("FL-DET-CLOCK", "error", "pkg/a.py", 10, msg)


def _entry(msg="m1", reason="reviewed: fixture"):
    return {"rule": "FL-DET-CLOCK", "path": "pkg/a.py",
            "message": msg, "reason": reason}


def test_baseline_suppresses_by_rule_path_message():
    report = apply_baseline([_finding()], [_entry()])
    assert report.clean
    assert len(report.suppressed) == 1


def test_baseline_is_line_independent():
    moved = Finding("FL-DET-CLOCK", "error", "pkg/a.py", 99, "m1")
    assert apply_baseline([moved], [_entry()]).clean


def test_stale_suppression_fails_gate():
    report = apply_baseline([], [_entry()])
    assert not report.clean
    assert report.stale == [_entry()]


def test_reasonless_suppression_fails_gate():
    report = apply_baseline([_finding()], [_entry(reason="  ")])
    assert not report.clean
    assert report.invalid


def test_unsuppressed_finding_fails_gate():
    report = apply_baseline([_finding("other")], [_entry()])
    assert not report.clean
    assert [f.message for f in report.unsuppressed] == ["other"]


def test_missing_baseline_path_is_a_usage_error(tmp_path):
    from tools.fluidlint.cli import main
    assert main(["--root", str(tmp_path),
                 "--baseline", "lint_baseline.json"]) == 2


def test_path_scoped_run_ignores_out_of_scope_suppressions(tmp_path):
    # linting one clean file must not go red because the baseline also
    # covers findings in files outside the analyzed subset
    from tools.fluidlint.cli import main
    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "clean.py").write_text("x = 1\n")
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({"version": 1, "suppressions": [
        {"rule": "FL-DET-CLOCK",
         "path": "fluidframework_tpu/loader/other.py",
         "message": "m", "reason": "reviewed"}]}))
    assert main(["--root", str(tmp_path), "--baseline", str(bp),
                 "fluidframework_tpu/loader/clean.py"]) == 0


def test_path_arguments_are_normalized_against_root(tmp_path, capsys):
    # a './'-spelled path must hit the same rule scopes as the canonical
    # repo-relative form, not silently match nothing and pass
    from tools.fluidlint.cli import main
    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    rc = main(["--root", str(tmp_path),
               "./fluidframework_tpu/loader/bad.py"])
    assert rc == 1
    assert "FL-DET-CLOCK" in capsys.readouterr().out
    assert main(["--root", str(tmp_path), "/etc/passwd"]) == 2


def test_path_scoped_run_ignores_project_rule_suppressions(tmp_path):
    # analyze() skips project rules on scoped runs, so their reviewed
    # suppressions must not surface as stale
    from tools.fluidlint.cli import main
    pkg = tmp_path / "fluidframework_tpu" / "protocol"
    pkg.mkdir(parents=True)
    (pkg / "wire.py").write_text("x = 1\n")
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({"version": 1, "suppressions": [
        {"rule": "FL-WIRE-COMPLETE",
         "path": "fluidframework_tpu/protocol/wire.py",
         "message": "m", "reason": "reviewed"}]}))
    assert main(["--root", str(tmp_path), "--baseline", str(bp),
                 "fluidframework_tpu/protocol/wire.py"]) == 0


def test_directory_path_argument_expands_to_py_files(tmp_path, capsys):
    from tools.fluidlint.cli import main
    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    assert main(["--root", str(tmp_path), "fluidframework_tpu"]) == 1
    assert "FL-DET-CLOCK" in capsys.readouterr().out


def test_duplicate_baseline_entries_are_invalid():
    report = apply_baseline([_finding()], [_entry(), _entry()])
    assert not report.clean
    assert any("duplicate" in m for m in report.invalid)
    assert report.stale == []


def test_invalid_entry_not_double_reported_as_stale():
    report = apply_baseline([], [{"rule": "FL-DET-CLOCK",
                                  "message": "m", "reason": "r"}])
    assert report.invalid
    assert report.stale == []


def test_load_baseline_rejects_non_object(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps(["not", "an", "object"]))
    with pytest.raises(ValueError):
        load_baseline(p)
