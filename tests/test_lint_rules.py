"""fluidlint self-tests: one positive + one negative fixture per rule.

A rule regression (pattern stops matching, scope widens/narrows, a rename
breaks registration) fails here loudly instead of silently opening a hole
in the tier-1 gate.  Module rules run through ``analyze_source`` against
in-memory fixtures; the project rule (FL-WIRE-COMPLETE) runs through
``analyze`` against a synthetic repo tree; the baseline machinery gets its
own match/stale/invalid coverage.
"""

import json
import textwrap

import pytest

from tools.fluidlint import (Finding, analyze, analyze_source,
                             apply_baseline, baseline_function_hygiene,
                             load_baseline)

OPS = "fluidframework_tpu/ops/x.py"          # replay + kernel scope
LOADER = "fluidframework_tpu/loader/x.py"    # replay scope only
RUNTIME = "fluidframework_tpu/runtime/x.py"  # event scope only
SERVICE = "fluidframework_tpu/service/x.py"  # replay + serving scope
TESTING = "fluidframework_tpu/testing/x.py"  # exempt everywhere


def findings_for(src, relpath, rule=None):
    out = analyze_source(textwrap.dedent(src), relpath)
    return [f for f in out if rule is None or f.rule == rule]


# -- one (positive, negative) pair per module rule ---------------------------

MODULE_RULE_FIXTURES = {
    "FL-DET-CLOCK": (
        """
        import time
        def hold():
            return time.time() + 5
        """,
        """
        import time
        def hold(clock=time.monotonic):
            return clock() + 5
        """,
        LOADER,
    ),
    "FL-DET-RANDOM": (
        """
        import random
        def jitter():
            return random.random()
        """,
        """
        import random
        def jitter(rng: random.Random):
            return rng.random()
        """,
        LOADER,
    ),
    "FL-DET-SETITER": (
        """
        def order(ids):
            seen = {i for i in ids}
            return [x for x in seen]
        """,
        """
        def order(ids):
            seen = {i for i in ids}
            return [x for x in sorted(seen)]
        """,
        LOADER,
    ),
    "FL-TRACE-HOSTSYNC": (
        """
        import jax
        @jax.jit
        def fold(x):
            return x + x.sum().item()
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def fold(x):
            return x + jnp.sum(x)
        """,
        OPS,
    ),
    "FL-TRACE-PYCOND": (
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def clamp(x):
            if jnp.sum(x) > 0:
                return x
            return -x
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def clamp(x):
            return jnp.where(jnp.sum(x) > 0, x, -x)
        """,
        OPS,
    ),
    "FL-TRACE-LOOPJNP": (
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def fold(xs, n):
            acc = xs[0]
            for i in range(n):
                acc = jnp.maximum(acc, xs[i])
            return acc
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def fold(xs):
            acc = xs[0]
            for i in range(4):  # bounded constant unroll is idiomatic
                acc = jnp.maximum(acc, xs[i])
            return acc
        """,
        OPS,
    ),
    "FL-TRACE-STATIC": (
        """
        import jax
        @jax.jit(static_argnames=("cfg",))
        def fold(x, cfg: dict):
            return x
        """,
        """
        import jax
        @jax.jit(static_argnames=("cfg",))
        def fold(x, cfg: tuple):
            return x
        """,
        OPS,
    ),
    "FL-TRACE-DONATE": (
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def extend(buf, rows):
            return buf + rows

        def caller(buf, rows):
            out = extend(buf, rows)
            return out, buf.sum()
        """,
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def extend(buf, rows):
            return buf + rows

        def caller(buf, rows):
            buf = extend(buf, rows)
            return buf, buf.sum()
        """,
        OPS,
    ),
    "FL-RACE-GUARD": (
        """
        import threading
        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries = {}  # guarded-by: _lock
            def size(self):
                return len(self._entries)
        """,
        """
        import threading
        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries = {}  # guarded-by: _lock
            def size(self):
                with self._lock:
                    return len(self._entries)
        """,
        SERVICE,
    ),
    "FL-RACE-BLOCKING": (
        """
        import threading
        class Client:
            def __init__(self):
                self._lock = threading.Lock()
            def ping(self):
                with self._lock:
                    return self.request("ping", {})
        """,
        """
        import threading
        class Client:
            def __init__(self):
                self._lock = threading.Lock()
            def ping(self):
                with self._lock:
                    pending = True
                return self.request("ping", {})
        """,
        SERVICE,
    ),
    "FL-RACE-ORDER": (
        """
        import threading
        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
            def one(self):
                with self._a:
                    with self._b:
                        pass
            def two(self):
                with self._b:
                    with self._a:
                        pass
        """,
        """
        import threading
        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
            def one(self):
                with self._a:
                    with self._b:
                        pass
            def two(self):
                with self._a:
                    with self._b:
                        pass
        """,
        SERVICE,
    ),
    "FL-RACE-MUTITER": (
        """
        import threading
        class Reg:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries = {}  # guarded-by: _lock
            def sweep(self):
                with self._lock:
                    for key in self._entries:
                        self._entries.pop(key)
        """,
        """
        import threading
        class Reg:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries = {}  # guarded-by: _lock
            def sweep(self):
                with self._lock:
                    for key in list(self._entries):
                        self._entries.pop(key)
        """,
        SERVICE,
    ),
    "FL-RACE-CHECKACT": (
        """
        import threading
        class Reg:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries = {}  # guarded-by: _lock
            def put(self, k, v):
                with self._lock:
                    seen = k in self._entries
                if not seen:
                    with self._lock:
                        self._entries[k] = v
        """,
        """
        import threading
        class Reg:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries = {}  # guarded-by: _lock
            def put(self, k, v):
                with self._lock:
                    if k not in self._entries:
                        self._entries[k] = v
        """,
        SERVICE,
    ),
    "FL-RACE-WAITFOREVER": (
        """
        import threading
        def run(flight):
            done = threading.Event()
            done.wait()
        """,
        """
        import threading
        def run(flight):
            done = threading.Event()
            if not done.wait(timeout=30.0):
                raise TimeoutError
        """,
        SERVICE,
    ),
    "FL-EVENT-EMITITER": (
        """
        class Emitter:
            def emit(self, event):
                for fn in self._listeners[event]:
                    fn(event)
        """,
        """
        class Emitter:
            def emit(self, event):
                for fn in list(self._listeners[event]):
                    fn(event)
        """,
        RUNTIME,
    ),
    "FL-LEAK-PAIR": (
        """
        class S:
            def work(self, key):
                status = self.cache.begin(key)
                tree = self.fold(key)
                self.cache.finish(key)
                return tree
        """,
        """
        class S:
            def work(self, key):
                status = self.cache.begin(key)
                try:
                    return self.fold(key)
                finally:
                    self.cache.abandon(key)
        """,
        SERVICE,
    ),
    "FL-LEAK-ESCAPE": (
        """
        import socket
        def probe(host):
            s = socket.create_connection((host, 1))
            data = s.recv(10)
            s.close()
            return data
        """,
        """
        import socket
        def probe(host):
            with socket.create_connection((host, 1)) as s:
                return s.recv(10)
        """,
        SERVICE,
    ),
    "FL-LEAK-SWALLOW": (
        """
        def loop(self):
            try:
                self.step()
            except Exception:
                pass
        """,
        """
        def loop(self):
            try:
                self.step()
            except Exception as exc:
                self.mc.logger.send({"eventName": "stepError",
                                     "error": str(exc)})
        """,
        SERVICE,
    ),
    "FL-LEAK-FINALLY-MASK": (
        """
        def f():
            try:
                work()
            finally:
                return 1
        """,
        """
        def f():
            try:
                work()
            finally:
                cleanup()
        """,
        SERVICE,
    ),
    "FL-LEAK-GEN-HOLD": (
        """
        def walk(self):
            with self._lock:
                for x in self._items:
                    yield x
        """,
        """
        def walk(self):
            with self._lock:
                snap = list(self._items)
            for x in snap:
                yield x
        """,
        SERVICE,
    ),
    "FL-LEAK-DOUBLE-CLOSE": (
        """
        class Session:
            def _write(self):
                self.close()
            def close(self):
                self.writer.close()
        """,
        """
        class Session:
            def _write(self):
                self.close()
            def close(self):
                if self._closed:
                    return
                self._closed = True
                self.writer.close()
        """,
        SERVICE,
    ),
    "FL-DUR-RENAME": (
        """
        import os
        def publish(tmp, path):
            with open(tmp, "wb") as f:
                f.write(b"data")
            os.replace(tmp, path)
        """,
        """
        import os
        def publish(tmp, path):
            with open(tmp, "wb") as f:
                f.write(b"data")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        """,
        SERVICE,
    ),
    "FL-DUR-COMMIT": (
        """
        class Log:
            def append(self, msg, client):
                client.ack(msg)
                self._file.write(msg)  # commit-point: op record
        """,
        """
        class Log:
            def append(self, msg, client):
                self._file.write(msg)  # commit-point: op record
                client.ack(msg)
        """,
        SERVICE,
    ),
    "FL-DUR-UNWIND": (
        """
        class Seq:
            def __init__(self):
                self._seq = 0  # durable-shadow: stamp counter
            def stamp(self, msg):
                self._seq += 1
                self._log.write(msg)  # unwinds: _seq
        """,
        """
        class Seq:
            def __init__(self):
                self._seq = 0  # durable-shadow: stamp counter
            def stamp(self, msg):
                self._seq += 1
                try:
                    self._log.write(msg)  # unwinds: _seq
                except Exception:
                    self._seq -= 1
                    raise
        """,
        SERVICE,
    ),
    "FL-DUR-TORN": (
        """
        import os
        class Log:
            def __init__(self, path):
                self._file = open(path, "wb")  # durable-handle: single-record
            def append(self, head, body):
                self._file.write(head)
                self._file.write(body)
                os.fsync(self._file.fileno())
        """,
        """
        import os
        class Log:
            def __init__(self, path):
                self._file = open(path, "wb")  # durable-handle: single-record
            def append(self, head, body):
                self._file.write(head + body)
                self._file.flush()
                os.fsync(self._file.fileno())
        """,
        SERVICE,
    ),
    "FL-ERR-CROSS": (
        """
        class Session:
            def respond(self, req):
                out = self._dispatch(req)
                return {"ok": True, "result": out}
        """,
        """
        class Session:
            def respond(self, req):
                try:
                    out = self._dispatch(req)
                except Exception as exc:
                    return {"ok": False, "code": "internal",
                            "error": str(exc)}
                return {"ok": True, "result": out}
        """,
        SERVICE,
    ),
    "FL-ERR-HANDLER": (
        """
        class Session:
            def respond(self, session, req):
                try:
                    payload = self._build(req)
                except Exception:
                    payload = None
                send_obj(session, payload)
        """,
        """
        class Session:
            def respond(self, session, req):
                try:
                    payload = self._build(req)
                except Exception as exc:
                    payload = {"ok": False, "code": "internal",
                               "error": str(exc)}
                send_obj(session, payload)
        """,
        SERVICE,
    ),
    "FL-KERN-BLOCK": (
        """
        from jax.experimental import pallas as pl
        def fold(x, D):
            spec = pl.BlockSpec((D, 100), lambda d: (d, 0))
            return spec
        """,
        """
        from jax.experimental import pallas as pl
        LANE = 128
        def _round_up(n, mult):
            return ((n + mult - 1) // mult) * mult
        def fold(x, D):
            Dp = _round_up(D, 8)
            spec = pl.BlockSpec((Dp, LANE), lambda d: (d, 0))
            return spec
        """,
        OPS,
    ),
    "FL-KERN-NARROW": (
        """
        import numpy as np
        def pack(vals):
            return np.asarray(vals).astype(np.int16)
        """,
        """
        import numpy as np
        I16_LIMIT = 32766
        def pack(vals, meta):
            if not meta.get("i16_ok"):
                raise ValueError("values exceed the narrow bound")
            return np.asarray(vals).astype(np.int16)
        """,
        OPS,
    ),
    "FL-KERN-BUCKET": (
        """
        import jax
        @jax.jit
        def _fold(x, n):
            return x[:n]
        def run(x, docs):
            return _fold(x, len(docs))
        """,
        """
        import jax
        from .interning import next_bucket
        @jax.jit
        def _fold(x, n):
            return x[:n]
        def run(x, docs):
            return _fold(x, next_bucket(len(docs)))
        """,
        OPS,
    ),
    "FL-KERN-PAD": (
        """
        import jax.numpy as jnp
        def digest(x):
            plane = jnp.pad(x, ((0, 3),))
            return plane.sum()
        """,
        """
        import jax.numpy as jnp
        def digest(x, mask):
            plane = jnp.pad(x, ((0, 3),))
            return jnp.where(mask, plane, 0).sum()
        """,
        OPS,
    ),
}


@pytest.mark.parametrize("rule", sorted(MODULE_RULE_FIXTURES))
def test_positive_fixture_fires(rule):
    bad, _good, relpath = MODULE_RULE_FIXTURES[rule]
    hits = findings_for(bad, relpath, rule)
    assert hits, f"{rule}: positive fixture produced no finding"
    assert all(f.line > 0 and f.message for f in hits)


@pytest.mark.parametrize("rule", sorted(MODULE_RULE_FIXTURES))
def test_negative_fixture_is_clean(rule):
    _bad, good, relpath = MODULE_RULE_FIXTURES[rule]
    assert findings_for(good, relpath, rule) == [], (
        f"{rule}: negative fixture flagged")


@pytest.mark.parametrize("rule", sorted(MODULE_RULE_FIXTURES))
def test_testing_dir_is_exempt(rule):
    bad, _good, _relpath = MODULE_RULE_FIXTURES[rule]
    assert findings_for(bad, TESTING, rule) == []


def test_setiter_reports_each_site_once():
    # a loop inside a def is visible from the module walk AND its own
    # scope walk; the walker must stop at scope boundaries or every
    # function-body site double-reports
    src = """
    def order():
        ids = {1, 2, 3}
        for i in ids:
            pass
    """
    assert len(findings_for(src, LOADER, "FL-DET-SETITER")) == 1


def test_setiter_checks_class_bodies():
    # class bodies are their own lexical scope; a hash-order-dependent
    # class attribute must not slip past the gate
    src = """
    class Registry:
        IDS = {"b", "a"}
        ORDER = [x for x in IDS]
    """
    assert len(findings_for(src, LOADER, "FL-DET-SETITER")) == 1


def test_trace_rules_do_not_fire_outside_kernel_scope():
    bad, _good, _ = MODULE_RULE_FIXTURES["FL-TRACE-HOSTSYNC"]
    assert findings_for(bad, LOADER, "FL-TRACE-HOSTSYNC") == []


def test_untraced_function_not_flagged():
    # host syncs in plain host-side code are fine — scope is traced defs
    src = """
    import numpy as np
    def host_extract(arr):
        return np.asarray(arr).tolist()
    """
    assert findings_for(src, OPS, "FL-TRACE-HOSTSYNC") == []


def test_hostsync_messages_are_function_scoped():
    # suppression keys are (rule, path, message): naming the owning def
    # keeps one reviewed suppression from masking a future host sync in
    # a different function of the same file
    src = """
    import jax
    @jax.jit
    def fold_a(x):
        return x.item()
    @jax.jit
    def fold_b(x):
        return x.item()
    """
    msgs = {f.message for f in findings_for(src, OPS, "FL-TRACE-HOSTSYNC")}
    assert len(msgs) == 2
    assert any("fold_a()" in m for m in msgs)
    assert any("fold_b()" in m for m in msgs)


def test_scan_argument_is_traced():
    # functions passed by name to lax.scan count as traced
    src = """
    import jax
    from jax import lax
    def step(carry, x):
        return carry + x.item(), x
    def fold(xs):
        return lax.scan(step, 0, xs)
    """
    assert findings_for(src, OPS, "FL-TRACE-HOSTSYNC")


def test_donate_assigned_jit_form_and_position():
    # f = jax.jit(g, donate_argnums=(1,)) donates position 1 ONLY: a
    # later read of the position-0 arg is fine, the donated one fires.
    src = """
    import jax
    def g(a, b):
        return a + b
    f = jax.jit(g, donate_argnums=(1,))
    def caller(a, b):
        out = f(a, b)
        keep = a.sum()
        return out, keep, b.sum()
    """
    msgs = [x.message for x in findings_for(src, OPS, "FL-TRACE-DONATE")]
    assert len(msgs) == 1 and "'b' was donated" in msgs[0], msgs


def test_donate_rebind_before_read_clears():
    # A Store between the donating call and the read re-points the name
    # at a live value — no finding.
    src = """
    import functools
    import jax
    @functools.partial(jax.jit, donate_argnums=(0,))
    def extend(buf, rows):
        return buf + rows
    def caller(buf, rows, fresh):
        out = extend(buf, rows)
        buf = fresh
        return out, buf.sum()
    """
    assert findings_for(src, OPS, "FL-TRACE-DONATE") == []


def test_donate_attribute_receiver_not_flagged():
    # Attribute receivers (entry.ops) are the documented limit: the
    # owner swaps the reference (the device-cache idiom) and the rule
    # stays silent rather than guessing aliasing.
    src = """
    import functools
    import jax
    @functools.partial(jax.jit, donate_argnums=(0,))
    def extend(buf, rows):
        return buf + rows
    def caller(entry, rows):
        entry.ops = extend(entry.ops, rows)
        return entry.ops.sum()
    """
    assert findings_for(src, OPS, "FL-TRACE-DONATE") == []


def test_donate_outside_kernel_scope_is_exempt():
    bad, _good, _path = MODULE_RULE_FIXTURES["FL-TRACE-DONATE"]
    assert findings_for(bad, LOADER, "FL-TRACE-DONATE") == []


# -- fluidrace: the concurrency family ---------------------------------------


RACE_PREAMBLE = """
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
"""


def test_race_guard_inferred_without_annotation():
    # all writes under one lock => the attribute is adopted as guarded;
    # the unlocked read is a finding even with no '# guarded-by' comment
    src = RACE_PREAMBLE + """
        self._n = 0
    def bump(self):
        with self._lock:
            self._n += 1
    def peek(self):
        return self._n
"""
    hits = findings_for(src, SERVICE, "FL-RACE-GUARD")
    assert len(hits) == 1 and "peek()" in hits[0].message


def test_race_guard_ambiguous_multi_lock_inference_declined():
    # writes only in a `_locked` method of a two-lock class are "held
    # under ALL locks" — adopting either one would be a guess, flagging
    # correctly-locked reads against the wrong lock; such attrs need an
    # explicit declaration
    src = """
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._extend_lock = threading.Lock()
        self._n = 0
    def _bump_locked(self):
        self._n += 1
    def peek(self):
        with self._lock:
            return self._n
"""
    assert findings_for(src, SERVICE, "FL-RACE-GUARD") == []


def test_race_guard_mixed_lock_writes_not_inferred():
    # a write outside any lock makes the inference ambiguous — flagging
    # reads would be noise; only a declaration enforces such an attr
    src = RACE_PREAMBLE + """
        self._n = 0
    def bump(self):
        with self._lock:
            self._n += 1
    def reset(self):
        self._n = 0
    def peek(self):
        return self._n
"""
    assert findings_for(src, SERVICE, "FL-RACE-GUARD") == []


def test_race_guard_locked_suffix_and_holds_comment_exempt():
    src = RACE_PREAMBLE + """
        self._entries = {}  # guarded-by: _lock
    def _get_locked(self, k):
        return self._entries[k]
    def fetch(self, k):  # holds-lock: _lock
        return self._entries[k]
"""
    assert findings_for(src, SERVICE, "FL-RACE-GUARD") == []


def test_race_guard_holds_comment_may_follow_signature():
    src = RACE_PREAMBLE + """
        self._entries = {}  # guarded-by: _lock
    def fetch(self, k):
        # holds-lock: _lock
        return self._entries[k]
"""
    assert findings_for(src, SERVICE, "FL-RACE-GUARD") == []


def test_race_guard_unknown_lock_declaration_is_flagged():
    src = RACE_PREAMBLE + """
        self._entries = {}  # guarded-by: _mutex
"""
    hits = findings_for(src, SERVICE, "FL-RACE-GUARD")
    assert len(hits) == 1 and "_mutex" in hits[0].message


def test_race_guard_unknown_holds_lock_annotation_is_flagged():
    # a typo'd '# holds-lock:' must not silently exempt the method (and
    # silently decline all-writes inference for what it writes)
    src = RACE_PREAMBLE + """
        self._entries = {}  # guarded-by: _lock
    def fetch(self, k):  # holds-lock: _lokc
        return self._entries[k]
"""
    hits = findings_for(src, SERVICE, "FL-RACE-GUARD")
    assert len(hits) == 2  # the bad annotation AND the unguarded read
    bad = [h for h in hits if "_lokc" in h.message]
    assert len(bad) == 1 and "fetch()" in bad[0].message


def test_race_guard_known_holds_lock_annotation_not_flagged():
    src = RACE_PREAMBLE + """
        self._entries = {}  # guarded-by: _lock
    def fetch(self, k):  # holds-lock: _lock
        return self._entries[k]
"""
    assert findings_for(src, SERVICE, "FL-RACE-GUARD") == []


def test_race_guard_deferred_closure_is_not_lock_held():
    # a callback defined under the lock RUNS later, without it
    src = RACE_PREAMBLE + """
        self._entries = {}  # guarded-by: _lock
        self._cb = None
    def kick(self):
        with self._lock:
            def cb():
                return self._entries
            self._cb = cb
"""
    hits = findings_for(src, SERVICE, "FL-RACE-GUARD")
    assert len(hits) == 1
    assert "deferred callback" in hits[0].message
    assert "kick()" in hits[0].message


def test_race_guard_messages_are_function_scoped():
    src = RACE_PREAMBLE + """
        self._n = 0  # guarded-by: _lock
    def peek_a(self):
        return self._n
    def peek_b(self):
        return self._n
"""
    msgs = {f.message for f in findings_for(src, SERVICE, "FL-RACE-GUARD")}
    assert len(msgs) == 2
    assert any("peek_a()" in m for m in msgs)
    assert any("peek_b()" in m for m in msgs)


def test_race_single_threaded_class_is_not_analyzed():
    # no locks, no threads, no events: annotation-free and silent even
    # with "racy"-looking access patterns
    src = """
class Plain:
    def __init__(self):
        self._entries = {}
    def put(self, k, v):
        self._entries[k] = v
"""
    for rule in ("FL-RACE-GUARD", "FL-RACE-CHECKACT", "FL-RACE-MUTITER"):
        assert findings_for(src, SERVICE, rule) == []


def test_race_order_self_deadlock_on_nonreentrant_lock():
    src = RACE_PREAMBLE + """
    def oops(self):
        with self._lock:
            with self._lock:
                pass
"""
    hits = findings_for(src, SERVICE, "FL-RACE-ORDER")
    assert len(hits) == 1 and "non-reentrant" in hits[0].message


def test_race_order_rlock_self_nesting_allowed():
    src = """
import threading
class C:
    def __init__(self):
        self._lock = threading.RLock()
    def fine(self):
        with self._lock:
            with self._lock:
                pass
"""
    assert findings_for(src, SERVICE, "FL-RACE-ORDER") == []


def test_race_order_multi_item_with_acquires_sequentially():
    # `with a, b:` orders a before b, so an opposite nested order in
    # another method is a real cycle
    src = """
import threading
class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
    def one(self):
        with self._a, self._b:
            pass
    def two(self):
        with self._b:
            with self._a:
                pass
"""
    hits = findings_for(src, SERVICE, "FL-RACE-ORDER")
    assert len(hits) == 1 and "_a" in hits[0].message


def test_race_order_cycle_reported_once_per_class():
    bad, _good, _path = MODULE_RULE_FIXTURES["FL-RACE-ORDER"]
    hits = findings_for(bad, SERVICE, "FL-RACE-ORDER")
    assert len(hits) == 1
    assert "_a" in hits[0].message and "_b" in hits[0].message


def test_race_blocking_event_wait_under_lock():
    src = RACE_PREAMBLE + """
        self.done = threading.Event()
    def stall(self):
        with self._lock:
            self.done.wait(5)
"""
    hits = findings_for(src, SERVICE, "FL-RACE-BLOCKING")
    assert len(hits) == 1 and "stall()" in hits[0].message


LOOP_PREAMBLE = """
import selectors
class Pump:
    def __init__(self):
        self._sel = selectors.DefaultSelector()
"""


def test_race_blocking_on_loop_blocklist_call_no_lock_needed():
    # a selector-constructing class is an event-loop class: a blocklist
    # call in any of its methods fires with NO lock held — it stalls the
    # loop, not a lock contender
    src = LOOP_PREAMBLE + """
    def on_frame(self, conn, frame):
        return self.rpc.request("heads", {})
"""
    hits = findings_for(src, SERVICE, "FL-RACE-BLOCKING")
    assert len(hits) == 1 and "on-loop" in hits[0].message \
        and "on_frame()" in hits[0].message


def test_race_blocking_on_loop_exemptions():
    # the loop's own socket primitives (recv/accept) run non-blocking on
    # the loop by construction; '# off-loop' methods run on other
    # threads; a deferred lambda executes off-loop (that IS the fix);
    # __init__ runs before the loop exists
    src = LOOP_PREAMBLE + """
        self.rpc.request("hello", {})
    def service(self, key):
        data = key.fileobj.recv(65536)
        conn = self._lsock.accept()
        return data, conn
    def submit(self, pool, frame):
        pool.defer(lambda: self.rpc.request("fold", frame))
    def admin_stats(self):  # off-loop
        return self.rpc.request("stats", {})
"""
    assert findings_for(src, SERVICE, "FL-RACE-BLOCKING") == []


def test_race_blocking_on_loop_opt_in_marker():
    # '# on-loop' opts a method in even in a class that never constructs
    # a selector (e.g. a callback registered ON some other pump)
    src = """
import time
class Handler:
    def on_frame(self, conn, frame):  # on-loop
        time.sleep(1)
"""
    hits = findings_for(src, SERVICE, "FL-RACE-BLOCKING")
    assert len(hits) == 1 and "on-loop" in hits[0].message


def test_race_blocking_on_loop_under_lock_single_finding():
    # a call that is BOTH under a lock and on-loop yields one finding
    # (the under-lock message wins), never a duplicate pair
    src = """
import selectors, threading
class Pump:
    def __init__(self):
        self._sel = selectors.DefaultSelector()
        self._lock = threading.Lock()
    def on_frame(self, conn, frame):
        with self._lock:
            return self.rpc.request("heads", {})
"""
    hits = findings_for(src, SERVICE, "FL-RACE-BLOCKING")
    assert len(hits) == 1 and "holding" in hits[0].message


def test_race_blocking_event_wait_on_loop():
    # Event.wait inside an on-loop callback stalls the loop even with no
    # lock anywhere in sight
    src = """
import selectors, threading
class Pump:
    def __init__(self):
        self._sel = selectors.DefaultSelector()
        self.ready = threading.Event()
    def on_frame(self, conn, frame):
        self.ready.wait(5)
"""
    hits = findings_for(src, SERVICE, "FL-RACE-BLOCKING")
    assert len(hits) == 1 and "ready.wait" in hits[0].message


def test_race_class_level_lock_spelled_via_class_name():
    # `with C._serial:` inside class C counts as acquiring C's own lock
    src = """
import threading
class C:
    _serial = threading.RLock()
    def __init__(self):
        self.n = 0  # guarded-by: _serial
    def bump(self):
        with C._serial:
            self.n += 1
    def peek(self):
        return self.n
"""
    hits = findings_for(src, SERVICE, "FL-RACE-GUARD")
    assert len(hits) == 1 and "peek()" in hits[0].message


def test_race_checkact_ignores_deferred_writes():
    # a callback DEFINED under the second acquisition does not mutate in
    # that critical section — no check-then-act
    src = RACE_PREAMBLE + """
        self._entries = {}  # guarded-by: _lock
        self._cb = None
    def arm(self, k):
        with self._lock:
            seen = k in self._entries
        if not seen:
            with self._lock:
                def cb():
                    self._entries[k] = 1
                self._cb = cb
"""
    assert findings_for(src, SERVICE, "FL-RACE-CHECKACT") == []


def test_race_non_lock_context_manager_not_adopted_as_lock():
    # `with self._file:` on an attr visibly assigned a non-lock must not
    # poison guard inference with a bogus '_file' lock
    src = """
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._file = open("/dev/null")
        self._n = 0
    def write_a(self):
        with self._file:
            self._n += 1
    def write_b(self):
        with self._file:
            self._n += 1
    def peek(self):
        return self._n
"""
    assert findings_for(src, SERVICE, "FL-RACE-GUARD") == []


def test_race_manual_acquire_method_exempt_not_flagged():
    # imperative lock.acquire()/try/finally-release flow is beyond the
    # lexical held-set: such methods are trusted, never false-positived
    src = RACE_PREAMBLE + """
        self._n = 0  # guarded-by: _lock
    def manual(self):
        self._lock.acquire()
        try:
            self._n = 1
        finally:
            self._lock.release()
"""
    assert findings_for(src, SERVICE, "FL-RACE-GUARD") == []


def test_race_method_local_lock_not_adopted_as_member():
    # `lk = threading.Lock()` inside a method is a local, not a class
    # lock; `with lk:` must not feed guard inference
    src = """
import threading
class C:
    def __init__(self):
        self._real = threading.Lock()
        self._n = 0
    def bump(self):
        lk = threading.Lock()
        with lk:
            self._n += 1
    def peek(self):
        return self._n
"""
    assert findings_for(src, SERVICE, "FL-RACE-GUARD") == []


def test_race_checkact_nested_reentrant_acquire_is_one_section():
    # an RLock re-acquired inside its own critical section never
    # releases in between — not a separate acquisition
    src = """
import threading
class C:
    def __init__(self):
        self._lock = threading.RLock()
        self._m = {}  # guarded-by: _lock
    def put(self):
        with self._lock:
            x = self._m.get(1)
            with self._lock:
                self._m[1] = 2
"""
    assert findings_for(src, SERVICE, "FL-RACE-CHECKACT") == []


def test_race_waitforever_only_on_serving_paths():
    bad, _good, _path = MODULE_RULE_FIXTURES["FL-RACE-WAITFOREVER"]
    assert findings_for(bad, RUNTIME, "FL-RACE-WAITFOREVER") == []


def test_race_annotated_lock_assignment_still_analyzed():
    # a type-hinted lock (AnnAssign) must not silently disable the class
    src = """
import threading
class C:
    def __init__(self):
        self._lock: threading.Lock = threading.Lock()
        self._m = {}  # guarded-by: _lock
    def peek(self):
        return self._m
"""
    hits = findings_for(src, SERVICE, "FL-RACE-GUARD")
    assert len(hits) == 1 and "peek()" in hits[0].message


def test_race_nested_class_model_does_not_leak_into_enclosing():
    # Inner's lock + guarded-by must not make Outer thread-visible or
    # flag Outer's same-named attribute; Inner itself is still analyzed
    # (class_models builds a model per ClassDef, nested included)
    src = """
import threading
class Outer:
    def __init__(self):
        self._m = {}
    def touch(self):
        return self._m
    class Inner:
        def __init__(self):
            self._lock = threading.Lock()
            self._m = {}  # guarded-by: _lock
        def peek(self):
            return self._m
"""
    hits = findings_for(src, SERVICE, "FL-RACE-GUARD")
    assert len(hits) == 1
    assert "Inner" in hits[0].message and "peek()" in hits[0].message


def test_race_bare_annotated_lock_declaration_recognized():
    # a value-less typed declaration (`_lock: threading.Lock`, assigned
    # by a base/harness) must keep the class thread-visible and serve as
    # a guard — not silently disable the whole analysis
    src = """
import threading
class C:
    _lock: threading.Lock
    def __init__(self):
        self._m = {}  # guarded-by: _lock
    def put(self, k, v):
        with self._lock:
            self._m[k] = v
    def peek(self):
        return self._m
"""
    hits = findings_for(src, SERVICE, "FL-RACE-GUARD")
    assert len(hits) == 1 and "peek()" in hits[0].message


def test_race_condition_wait_under_its_lock_not_blocking():
    # Condition.wait() REQUIRES the lock held (it releases internally):
    # the canonical pattern must not be a blocking-under-lock finding...
    src = """
import threading
class C:
    def __init__(self):
        self._cond = threading.Condition()
        self.ready = False
    def consume(self):
        with self._cond:
            while not self.ready:
                self._cond.wait(5.0)
"""
    assert findings_for(src, SERVICE, "FL-RACE-BLOCKING") == []
    # ...but a timeout-less Condition.wait() still hangs a crashed
    # notifier's waiters: FL-RACE-WAITFOREVER owns that case.
    src_no_timeout = src.replace("self._cond.wait(5.0)",
                                 "self._cond.wait()")
    hits = findings_for(src_no_timeout, SERVICE, "FL-RACE-WAITFOREVER")
    assert len(hits) == 1 and "consume()" in hits[0].message


def test_race_blocking_messages_survive_baseline_hygiene(tmp_path):
    # the bare-acquire message spells '.acquire()' dot-prefixed so a
    # reviewed suppression of it can actually pass the hygiene check
    src = RACE_PREAMBLE + """
        self._other = threading.Lock()
    def grab(self):
        with self._lock:
            self._other.acquire()
"""
    hits = findings_for(src, SERVICE, "FL-RACE-BLOCKING")
    assert len(hits) == 1 and ".acquire()" in hits[0].message
    pkg = tmp_path / "fluidframework_tpu" / "service"
    pkg.mkdir(parents=True)
    (pkg / "x.py").write_text(textwrap.dedent(src))
    entry = {"rule": "FL-RACE-BLOCKING", "path": SERVICE,
             "message": hits[0].message, "reason": "reviewed"}
    assert baseline_function_hygiene(tmp_path, [entry]) == []


# -- project rule: FL-WIRE-COMPLETE ------------------------------------------


def _write_wire_tree(root, wire_body, test_body=None):
    proto = root / "fluidframework_tpu" / "protocol"
    proto.mkdir(parents=True)
    (proto / "messages.py").write_text(textwrap.dedent("""
        import dataclasses

        @dataclasses.dataclass
        class PingMessage:
            seq: int
    """))
    (proto / "wire.py").write_text(textwrap.dedent(wire_body))
    if test_body is not None:
        tdir = root / "tests"
        tdir.mkdir()
        (tdir / "test_wire_roundtrip.py").write_text(
            textwrap.dedent(test_body))


COMPLETE_WIRE = """
    def encode_ping_message(m): return {"seq": m.seq}
    def decode_ping_message(d): return d["seq"]
    MESSAGE_CODECS = {"PingMessage": (encode_ping_message,
                                      decode_ping_message)}
"""


def test_wire_complete_positive(tmp_path):
    _write_wire_tree(tmp_path, "MESSAGE_CODECS = {}\n", test_body="x = 1\n")
    msgs = {f.message for f in analyze(tmp_path)
            if f.rule == "FL-WIRE-COMPLETE"}
    assert any("encode_ping_message" in m for m in msgs), msgs
    assert any("decode_ping_message" in m for m in msgs), msgs
    assert any("MESSAGE_CODECS" in m for m in msgs), msgs
    assert any("round-trip coverage" in m for m in msgs), msgs


def test_wire_complete_negative(tmp_path):
    _write_wire_tree(tmp_path, COMPLETE_WIRE,
                     test_body="from x import PingMessage\n")
    assert [f for f in analyze(tmp_path)
            if f.rule == "FL-WIRE-COMPLETE"] == []


def test_project_rules_skipped_on_path_scoped_runs(tmp_path):
    # whole-repo contracts don't belong to a "files I touched" run (and
    # their suppressions would be filtered out of scope with them)
    _write_wire_tree(tmp_path, "MESSAGE_CODECS = {}\n", test_body="x = 1\n")
    scoped = analyze(tmp_path,
                     relpaths=["fluidframework_tpu/protocol/messages.py"])
    assert [f for f in scoped if f.rule == "FL-WIRE-COMPLETE"] == []


def test_wire_complete_missing_test_suite(tmp_path):
    _write_wire_tree(tmp_path, COMPLETE_WIRE, test_body=None)
    msgs = {f.message for f in analyze(tmp_path)
            if f.rule == "FL-WIRE-COMPLETE"}
    assert any("no tests/test_wire*.py" in m for m in msgs), msgs


def test_wire_complete_covers_wire_module_dataclasses(tmp_path):
    """A dataclass defined in wire.py ITSELF (the columnar batch forms)
    carries the same codec + registry + round-trip obligations as one in
    messages.py."""
    _write_wire_tree(tmp_path, COMPLETE_WIRE + """
    import dataclasses

    @dataclasses.dataclass(eq=False)
    class ColumnBatch:
        packed: bytes
""", test_body="from x import PingMessage\n")
    msgs = {f.message for f in analyze(tmp_path)
            if f.rule == "FL-WIRE-COMPLETE"}
    assert any("encode_column_batch" in m for m in msgs), msgs
    assert any("decode_column_batch" in m for m in msgs), msgs
    assert any("ColumnBatch is not registered" in m for m in msgs), msgs
    assert any("ColumnBatch has no round-trip coverage" in m
               for m in msgs), msgs


def test_wire_complete_wire_dataclass_negative(tmp_path):
    _write_wire_tree(tmp_path, """
    import dataclasses

    @dataclasses.dataclass(eq=False)
    class ColumnBatch:
        packed: bytes

    def encode_ping_message(m): return {"seq": m.seq}
    def decode_ping_message(d): return d["seq"]
    def encode_column_batch(b): return {"packed": b.packed}
    def decode_column_batch(d): return ColumnBatch(d["packed"])
    MESSAGE_CODECS = {"PingMessage": (encode_ping_message,
                                      decode_ping_message),
                      "ColumnBatch": (encode_column_batch,
                                      decode_column_batch)}
""", test_body="from x import PingMessage, ColumnBatch\n")
    assert [f for f in analyze(tmp_path)
            if f.rule == "FL-WIRE-COMPLETE"] == []


# -- baseline machinery ------------------------------------------------------


def _finding(msg="m1"):
    return Finding("FL-DET-CLOCK", "error", "pkg/a.py", 10, msg)


def _entry(msg="m1", reason="reviewed: fixture"):
    return {"rule": "FL-DET-CLOCK", "path": "pkg/a.py",
            "message": msg, "reason": reason}


def test_baseline_suppresses_by_rule_path_message():
    report = apply_baseline([_finding()], [_entry()])
    assert report.clean
    assert len(report.suppressed) == 1


def test_baseline_is_line_independent():
    moved = Finding("FL-DET-CLOCK", "error", "pkg/a.py", 99, "m1")
    assert apply_baseline([moved], [_entry()]).clean


def test_stale_suppression_fails_gate():
    report = apply_baseline([], [_entry()])
    assert not report.clean
    assert report.stale == [_entry()]


def test_reasonless_suppression_fails_gate():
    report = apply_baseline([_finding()], [_entry(reason="  ")])
    assert not report.clean
    assert report.invalid


def test_unsuppressed_finding_fails_gate():
    report = apply_baseline([_finding("other")], [_entry()])
    assert not report.clean
    assert [f.message for f in report.unsuppressed] == ["other"]


def test_missing_baseline_path_is_a_usage_error(tmp_path):
    from tools.fluidlint.cli import main
    assert main(["--root", str(tmp_path),
                 "--baseline", "lint_baseline.json"]) == 2


def test_path_scoped_run_ignores_out_of_scope_suppressions(tmp_path):
    # linting one clean file must not go red because the baseline also
    # covers findings in files outside the analyzed subset
    from tools.fluidlint.cli import main
    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "clean.py").write_text("x = 1\n")
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({"version": 1, "suppressions": [
        {"rule": "FL-DET-CLOCK",
         "path": "fluidframework_tpu/loader/other.py",
         "message": "m", "reason": "reviewed"}]}))
    assert main(["--root", str(tmp_path), "--baseline", str(bp),
                 "fluidframework_tpu/loader/clean.py"]) == 0


def test_path_arguments_are_normalized_against_root(tmp_path, capsys):
    # a './'-spelled path must hit the same rule scopes as the canonical
    # repo-relative form, not silently match nothing and pass
    from tools.fluidlint.cli import main
    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    rc = main(["--root", str(tmp_path),
               "./fluidframework_tpu/loader/bad.py"])
    assert rc == 1
    assert "FL-DET-CLOCK" in capsys.readouterr().out
    assert main(["--root", str(tmp_path), "/etc/passwd"]) == 2


def test_path_scoped_run_ignores_project_rule_suppressions(tmp_path):
    # analyze() skips project rules on scoped runs, so their reviewed
    # suppressions must not surface as stale
    from tools.fluidlint.cli import main
    pkg = tmp_path / "fluidframework_tpu" / "protocol"
    pkg.mkdir(parents=True)
    (pkg / "wire.py").write_text("x = 1\n")
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({"version": 1, "suppressions": [
        {"rule": "FL-WIRE-COMPLETE",
         "path": "fluidframework_tpu/protocol/wire.py",
         "message": "m", "reason": "reviewed"}]}))
    assert main(["--root", str(tmp_path), "--baseline", str(bp),
                 "fluidframework_tpu/protocol/wire.py"]) == 0


def test_directory_path_argument_expands_to_py_files(tmp_path, capsys):
    from tools.fluidlint.cli import main
    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")
    assert main(["--root", str(tmp_path), "fluidframework_tpu"]) == 1
    assert "FL-DET-CLOCK" in capsys.readouterr().out


def test_duplicate_baseline_entries_are_invalid():
    report = apply_baseline([_finding()], [_entry(), _entry()])
    assert not report.clean
    assert any("duplicate" in m for m in report.invalid)
    assert report.stale == []


def test_invalid_entry_not_double_reported_as_stale():
    report = apply_baseline([], [{"rule": "FL-DET-CLOCK",
                                  "message": "m", "reason": "r"}])
    assert report.invalid
    assert report.stale == []


def test_load_baseline_rejects_non_object(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps(["not", "an", "object"]))
    with pytest.raises(ValueError):
        load_baseline(p)


# -- baseline function hygiene ------------------------------------------------


def _hygiene_tree(tmp_path, body="def hold():\n    return 1\n"):
    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "mod.py").write_text(body)
    return "fluidframework_tpu/loader/mod.py"


def _hygiene_entry(path, msg):
    return {"rule": "FL-DET-CLOCK", "path": path, "message": msg,
            "reason": "reviewed"}


def test_hygiene_flags_entry_for_deleted_function(tmp_path):
    path = _hygiene_tree(tmp_path)
    entries = [_hygiene_entry(path, "wall-clock read in vanished()")]
    problems = baseline_function_hygiene(tmp_path, entries)
    assert len(problems) == 1 and "vanished" in problems[0]


def test_hygiene_accepts_live_function_reference(tmp_path):
    path = _hygiene_tree(tmp_path)
    entries = [_hygiene_entry(path, "wall-clock read in hold()")]
    assert baseline_function_hygiene(tmp_path, entries) == []


def test_hygiene_ignores_builtins_and_dotted_calls(tmp_path):
    # "time.time()" names an API, "int()" a builtin — neither is a
    # function-scoped key; only bare local names count
    path = _hygiene_tree(tmp_path)
    entries = [_hygiene_entry(
        path, "int() via time.time() then str.join() somewhere")]
    assert baseline_function_hygiene(tmp_path, entries) == []


def test_hygiene_flags_entry_for_deleted_file(tmp_path):
    _hygiene_tree(tmp_path)
    entries = [_hygiene_entry("fluidframework_tpu/loader/gone.py",
                              "wall-clock read in hold()")]
    problems = baseline_function_hygiene(tmp_path, entries)
    assert len(problems) == 1 and "no longer exists" in problems[0]


def test_hygiene_fails_the_cli_gate(tmp_path, capsys):
    from tools.fluidlint.cli import main
    path = _hygiene_tree(tmp_path)
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({"version": 1, "suppressions": [
        # message matches nothing AND names a dead function: surface the
        # hygiene diagnostic alongside staleness, and fail
        _hygiene_entry(path, "wall-clock read in vanished()")]}))
    assert main(["--root", str(tmp_path), "--baseline", str(bp)]) == 1
    out = capsys.readouterr().out
    assert "vanished" in out and "hygiene" in out


def test_check_baseline_mode_runs_without_analysis(tmp_path, capsys):
    from tools.fluidlint.cli import main
    path = _hygiene_tree(tmp_path)
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({"version": 1, "suppressions": [
        _hygiene_entry(path, "wall-clock read in hold()")]}))
    assert main(["--root", str(tmp_path), "--baseline", str(bp),
                 "--check-baseline"]) == 0
    bp.write_text(json.dumps({"version": 1, "suppressions": [
        _hygiene_entry(path, "wall-clock read in vanished()")]}))
    assert main(["--root", str(tmp_path), "--baseline", str(bp),
                 "--check-baseline"]) == 1


# -- CLI: --rules family filtering & --json -----------------------------------


def _clock_violation_tree(tmp_path):
    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\n\ndef hold():\n    return time.time()\n")


def test_rules_filter_excludes_other_families(tmp_path, capsys):
    from tools.fluidlint.cli import main
    _clock_violation_tree(tmp_path)
    # The clock violation is invisible to a FL-RACE-only run...
    assert main(["--root", str(tmp_path), "--rules", "FL-RACE"]) == 0
    capsys.readouterr()
    # ...and still red for the family that owns it (prefix match).
    assert main(["--root", str(tmp_path), "--rules", "FL-DET"]) == 1
    assert "FL-DET-CLOCK" in capsys.readouterr().out


def test_rules_filter_spares_out_of_family_suppressions(tmp_path):
    # entries for unselected rules are ignored, not reported stale
    from tools.fluidlint.cli import main
    _clock_violation_tree(tmp_path)
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({"version": 1, "suppressions": [
        {"rule": "FL-DET-CLOCK",
         "path": "fluidframework_tpu/loader/bad.py",
         "message": "m-that-matches-nothing", "reason": "reviewed"}]}))
    assert main(["--root", str(tmp_path), "--baseline", str(bp),
                 "--rules", "FL-RACE"]) == 0


def test_rules_filter_rejects_unknown_family(tmp_path):
    from tools.fluidlint.cli import main
    _clock_violation_tree(tmp_path)
    assert main(["--root", str(tmp_path), "--rules", "FL-NOPE"]) == 2


def test_json_flag_emits_machine_readable_report(tmp_path, capsys):
    from tools.fluidlint.cli import main
    _clock_violation_tree(tmp_path)
    assert main(["--root", str(tmp_path), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["unsuppressed"], doc
    assert doc["unsuppressed"][0]["rule"] == "FL-DET-CLOCK"
    assert set(doc) == {"unsuppressed", "suppressed", "stale_suppressions",
                       "invalid_suppressions", "baseline_hygiene"}


# -- fluidleak: exit-path enumerator ------------------------------------------


def _parse_fn(src):
    import ast
    return ast.parse(textwrap.dedent(src)).body[0]


def test_exit_paths_enumerate_every_exit_kind():
    from tools.fluidlint.core import iter_exit_paths
    fn = _parse_fn("""
    def f(x):
        a = probe()
        if x:
            return 1
        raise ValueError("no")
    """)
    kinds = {p.kind for p in iter_exit_paths(fn)}
    # probe()/ValueError() may raise ("exception"), the explicit raise is
    # "raise", the if-true arm is "return"; no path falls off the end.
    assert kinds == {"return", "raise", "exception"}


def test_exit_paths_fall_through_records_calls_in_order():
    from tools.fluidlint.core import iter_exit_paths
    fn = _parse_fn("""
    def f():
        first()
        second()
    """)
    falls = [p for p in iter_exit_paths(fn) if p.kind == "fall"]
    assert len(falls) == 1
    names = [ev.node.func.id for ev in falls[0].events
             if ev.kind == "call"]
    assert names == ["first", "second"]


def test_exit_paths_finally_runs_on_exception_flows():
    from tools.fluidlint.core import iter_exit_paths
    fn = _parse_fn("""
    def f(res):
        res.start()
        try:
            work()
        finally:
            res.stop()
    """)
    def attr(ev):
        return getattr(ev.node.func, "attr", None)

    for p in iter_exit_paths(fn):
        started = [i for i, ev in enumerate(p.events)
                   if ev.kind == "call" and attr(ev) == "start"]
        if not started:
            continue  # start() itself raised
        assert any(attr(ev) == "stop"
                   for ev in p.events[started[0] + 1:]
                   if ev.kind in ("call", "call-raised")), (
            f"path exiting via {p.kind} never reached the finally")


def test_exit_paths_decline_over_budget():
    from tools.fluidlint.core import iter_exit_paths
    body = "".join(f"    if a{i}():\n        b{i}()\n" for i in range(64))
    fn = _parse_fn("def f():\n" + body)
    assert iter_exit_paths(fn) is None


def test_pair_rule_declines_over_budget_instead_of_guessing():
    # an opener followed by pathological branching: the enumerator
    # declines, so the rule reports NOTHING (never guesses)
    body = "".join(f"    if a{i}():\n        b{i}()\n" for i in range(64))
    src = ("class S:\n    def work(self, k):\n"
           "        self.cache.begin(k)\n" + body.replace("    ", "        "))
    assert findings_for(src, SERVICE, "FL-LEAK-PAIR") == []


# -- fluidleak: FL-LEAK-PAIR edges --------------------------------------------


def test_pair_closer_on_every_branch_is_clean():
    src = """
    class S:
        def work(self, k):
            h = self.c.begin(k)
            if h:
                self.c.finish(k)
            else:
                self.c.abandon(k)
    """
    assert findings_for(src, SERVICE, "FL-LEAK-PAIR") == []


def test_pair_executor_shutdown_keywords_not_an_opener():
    # shutdown->close is the SOCKET pair; Executor.shutdown(wait=...) is
    # itself terminal (keyword args mark the executor signature) while a
    # bare socket shutdown(how) still demands its close
    good = """
    class S:
        def stop(self):
            self.pool.shutdown(wait=False)
    """
    bad = """
    import socket
    class S:
        def stop(self):
            self.sock.shutdown(socket.SHUT_RDWR)
    """
    assert findings_for(good, SERVICE, "FL-LEAK-PAIR") == []
    assert findings_for(bad, SERVICE, "FL-LEAK-PAIR")


def test_pair_closer_on_one_branch_only_fires():
    src = """
    class S:
        def work(self, k):
            h = self.c.begin(k)
            if h:
                self.c.finish(k)
    """
    hits = findings_for(src, SERVICE, "FL-LEAK-PAIR")
    assert hits and "begin" in hits[0].message


def test_pair_receiver_must_match():
    # closing a DIFFERENT receiver's protocol does not close this one
    src = """
    class S:
        def work(self, k):
            self.c.begin(k)
            self.other.finish(k)
    """
    assert findings_for(src, SERVICE, "FL-LEAK-PAIR")


def test_pair_pairs_with_annotation_declares_site_specific_closers():
    bad = """
    class S:
        def work(self, key):
            h = self.store.grab(key)  # pairs-with: put_back, drop
            return self.fold(h)
    """
    good = """
    class S:
        def work(self, key):
            h = self.store.grab(key)  # pairs-with: put_back, drop
            try:
                return self.fold(h)
            finally:
                self.store.drop(key)
    """
    assert findings_for(bad, SERVICE, "FL-LEAK-PAIR")
    assert findings_for(good, SERVICE, "FL-LEAK-PAIR") == []


def test_pair_with_statement_counts_as_closed():
    src = """
    class S:
        def work(self, k):
            with self.pool.acquire(k) as conn:
                return conn.run()
    """
    assert findings_for(src, SERVICE, "FL-LEAK-PAIR") == []


def test_pair_imperative_lock_requires_release():
    bad = """
    class S:
        def work(self):
            self._lock.acquire()
            return self.compute()
    """
    good = """
    class S:
        def work(self):
            self._lock.acquire()
            try:
                return self.compute()
            finally:
                self._lock.release()
    """
    assert findings_for(bad, SERVICE, "FL-LEAK-PAIR")
    assert findings_for(good, SERVICE, "FL-LEAK-PAIR") == []


def test_exit_paths_break_escaping_a_finally_to_outer_loop():
    # regression: break/continue flow items are bare event tuples — the
    # finally re-threading used to index them as (events, node) pairs
    # and crash the whole analyze() run with a TypeError
    from tools.fluidlint.core import iter_exit_paths
    fn = _parse_fn("""
    def f(self, items):
        for x in items:
            try:
                if x:
                    break
                if not x:
                    continue
            finally:
                cleanup(x)
        done()
    """)
    paths = iter_exit_paths(fn)
    assert paths is not None
    falls = [p for p in paths if p.kind == "fall"]
    assert falls, "break out of the loop must still fall off the end"
    # ...and the escaping break ran the finally before leaving the try
    names = [[getattr(ev.node.func, "id", None) for ev in p.events
              if ev.kind == "call"] for p in falls]
    assert any("cleanup" in seq and "done" in seq for seq in names)


def test_pair_break_through_finally_is_analyzed_not_crashed():
    src = """
    class S:
        def work(self, items):
            self._lock.acquire()
            try:
                for x in items:
                    try:
                        if x:
                            break
                    finally:
                        self.note(x)
            finally:
                self._lock.release()
    """
    assert findings_for(src, SERVICE, "FL-LEAK-PAIR") == []


def test_pair_match_case_arms_branch_not_flatten():
    # regression: match fell into the plain-statement branch, flattening
    # case bodies into straight-line code — a closer in ONE arm looked
    # unconditional and a leaking arm's early return was invisible
    bad = """
    class S:
        def work(self, k):
            self.cache.begin(k)
            match k:
                case 0:
                    return None
                case _:
                    self.cache.finish(k)
    """
    good = """
    class S:
        def work(self, k):
            self.cache.begin(k)
            match k:
                case 0:
                    self.cache.abandon(k)
                case _:
                    self.cache.finish(k)
    """
    hits = findings_for(bad, SERVICE, "FL-LEAK-PAIR")
    assert hits and "begin" in hits[0].message
    assert findings_for(good, SERVICE, "FL-LEAK-PAIR") == []


def test_pair_non_exhaustive_match_keeps_fall_through_path():
    # no wildcard arm: no case may match, so the closer inside the only
    # arm does not cover the fall-through path
    src = """
    class S:
        def work(self, k):
            self.cache.begin(k)
            match k:
                case 0:
                    self.cache.finish(k)
    """
    assert findings_for(src, SERVICE, "FL-LEAK-PAIR")


# -- fluidleak: FL-LEAK-ESCAPE edges ------------------------------------------


def test_escape_handoff_to_self_is_not_a_leak():
    src = """
    import socket
    class C:
        def connect(self, host):
            s = socket.create_connection((host, 1))
            self._sock = s
    """
    assert findings_for(src, SERVICE, "FL-LEAK-ESCAPE") == []


def test_escape_handoff_as_argument_is_not_a_leak():
    src = """
    import socket
    def connect(pool, host):
        s = socket.create_connection((host, 1))
        pool.adopt(s)
    """
    assert findings_for(src, SERVICE, "FL-LEAK-ESCAPE") == []


def test_escape_daemon_thread_is_exempt_nondaemon_is_not():
    daemon = """
    import threading
    def run(fn):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
    """
    plain = """
    import threading
    def run(fn):
        t = threading.Thread(target=fn)
        t.start()
    """
    assert findings_for(daemon, SERVICE, "FL-LEAK-ESCAPE") == []
    assert findings_for(plain, SERVICE, "FL-LEAK-ESCAPE")


def test_escape_close_in_finally_is_clean():
    src = """
    def read(path):
        f = open(path)
        try:
            return f.read()
        finally:
            f.close()
    """
    assert findings_for(src, SERVICE, "FL-LEAK-ESCAPE") == []


def test_escape_popen_is_tracked_positive_and_negative():
    """ISSUE 12 satellite: subprocess.Popen is a tracked resource — a
    fire-and-forget child process (zombie + leaked pipes) fires; reaping
    on all paths (try/finally wait or terminate) and the supervisor
    hand-off shape (stored on self) stay clean."""
    bad = """
    import subprocess
    def probe(cmd):
        p = subprocess.Popen(cmd)
        return p.stdout.read()
    """
    reaped = """
    import subprocess
    def probe(cmd):
        p = subprocess.Popen(cmd)
        try:
            return p.stdout.read()
        finally:
            p.wait()
    """
    killed = """
    import subprocess
    def probe(cmd):
        p = subprocess.Popen(cmd)
        try:
            return p.stdout.read()
        finally:
            p.kill()
    """
    handed_off = """
    import subprocess
    class Supervisor:
        def spawn(self, cmd):
            p = subprocess.Popen(cmd)
            self._shards.append(p)
    """
    assert findings_for(bad, SERVICE, "FL-LEAK-ESCAPE")
    assert findings_for(reaped, SERVICE, "FL-LEAK-ESCAPE") == []
    assert findings_for(killed, SERVICE, "FL-LEAK-ESCAPE") == []
    assert findings_for(handed_off, SERVICE, "FL-LEAK-ESCAPE") == []


def test_escape_makefile_needs_close():
    bad = """
    class C:
        def loop(self):
            rfile = self._sock.makefile("rb")
            return rfile.read(4)
    """
    good = """
    class C:
        def loop(self):
            rfile = self._sock.makefile("rb")
            try:
                return rfile.read(4)
            finally:
                rfile.close()
    """
    assert findings_for(bad, SERVICE, "FL-LEAK-ESCAPE")
    assert findings_for(good, SERVICE, "FL-LEAK-ESCAPE") == []


# -- fluidleak: FL-LEAK-SWALLOW edges -----------------------------------------


def test_swallow_bare_except_fires():
    src = """
    def loop(self):
        try:
            self.step()
        except:
            self.count += 1
    """
    assert findings_for(src, SERVICE, "FL-LEAK-SWALLOW")


def test_swallow_reraise_is_clean():
    src = """
    def loop(self):
        try:
            self.step()
        except Exception:
            self.rollback()
            raise
    """
    assert findings_for(src, SERVICE, "FL-LEAK-SWALLOW") == []


def test_swallow_narrow_exception_is_clean():
    src = """
    def loop(self):
        try:
            self.step()
        except KeyError:
            pass
    """
    assert findings_for(src, SERVICE, "FL-LEAK-SWALLOW") == []


def test_swallow_tuple_broad_except_fires():
    """`except (Exception, ValueError):` is the same front door as
    `except Exception:` — the tuple spelling must not slip the gate."""
    src = """
    def loop(self):
        try:
            self.step()
        except (Exception, ValueError):
            pass
    """
    assert findings_for(src, SERVICE, "FL-LEAK-SWALLOW")
    narrow = """
    def loop(self):
        try:
            self.step()
        except (KeyError, ValueError):
            pass
    """
    assert findings_for(narrow, SERVICE, "FL-LEAK-SWALLOW") == []


def test_swallow_sink_names_match_whole_words_only():
    """A bare call only counts as a telemetry sink when a whole
    underscore-word says so: 'update_backlog'/'login'/'catalog' merely
    CONTAIN 'log' and must not launder the swallow, while a real
    'log_event'/'warn' direct call still does."""
    for decoy in ("self.update_backlog()", "self.login()", "catalog()",
                  "self.backlog.put(1)"):
        src = f"""
        def loop(self):
            try:
                self.step()
            except Exception:
                {decoy}
        """
        assert findings_for(src, SERVICE, "FL-LEAK-SWALLOW"), decoy
    for sink in ("log_event('stepError')", "warn('stepError')"):
        src = f"""
        def loop(self):
            try:
                self.step()
            except Exception:
                {sink}
        """
        assert findings_for(src, SERVICE, "FL-LEAK-SWALLOW") == [], sink


def test_swallow_scope_is_serving_paths_only():
    bad, _good, _ = MODULE_RULE_FIXTURES["FL-LEAK-SWALLOW"]
    assert findings_for(bad, RUNTIME, "FL-LEAK-SWALLOW") == []


# -- fluidleak: FL-LEAK-FINALLY-MASK edges ------------------------------------


def test_finally_mask_bare_reraise_is_fine():
    src = """
    def f():
        try:
            work()
        except Exception:
            raise
        finally:
            try:
                cleanup()
            except OSError:
                raise
    """
    # `raise` with no exception re-raises; only `raise X` masks
    assert findings_for(src, SERVICE, "FL-LEAK-FINALLY-MASK") == []


def test_finally_mask_break_inside_local_loop_is_fine():
    src = """
    def f(items):
        try:
            work()
        finally:
            for x in items:
                if x:
                    break
    """
    assert findings_for(src, SERVICE, "FL-LEAK-FINALLY-MASK") == []


def test_finally_mask_continue_fires():
    src = """
    def f(items):
        for x in items:
            try:
                work(x)
            finally:
                continue
    """
    assert findings_for(src, SERVICE, "FL-LEAK-FINALLY-MASK")


def test_finally_mask_nested_try_reported_once():
    """A try/finally nested inside an outer finally must not double-
    report: the outer finalbody walk already covers it, and check()'s
    direct visit of the inner Try has to be skipped."""
    src = """
    def f():
        try:
            a()
        finally:
            try:
                b()
            finally:
                return 1
    """
    found = findings_for(src, SERVICE, "FL-LEAK-FINALLY-MASK")
    assert len(found) == 1, [f.message for f in found]


def test_finally_mask_caught_raise_inside_finally_is_fine():
    """A raise inside a finally-local try WITH handlers is assumed
    caught before it can mask the in-flight exception; the same raise
    in a handler or orelse body stays unprotected and fires."""
    src = """
    def f():
        try:
            work()
        finally:
            try:
                raise ValueError("probe")
            except ValueError:
                cleanup()
    """
    assert findings_for(src, SERVICE, "FL-LEAK-FINALLY-MASK") == []
    src_handler = """
    def f():
        try:
            work()
        finally:
            try:
                cleanup()
            except OSError:
                raise RuntimeError("masks")
    """
    assert findings_for(src_handler, SERVICE, "FL-LEAK-FINALLY-MASK")


# -- fluidleak: FL-LEAK-GEN-HOLD edges ----------------------------------------


def test_gen_hold_open_file_handle_fires():
    src = """
    def lines(path):
        with open(path) as f:
            for line in f:
                yield line
    """
    assert findings_for(src, SERVICE, "FL-LEAK-GEN-HOLD")


def test_gen_hold_non_resource_context_is_fine():
    src = """
    def rows(self):
        with self.profiler:
            for r in self._rows:
                yield r
    """
    assert findings_for(src, SERVICE, "FL-LEAK-GEN-HOLD") == []


# -- fluidleak: FL-LEAK-DOUBLE-CLOSE edges ------------------------------------


def test_double_close_two_tracked_call_sites_fire():
    src = """
    class C:
        def close(self):
            self._file.close()
    def teardown():
        c = C()
        c.close()
        c.close()
    """
    assert findings_for(src, SERVICE, "FL-LEAK-DOUBLE-CLOSE")


def test_double_close_single_call_site_is_quiet():
    src = """
    class C:
        def close(self):
            self._file.close()
    def teardown():
        c = C()
        c.close()
    """
    assert findings_for(src, SERVICE, "FL-LEAK-DOUBLE-CLOSE") == []


def test_double_close_try_except_guard_accepted():
    # the _RpcClient shape: every release individually armored
    src = """
    class C:
        def reset(self):
            self.close()
        def close(self):
            try:
                self._sock.shutdown()
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
    """
    assert findings_for(src, SERVICE, "FL-LEAK-DOUBLE-CLOSE") == []


def test_double_close_lock_wrapped_guard_accepted():
    # the OTHER _RpcClient shape: the idempotency flag is checked and
    # set under the state lock; the guard must be seen through `with`
    src = """
    class C:
        def reset(self):
            self.close()
        def close(self):
            with self._state_lock:
                if self._closed:
                    return
                self._closed = True
            self._writer.close()
    """
    assert findings_for(src, SERVICE, "FL-LEAK-DOUBLE-CLOSE") == []


# -- fluiddur behavior details -----------------------------------------------


def test_dur_rename_flags_os_rename_and_unflushed_fsync():
    src = """
    import os
    def publish(tmp_path, path, f):
        f.write(b"data")
        os.fsync(f.fileno())
        os.rename(tmp_path, path)
    """
    msgs = [f.message for f in findings_for(src, SERVICE, "FL-DUR-RENAME")]
    assert any("use os.replace()" in m for m in msgs), msgs
    assert any("without a preceding .flush()" in m for m in msgs), msgs


def test_dur_rename_tmpness_through_local_assignment():
    # the publish source is tmp-ish only via the local name it was
    # assigned from — the rule must chase one level of assignment
    src = """
    import os
    def publish(base, path):
        staging = base + ".tmp"
        os.replace(staging, path)
    """
    hits = findings_for(src, SERVICE, "FL-DUR-RENAME")
    assert len(hits) == 1 and "no os.fsync()" in hits[0].message


def test_dur_commit_annotation_requires_a_call():
    src = """
    class Log:
        def append(self, msg):
            pending = True  # commit-point: op record
            self._file.write(msg)
    """
    hits = findings_for(src, SERVICE, "FL-DUR-COMMIT")
    assert len(hits) == 1 and "no call" in hits[0].message


def test_dur_commit_names_the_label():
    src = """
    class Log:
        def append(self, msg, client):
            client.broadcast(msg)
            self._file.write(msg)  # commit-point: op record
    """
    hits = findings_for(src, SERVICE, "FL-DUR-COMMIT")
    assert len(hits) == 1
    assert "broadcast" in hits[0].message
    assert "op record" in hits[0].message


def test_dur_unwind_unknown_attribute_is_flagged():
    src = """
    class Seq:
        def __init__(self):
            self._seq = 0  # durable-shadow: stamp counter
        def stamp(self, msg):
            try:
                self._log.write(msg)  # unwinds: _sqe
            except Exception:
                raise
    """
    hits = findings_for(src, SERVICE, "FL-DUR-UNWIND")
    assert len(hits) == 1 and "_sqe" in hits[0].message
    assert "not declared" in hits[0].message


def test_dur_unwind_bare_commit_point_needs_pairing():
    src = """
    class Seq:
        def __init__(self):
            self._seq = 0  # durable-shadow: stamp counter
        def stamp(self, msg):
            self._seq += 1
            self._log.write(msg)  # commit-point: stamp record
    """
    hits = findings_for(src, SERVICE, "FL-DUR-UNWIND")
    assert len(hits) == 1
    assert "no '# unwinds:' pairing" in hits[0].message


def test_dur_unwind_restores_through_alias_and_helper():
    # the two real restore shapes: a subscript store through a local
    # alias of the shadow attr, and a one-level same-class helper call
    src = """
    class Seq:
        def __init__(self):
            self._docs = {}  # durable-shadow: log view
            self._slots = {}  # durable-shadow: membership
        def _drop(self, cid):
            self._slots = {}
        def stamp(self, cid, msg):
            log = self._docs.setdefault(cid, [])
            log.append(msg)
            self._slots[cid] = 1
            try:
                self._file.write(msg)  # unwinds: _docs, _slots
            except Exception:
                log.pop()
                self._drop(cid)
                raise
    """
    assert findings_for(src, SERVICE, "FL-DUR-UNWIND") == []
    # drop the helper call: _slots is no longer restored
    broken = src.replace("                self._drop(cid)\n", "")
    hits = findings_for(broken, SERVICE, "FL-DUR-UNWIND")
    assert len(hits) == 1 and "'_slots'" in hits[0].message


def test_dur_torn_same_class_fsync_helper_is_an_fsync_point():
    src = """
    import os
    class Log:
        def __init__(self, path):
            self._file = open(path, "wb")  # durable-handle: single-record
        def flush(self):
            self._file.flush()
            os.fsync(self._file.fileno())
        def append(self, head, body):
            self._file.write(head)
            self.flush()
            self._file.write(body)
            self.flush()
    """
    assert findings_for(src, SERVICE, "FL-DUR-TORN") == []
    broken = src.replace("            self.flush()\n"
                         "            self._file.write(body)",
                         "            self._file.write(body)")
    hits = findings_for(broken, SERVICE, "FL-DUR-TORN")
    assert len(hits) == 1 and "torn record" in hits[0].message


# -- project rule: FL-DUR-SEAM -----------------------------------------------


def _write_seam_tree(root, faults_body, service_body):
    pkg = root / "fluidframework_tpu"
    (pkg / "testing").mkdir(parents=True)
    (pkg / "service").mkdir()
    (pkg / "testing" / "faults.py").write_text(textwrap.dedent(faults_body))
    (pkg / "service" / "x.py").write_text(textwrap.dedent(service_body))


def test_dur_seam_positive(tmp_path):
    _write_seam_tree(tmp_path, """
        SITES = {
            "shard.kill": "kill a shard host",
            "oplog.lost": "drop an oplog append",
        }
        SCHEDULED_SITES = ("shard.kill", "client.stall")
    """, """
        def hurt(faults):
            faults.fire("shard.kill")
            faults.fire("proc.vanish")
    """)
    msgs = {f.message for f in analyze(tmp_path) if f.rule == "FL-DUR-SEAM"}
    assert any("'proc.vanish' is fired here but not registered" in m
               for m in msgs), msgs
    assert any("'oplog.lost' is armed nowhere" in m for m in msgs), msgs
    assert any("'client.stall' is not a SITES key" in m for m in msgs), msgs


def test_dur_seam_negative(tmp_path):
    _write_seam_tree(tmp_path, """
        SITES = {
            "shard.kill": "kill a shard host",
            "oplog.lost": "drop an oplog append",
        }
        SCHEDULED_SITES = ("shard.kill",)
    """, """
        def hurt(faults):
            faults.fire("oplog.lost")
            for site in ("shard.kill",):
                faults.due(site)
    """)
    assert [f for f in analyze(tmp_path) if f.rule == "FL-DUR-SEAM"] == []


# -- project rule: FL-DUR-GATE -----------------------------------------------


def _write_gate_tree(root, gates_body, service_body):
    pkg = root / "fluidframework_tpu" / "service"
    pkg.mkdir(parents=True)
    (pkg / "gates.py").write_text(textwrap.dedent(gates_body))
    (pkg / "x.py").write_text(textwrap.dedent(service_body))


def test_dur_gate_positive(tmp_path):
    _write_gate_tree(tmp_path, """
        GATES = {
            "Catchup.Cache": "on",
            "Catchup.Ghost": 1,
        }
    """, """
        def read(config):
            config.get_str("Catchup.Cache", "on")
            config.get_int("Server.Unknown", 1)
    """)
    msgs = {f.message for f in analyze(tmp_path) if f.rule == "FL-DUR-GATE"}
    assert any("'Server.Unknown' is read here but not registered" in m
               for m in msgs), msgs
    assert any("'Catchup.Ghost' is never read" in m for m in msgs), msgs


def test_dur_gate_negative(tmp_path):
    _write_gate_tree(tmp_path, """
        GATES = {
            "Catchup.Cache": "on",
            "Server.DrainRetryAfter": 0.5,
        }
    """, """
        def read(config):
            config.get_str("Catchup.Cache", "on")
            config.get_float("Server.DrainRetryAfter", 0.5)
    """)
    assert [f for f in analyze(tmp_path) if f.rule == "FL-DUR-GATE"] == []


# -- project rules: FL-ERR-CODE / FL-ERR-RAISE / FL-ERR-RETRY ------------------


def _write_err_tree(root, errors_body, service_body):
    pkg = root / "fluidframework_tpu"
    (pkg / "protocol").mkdir(parents=True)
    (pkg / "service").mkdir()
    (pkg / "protocol" / "errors.py").write_text(textwrap.dedent(errors_body))
    (pkg / "service" / "x.py").write_text(textwrap.dedent(service_body))


def test_err_code_positive(tmp_path):
    _write_err_tree(tmp_path, """
        WIRE_ERRORS = {
            "throttled": {"channel": "nack"},
            "epochMismatch": {"channel": "frame"},
            "ghostCode": {"channel": "frame"},
        }
        EXCEPTIONS = {}
    """, """
        def reply(err):
            if err.code == "mystery":
                return {"ok": False, "code": "freeLancer"}
            return {"ok": False, "code": "epochMismatch"}
    """)
    msgs = {f.message for f in analyze(tmp_path)
            if f.rule == "FL-ERR-CODE"}
    assert any("'freeLancer' is produced here but not registered" in m
               for m in msgs), msgs
    assert any("'mystery' is handled here but not registered" in m
               for m in msgs), msgs
    assert any("'ghostCode' is produced nowhere" in m for m in msgs), msgs
    assert any("'epochMismatch' is produced but never handled" in m
               for m in msgs), msgs


def test_err_code_negative(tmp_path):
    _write_err_tree(tmp_path, """
        WIRE_ERRORS = {
            "throttled": {"channel": "nack"},
            "epochMismatch": {"channel": "frame"},
        }
        EXCEPTIONS = {}
    """, """
        def reply(err):
            if err.code == "epochMismatch":
                return {"ok": False, "code": "epochMismatch"}
            return {"ok": False, "code": "throttled"}
    """)
    assert [f for f in analyze(tmp_path)
            if f.rule == "FL-ERR-CODE"] == []


def test_err_raise_positive(tmp_path):
    _write_err_tree(tmp_path, """
        WIRE_ERRORS = {
            "throttled": {"channel": "nack"},
            "epochMismatch": {"channel": "frame"},
        }
        EXCEPTIONS = {}
    """, """
        def pace():
            raise NackError("busy", code="fluxCapacitor")

        def fence():
            raise NackError("stale", code="epochMismatch")
    """)
    msgs = {f.message for f in analyze(tmp_path)
            if f.rule == "FL-ERR-RAISE"}
    assert any("free-string code 'fluxCapacitor'" in m for m in msgs), msgs
    assert any("'epochMismatch', a frame-channel code" in m
               for m in msgs), msgs


def test_err_raise_negative(tmp_path):
    _write_err_tree(tmp_path, """
        WIRE_ERRORS = {
            "throttled": {"channel": "nack"},
        }
        EXCEPTIONS = {}
    """, """
        def pace():
            raise NackError("busy", code="throttled")
    """)
    assert [f for f in analyze(tmp_path)
            if f.rule == "FL-ERR-RAISE"] == []


def test_err_retry_positive(tmp_path):
    _write_err_tree(tmp_path, """
        WIRE_ERRORS = {}
        EXCEPTIONS = {
            "RpcTransportError": {"retry": "transport"},
            "ConnectionLostError": {"retry": "reconnect",
                                    "parent": "RpcTransportError"},
        }
    """, """
        def call(policy, op):
            return policy.run(
                operation=op,
                retry_on=(RpcTransportError, OSError),
            )
    """)
    msgs = {f.message for f in analyze(tmp_path)
            if f.rule == "FL-ERR-RETRY"}
    assert any("reconnect-class exception 'ConnectionLostError'" in m
               and "absent from no_retry" in m for m in msgs), msgs


def test_err_retry_negative(tmp_path):
    _write_err_tree(tmp_path, """
        WIRE_ERRORS = {}
        EXCEPTIONS = {
            "RpcTransportError": {"retry": "transport"},
            "ConnectionLostError": {"retry": "reconnect",
                                    "parent": "RpcTransportError"},
        }
    """, """
        def call(policy, op):
            return policy.run(
                operation=op,
                retry_on=(RpcTransportError, OSError),
                no_retry=(ConnectionLostError,),
            )
    """)
    assert [f for f in analyze(tmp_path)
            if f.rule == "FL-ERR-RETRY"] == []


# -- fluidshape: FL-KERN-BLOCK behavior ---------------------------------------


def test_kern_block_annotation_accepts_unprovable_dim():
    src = """
    from jax.experimental import pallas as pl
    def _round_up(n, mult):
        return ((n + mult - 1) // mult) * mult
    def fold(x, Sp):
        return pl.BlockSpec((8, Sp), lambda d: (d, 0))  # block-rule: _round_up
    """
    assert findings_for(src, OPS, "FL-KERN-BLOCK") == []


def test_kern_block_annotation_typo_is_a_finding():
    # a typo'd '# block-rule:' must not silently exempt the dim
    src = """
    from jax.experimental import pallas as pl
    def _round_up(n, mult):
        return ((n + mult - 1) // mult) * mult
    def fold(x, Sp):
        return pl.BlockSpec((8, Sp), lambda d: (d, 0))  # block-rule: _round_upp
    """
    hits = findings_for(src, OPS, "FL-KERN-BLOCK")
    assert len(hits) == 2  # the bad annotation AND the unproven dim
    assert any("no recognized rounding helper" in f.message for f in hits)


def test_kern_block_proven_violation_fires_despite_annotation():
    # annotations excuse what the rule cannot prove, never what it can
    src = """
    from jax.experimental import pallas as pl
    def _round_up(n, mult):
        return ((n + mult - 1) // mult) * mult
    def fold(x):
        return pl.BlockSpec((8, 100), lambda d: (d, 0))  # block-rule: _round_up
    """
    hits = findings_for(src, OPS, "FL-KERN-BLOCK")
    assert len(hits) == 1 and "literal 100" in hits[0].message


def test_kern_block_tuple_helper_route_accepted():
    # the pallas_fold shape: dims unpacked from a tuple-returning wrapper
    # around the canonical round-up, consts aliased locally, grid algebra
    # over rounded names
    src = """
    import jax
    from jax.experimental import pallas as pl
    DOC_BLOCK = 8
    LANE = 128
    def _round_up(n, mult):
        return ((n + mult - 1) // mult) * mult
    def _padded_dims(D, S):
        return (_round_up(max(D, 1), DOC_BLOCK),
                _round_up(max(S, 1), LANE))
    def fold(kernel, x, D, S):
        Dp, Sp = _padded_dims(D, S)
        B = DOC_BLOCK
        row = pl.BlockSpec((B, Sp), lambda d: (d, 0))
        return pl.pallas_call(kernel, grid=(Dp // B,), in_specs=[row])
    """
    assert findings_for(src, OPS, "FL-KERN-BLOCK") == []


def test_kern_block_wrong_position_rounding_fires():
    # a dim rounded to the SUBLANE multiple used in the lane position is
    # a proven violation — 8 does not divide 128
    src = """
    from jax.experimental import pallas as pl
    DOC_BLOCK = 8
    LANE = 128
    def _round_up(n, mult):
        return ((n + mult - 1) // mult) * mult
    def _padded_dims(D, S):
        return (_round_up(max(D, 1), DOC_BLOCK),
                _round_up(max(S, 1), LANE))
    def fold(x, D, S):
        Dp, Sp = _padded_dims(D, S)
        return pl.BlockSpec((8, Dp), lambda d: (d, 0))
    """
    hits = findings_for(src, OPS, "FL-KERN-BLOCK")
    assert len(hits) == 1
    assert "rounded to multiples of 8, not of 128" in hits[0].message


def test_kern_block_is_interpret_mode_blind():
    # interpret=True accepts blocks Mosaic rejects — the r05 failure.
    # The rule must fire regardless of the interpret kwarg.
    src = """
    from jax.experimental import pallas as pl
    def fold(kernel, x, D):
        return pl.pallas_call(kernel, grid=(D // 8,), interpret=True)
    """
    hits = findings_for(src, OPS, "FL-KERN-BLOCK")
    assert len(hits) == 1 and "grid extent" in hits[0].message


# -- fluidshape: FL-KERN-NARROW behavior --------------------------------------


def test_kern_narrow_bound_annotation_accepted():
    src = """
    import numpy as np
    I16_LIMIT = 32766
    def pack(vals):
        return vals.astype(np.int16)  # bound: I16_LIMIT
    """
    assert findings_for(src, OPS, "FL-KERN-NARROW") == []


def test_kern_narrow_bound_annotation_typo_is_a_finding():
    src = """
    import numpy as np
    I16_LIMIT = 32766
    def pack(vals):
        return vals.astype(np.int16)  # bound: I16_LIMIT_TYPO
    """
    hits = findings_for(src, OPS, "FL-KERN-NARROW")
    assert len(hits) == 1
    assert "references no bound guard" in hits[0].message


def test_kern_narrow_dtype_compare_is_a_guard():
    # relayout of an ALREADY-narrow buffer narrows nothing
    src = """
    import numpy as np
    def relayout(buf):
        if buf.dtype != np.int16:
            return None
        return np.ascontiguousarray(buf, np.int16)
    """
    assert findings_for(src, OPS, "FL-KERN-NARROW") == []


def test_kern_narrow_accumulation_on_narrow_lanes_fires():
    src = """
    import numpy as np
    def total(vals):
        packed = vals.astype(np.int16)
        return packed.sum()
    """
    hits = findings_for(src, OPS, "FL-KERN-NARROW")
    assert any("accumulating op on narrow lanes 'packed'" in f.message
               for f in hits)


def test_kern_narrow_iinfo_is_a_guard():
    src = """
    import numpy as np
    def pack(vals):
        info = np.iinfo(np.int16)
        ok = vals.max() <= info.max
        return vals.astype(np.int16) if ok else vals
    """
    assert findings_for(src, OPS, "FL-KERN-NARROW") == []


# -- fluidshape: FL-KERN-BUCKET behavior --------------------------------------


def test_kern_bucket_annotation_accepted():
    src = """
    import jax
    @jax.jit
    def _fold(x, n):
        return x[:n]
    def run(x, docs):
        return _fold(x, len(docs))  # bucketed-by: next_bucket
    """
    assert findings_for(src, OPS, "FL-KERN-BUCKET") == []


def test_kern_bucket_annotation_typo_is_a_finding():
    src = """
    import jax
    @jax.jit
    def _fold(x, n):
        return x[:n]
    def run(x, docs):
        return _fold(x, len(docs))  # bucketed-by: next_bucket_typo
    """
    hits = findings_for(src, OPS, "FL-KERN-BUCKET")
    assert len(hits) == 2  # the bad annotation AND the unrouted shape
    assert any("no recognized bucket or rounding helper" in f.message
               for f in hits)


def test_kern_bucket_taint_flows_through_names():
    # D = len(docs) is dirty; rebinding through the ladder cleans it
    src = """
    import jax
    from .interning import next_bucket
    @jax.jit
    def _fold(x, n):
        return x[:n]
    def dirty(x, docs):
        D = len(docs)
        return _fold(x, D)
    def clean(x, docs):
        D = next_bucket(len(docs))
        return _fold(x, D)
    """
    hits = findings_for(src, OPS, "FL-KERN-BUCKET")
    assert len(hits) == 1 and "in dirty()" in hits[0].message


def test_kern_bucket_jit_factory_calls_checked():
    # the lru-cached factory idiom: factory(...)(args) reaches a jit
    src = """
    import jax
    import functools
    @functools.lru_cache(maxsize=8)
    def _fold_fn(static):
        return jax.jit(lambda x, n: x[:n])
    def run(x, docs):
        return _fold_fn(True)(x, len(docs))
    """
    hits = findings_for(src, OPS, "FL-KERN-BUCKET")
    assert len(hits) == 1 and "_fold_fn" in hits[0].message


# -- fluidshape: FL-KERN-PAD behavior -----------------------------------------


def test_kern_pad_masked_by_annotation_accepted():
    src = """
    import jax.numpy as jnp
    def digest(x, mask):
        plane = jnp.pad(x, ((0, 3),))
        return plane.sum()  # masked-by: mask
    """
    assert findings_for(src, OPS, "FL-KERN-PAD") == []


def test_kern_pad_masked_by_typo_is_a_finding():
    src = """
    import jax.numpy as jnp
    def digest(x, mask):
        plane = jnp.pad(x, ((0, 3),))
        return plane.sum()  # masked-by: maskk
    """
    hits = findings_for(src, OPS, "FL-KERN-PAD")
    assert len(hits) == 2  # the bad annotation AND the unmasked reduce
    assert any("no name" in f.message for f in hits)


def test_kern_pad_mask_reassignment_clears():
    src = """
    import jax.numpy as jnp
    def digest(x, mask):
        plane = jnp.pad(x, ((0, 3),))
        plane = jnp.where(mask, plane, 0)
        return plane.sum()
    """
    assert findings_for(src, OPS, "FL-KERN-PAD") == []


def test_kern_pad_inline_chain_fires():
    src = """
    import jax.numpy as jnp
    def digest(x):
        return jnp.pad(x, ((0, 3),)).sum()
    """
    hits = findings_for(src, OPS, "FL-KERN-PAD")
    assert len(hits) == 1 and "reaches reduction 'sum'" in hits[0].message


# -- project rule: FL-KERN-FAMILY ---------------------------------------------


def _write_family_tree(root, pipeline_body, shard_body):
    ops = root / "fluidframework_tpu" / "ops"
    par = root / "fluidframework_tpu" / "parallel"
    ops.mkdir(parents=True)
    par.mkdir(parents=True)
    (ops / "family.py").write_text(textwrap.dedent("""
        from dataclasses import dataclass
        @dataclass(frozen=True)
        class KernelFamily:
            name: str
            pack: object
            dispatch: object
            make_pad: object = None
            pad_token: object = None
            dispatch_sharded: object = None
    """))
    (ops / "pipeline.py").write_text(textwrap.dedent(pipeline_body))
    (par / "shard.py").write_text(textwrap.dedent(shard_body))


def test_kern_family_positive(tmp_path):
    _write_family_tree(tmp_path, """
        from .family import KernelFamily
        STAGE_KEYS = ("pack", "upload", "dispatch", "download", "extract")
        def seed_stage(stage):
            return stage
        FAM = KernelFamily(
            name="mt", pack=object(),
            make_pad=None, pad_token=object(),
            dispatch_sharded=object(), chunk_tag=object(),
        )
    """, """
        def replay_sharded(stage):
            return stage
    """)
    msgs = {f.message for f in analyze(tmp_path)
            if f.rule == "FL-KERN-FAMILY"}
    assert any("omits descriptor hook 'dispatch'" in m for m in msgs), msgs
    assert any("unknown hook 'chunk_tag'" in m for m in msgs), msgs
    assert any("mesh hook 'make_pad' is None" in m for m in msgs), msgs
    assert any("diverges from the canonical stage schema" in m
               for m in msgs), msgs
    assert any("mesh twin never seeds" in m for m in msgs), msgs


def test_kern_family_negative(tmp_path):
    _write_family_tree(tmp_path, """
        from .family import KernelFamily
        STAGE_KEYS = ("pack", "upload", "dispatch", "device_wait",
                      "download", "extract")
        def seed_stage(stage):
            return stage
        FAM = KernelFamily(
            name="mt", pack=object(), dispatch=object(),
            make_pad=object(), pad_token=object(),
            dispatch_sharded=object(),
        )
    """, """
        from ..ops.pipeline import seed_stage
        def replay_sharded(stage):
            return seed_stage(stage)
    """)
    assert [f for f in analyze(tmp_path)
            if f.rule == "FL-KERN-FAMILY"] == []


# -- registry meta-coverage ----------------------------------------------------


def test_registry_fully_self_tested():
    """Every registered rule must carry at least one positive (fires)
    and one negative (stays quiet) self-test: module rules through a
    MODULE_RULE_FIXTURES pair, project rules through named
    test_<slug>_positive/negative functions.  A future rule landing
    without tests fails HERE, not silently in production."""
    from tools.fluidlint import all_rules
    from tools.fluidlint.core import ProjectRule

    rules = all_rules()
    module_ids = {n for n, r in rules.items()
                  if not isinstance(r, ProjectRule)}
    missing = sorted(module_ids - set(MODULE_RULE_FIXTURES))
    assert not missing, (
        f"module rules without a (positive, negative) fixture pair in "
        f"MODULE_RULE_FIXTURES: {missing}")
    unknown = sorted(set(MODULE_RULE_FIXTURES) - module_ids)
    assert not unknown, f"fixtures for unregistered rules: {unknown}"
    for rule_id in sorted(set(rules) - module_ids):
        slug = rule_id.lower().replace("fl-", "", 1).replace("-", "_")
        for suffix in ("positive", "negative"):
            assert f"test_{slug}_{suffix}" in globals(), (
                f"{rule_id}: project rule needs a test_{slug}_{suffix}")


# -- baseline rule-id hygiene --------------------------------------------------


def test_rule_hygiene_flags_unregistered_rule_id():
    from tools.fluidlint import baseline_rule_hygiene
    problems = baseline_rule_hygiene([
        {"rule": "FL-GONE-RULE", "path": "x.py", "message": "m",
         "reason": "r"}])
    assert problems and "not registered" in problems[0]
    assert baseline_rule_hygiene([
        {"rule": "FL-DET-CLOCK", "path": "x.py", "message": "m",
         "reason": "r"}]) == []


def test_check_baseline_flags_unregistered_rule_id(tmp_path, capsys):
    from tools.fluidlint.cli import main
    _clock_violation_tree(tmp_path)
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({"version": 1, "suppressions": [
        {"rule": "FL-GONE-RULE",
         "path": "fluidframework_tpu/loader/bad.py",
         "message": "m", "reason": "reviewed"}]}))
    assert main(["--root", str(tmp_path), "--baseline", str(bp),
                 "--check-baseline"]) == 1
    assert "not registered" in capsys.readouterr().out


def test_unregistered_rule_entry_fails_even_under_rules_filter(tmp_path):
    # --rules filtering ignores entries of UNSELECTED rules, but an
    # UNREGISTERED rule id is dead weight on every run: the hygiene
    # check consults the full, unfiltered registry and baseline.
    from tools.fluidlint.cli import main
    pkg = tmp_path / "fluidframework_tpu" / "loader"
    pkg.mkdir(parents=True)
    (pkg / "ok.py").write_text("X = 1\n")
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({"version": 1, "suppressions": [
        {"rule": "FL-GONE-RULE",
         "path": "fluidframework_tpu/loader/ok.py",
         "message": "m", "reason": "reviewed"}]}))
    assert main(["--root", str(tmp_path), "--baseline", str(bp),
                 "--rules", "FL-RACE"]) == 1
