"""Golden-file back-compat: committed summary bytes must stay loadable and
re-summarize byte-identically forever (the reference's snapshot-test
capability, SURVEY.md §4).

``tests/golden/container_v1.json`` holds a mixed-channel container summary
(string with an obliterate in-window, map, matrix, tree, an accepted quorum
proposal), its digest, a sequenced op tail, and the digest after replaying
the tail.  If ANY codec change breaks these bytes, this test fails — format
changes must bump the version and keep an N-1 read path instead.
"""

import json
import os

import pytest

from fluidframework_tpu.protocol.messages import SequencedMessage
from fluidframework_tpu.protocol.summary import (
    SUMMARY_WIRE_VERSION,
    tree_from_obj,
    tree_to_obj,
)
from fluidframework_tpu.dds.tree import SharedTree
from fluidframework_tpu.runtime.container import ContainerRuntime

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "container_v1.json")


@pytest.fixture(scope="module")
def golden():
    with open(GOLDEN) as f:
        return json.load(f)


def test_golden_summary_reloads_byte_identically(golden):
    tree = tree_from_obj(golden["summary"])
    assert tree.digest() == golden["summary_digest"], (
        "committed summary bytes no longer reproduce their digest — a "
        "codec change broke back-compat"
    )
    runtime = ContainerRuntime()
    loaded_seq = runtime.load(tree)
    assert loaded_seq == golden["summary_seq"]
    # a freshly produced summary of the loaded state is byte-identical
    assert runtime.summarize().digest() == golden["summary_digest"]


def test_golden_tail_replay_reaches_committed_digest(golden):
    runtime = ContainerRuntime()
    runtime.load(tree_from_obj(golden["summary"]))
    for d in golden["tail"]:
        runtime.process(SequencedMessage.from_dict(d))
    assert runtime.summarize().digest() == golden["final_digest"]
    assert runtime.get_datastore("ds").get_channel("text").text == \
        golden["final_text"]
    # quorum proposal survived the round trip
    assert runtime.quorum_proposals.get("code") == {"pkg": "golden", "v": 1}


def test_golden_wire_roundtrip_is_stable(golden):
    tree = tree_from_obj(golden["summary"])
    again = tree_from_obj(tree_to_obj(tree))
    assert again.digest() == golden["summary_digest"]


# -- version skew --------------------------------------------------------------


def test_newer_summary_format_is_refused(golden):
    tree = tree_from_obj(golden["summary"])
    meta = json.loads(tree.blob_bytes(".metadata"))
    meta["format"] = ContainerRuntime.SUMMARY_FORMAT_VERSION + 1
    tree.add_json_blob(".metadata", meta)
    with pytest.raises(ValueError, match="newer than supported"):
        ContainerRuntime().load(tree)


def test_older_versionless_summary_still_loads(golden):
    """The N-1 read path: a summary written before version stamping
    (no 'format' key) loads as version 1."""
    tree = tree_from_obj(golden["summary"])
    meta = json.loads(tree.blob_bytes(".metadata"))
    meta.pop("format")
    tree.add_json_blob(".metadata", meta)
    runtime = ContainerRuntime()
    runtime.load(tree)
    assert runtime.ref_seq == golden["summary_seq"]


def test_newer_batch_wire_version_is_refused():
    from fluidframework_tpu.runtime.op_pipeline import (
        BATCH_WIRE_VERSION,
        check_batch_version,
    )

    check_batch_version({"type": "groupedBatch", "ops": []})  # absent = v1
    check_batch_version({"type": "groupedBatch", "v": 1, "ops": []})
    with pytest.raises(ValueError, match="newer than supported"):
        check_batch_version(
            {"type": "groupedBatch", "v": BATCH_WIRE_VERSION + 1, "ops": []}
        )


def test_newer_summary_wire_version_is_refused(golden):
    obj = dict(golden["summary"])
    obj["v"] = SUMMARY_WIRE_VERSION + 1
    with pytest.raises(ValueError, match="newer than supported"):
        tree_from_obj(obj)


# --- tree limbo format golden (round 3) --------------------------------------

LIMBO_GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                            "tree_limbo_v1.json")


@pytest.fixture(scope="module")
def limbo_golden():
    with open(LIMBO_GOLDEN) as f:
        return json.load(f)


def test_limbo_golden_reloads_byte_identically(limbo_golden):
    """The committed limbo-carrying tree summary (a node whose enclosing
    tombstone expired, still rescuable by id) must load and re-summarize
    to the same bytes forever."""
    tree = tree_from_obj(limbo_golden["summary"])
    assert tree.digest() == limbo_golden["summary_digest"], (
        "committed limbo summary bytes no longer reproduce their digest"
    )
    replica = SharedTree("t")
    replica.load(tree)
    assert replica._last_seq == limbo_golden["summary_seq"]
    assert replica.summarize().digest() == limbo_golden["summary_digest"]


def test_limbo_golden_tail_rescue_reaches_committed_digest(limbo_golden):
    """Replaying the committed tail (the rescue move) on the reloaded
    summary reaches the committed final digest — limbo nodes stay
    addressable across summarize/reload."""
    replica = SharedTree("t")
    replica.load(tree_from_obj(limbo_golden["summary"]))
    for msg_dict in limbo_golden["tail"]:
        replica.process(SequencedMessage.from_dict(msg_dict), local=False)
    assert replica.summarize().digest() == limbo_golden["final_digest"]
